#!/usr/bin/env python3
"""Diff two bench result files — the regression gate for BENCH_*.json.

``bench.py`` emits its per-row numbers as a ``{"details": {row: {...}}}``
JSON line on stderr; the repo's archived ``BENCH_r*.json`` artifacts wrap
that whole invocation as ``{"n", "cmd", "rc", "tail", "parsed"}`` with
the details line embedded somewhere inside the ``tail`` string. This
tool accepts EITHER form on either side (plus a bare row-mapping), so

    python hack/bench_diff.py BENCH_r05.json BENCH_r06.json

compares two archived rounds and

    python hack/bench_diff.py old.json new.json --strict

gates a fresh run against a baseline in CI (also reachable as
``python hack/verify.py --bench-diff OLD NEW``).

Three classes of finding, each printed as one line:

- ``regression``: a row's p50 latency (``p50_s``, falling back to
  ``xla_s`` on rows without percentiles) grew by more than
  ``--threshold`` (default 15%);
- ``parity``: a parity bit (``placements_equal_serial``,
  ``placements_equal_full_cycle``, or the kill-drill acceptance bit
  ``p50_within_lease_window`` on ``federation_kill_mttr``) that was
  true in OLD is false or gone in NEW — the device solver stopped
  matching its oracle (or failover MTTR left its lease window), which
  no latency number excuses;
- ``compiles``: a compile-budget change — ``measured_compiles`` (or
  ``warm_encode_compiles``) grew, meaning a row started paying
  trace+compile inside its measured repeats.

Rows present on only one side are reported (``added``/``removed``) but
only ``removed`` counts as a finding: a vanished row is a silently
narrowed bench. Improvements are listed informationally.

Device-phase and fleet telemetry columns (``solve_device_s``,
``pipeline_overlap_fraction``, ``arena_hbm_watermark_bytes``, and any
``fleet_*`` column) are understood but NEVER flagged: solve_device_s is
a sub-phase of ``solve_s`` (already covered by the latency gate), the
overlap fraction and HBM watermark are descriptive telemetry whose
"right" value is config-dependent, and fleet columns are aggregator
state rather than per-row latency. Changes in them print as ``[info]``
lines and do not affect the exit code, even under ``--strict``.

Wire-transport columns (ISSUE 17) are the opposite: they ARE the
product of their rows, so they gate. A row carrying a ``wire_runs``
sub-list (the federation scale-out's v1-vs-v2 transport ladder) is
expanded into one pseudo-row per run, named
``<row>.wire_v<protocol>_n<shards>``, and within those rows
``binds_per_s`` and ``txn_batch*`` regress when they SHRINK by more
than ``--threshold`` while ``wire_bytes_per_bind`` and
``backend_rtt_*`` regress when they GROW — a v2 transport that slid
back to v1 throughput or v1 byte volume is a ``regression`` finding,
not an ``[info]`` line. Their ``exactly_once``/``union_parity`` bits
join the parity gate.

Admission-storm columns (ISSUE 18) gate the same way with their own
directions: ``storm_high_p99_s`` (the protected lane's tail under
overload) and ``storm_mttr_s`` (kill-cell recovery) regress when they
grow, ``storm_goodput_pods_per_s`` when it shrinks; ``storm_shed_*``
counts are ``[info]`` (shed volume is a policy outcome of offered
load, pinned by the row's own ``ok`` bit rather than diffed).

Node-class compression columns (ISSUE 20) split the same way:
``compression_ratio`` (valid nodes per node class on the compressed
solve) regresses when it SHRINKS — a workload row whose duplication
collapsed means the class key picked up an accidental splitter and the
solve cost silently reverted toward per-node scaling. ``class_count``
and the solve-cost split (``class_group_s`` host regroup vs
``class_kernel_s`` device solve, plus ``class_splits``) are ``[info]``:
they describe where the time went, and gating them would let a row
"pass" by shifting cost between phases while p50 — which still gates
on its own — tells the truth.

``--json`` emits one machine-readable summary line; ``--strict`` exits
nonzero when any finding fired (default exit is 0 — informational).
"""

from __future__ import annotations

import argparse
import json
import re
import sys

# latency key preference per row: tail-honest median first
_LATENCY_KEYS = ("p50_s", "xla_s")
# true->anything-else is a finding; covers placement parity, the
# kill-drill MTTR acceptance bit (p50 <= lease TTL + renew period) and
# the wire pseudo-rows' correctness bits
_PARITY_KEYS = (
    "placements_equal_serial",
    "placements_equal_full_cycle",
    "placements_equal_uncompressed",
    "p50_within_lease_window",
    "exactly_once",
    "union_parity",
)
_COMPILE_KEYS = ("measured_compiles", "warm_encode_compiles")
# never-flagged telemetry columns (see module docstring)
_INFO_KEYS = (
    "solve_device_s",
    "pipeline_overlap_fraction",
    "arena_hbm_watermark_bytes",
)
# wire-transport columns (see module docstring): gated, with direction.
# lower-better: bytes and round-trip latency; higher-better: throughput
# and txn coalescing depth (a batch mean collapsing to 1 means the v2
# path quietly degraded to per-gang writes).
_WIRE_LOWER = ("wire_bytes_per_bind",)
_WIRE_HIGHER = ("binds_per_s",)
# admission-storm columns (ISSUE 18): the protected lane's tail and the
# kill-cell MTTR regress when they GROW; storm goodput regresses when
# it SHRINKS. Shed counts are load-dependent policy outcomes (a faster
# solver sheds less at the same offered rate), so they print as [info]
# — the protected-lane zero-shed claim is asserted inside the row's
# own ``ok`` bit, not diffed across rounds.
_STORM_LOWER = ("storm_high_p99_s", "storm_mttr_s")
_STORM_HIGHER = ("storm_goodput_pods_per_s",)
# node-class compression (ISSUE 20): ratio shrink = the class key lost
# its duplication and the solve is drifting back to per-node cost;
# class_count / class_group_s / class_kernel_s / class_splits are the
# [info] solve-cost split (see module docstring).
_CLASS_HIGHER = ("compression_ratio",)


def _is_info_key(key: str) -> bool:
    return (key in _INFO_KEYS or key.startswith("fleet_")
            or key.startswith("storm_shed_") or key.startswith("class_"))


def _is_wire_lower(key: str) -> bool:
    return (key in _WIRE_LOWER or key in _STORM_LOWER
            or key.startswith("backend_rtt_"))


def _is_wire_higher(key: str) -> bool:
    return (key in _WIRE_HIGHER or key in _STORM_HIGHER
            or key in _CLASS_HIGHER or key.startswith("txn_batch"))


def _rows_from_obj(obj):
    """Extract the row mapping from any of the accepted shapes."""
    if not isinstance(obj, dict):
        return None
    if isinstance(obj.get("details"), dict):
        return obj["details"]
    if isinstance(obj.get("tail"), str):
        # driver wrapper: scan the captured output for the stderr
        # details line (bench.py prints exactly one such object)
        for line in obj["tail"].splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                inner = json.loads(line)
            except ValueError:
                continue
            if isinstance(inner, dict) and isinstance(
                inner.get("details"), dict
            ):
                return inner["details"]
        return _rows_from_fragment(obj["tail"])
    # bare mapping of row name -> row dict
    if obj and all(isinstance(v, dict) for v in obj.values()):
        return obj
    return None


def _rows_from_fragment(text: str) -> dict | None:
    """Recover rows from a FRONT-TRUNCATED details line: the archived
    wrappers keep only the trailing bytes of stderr, so the
    ``{"details": {`` prefix (and possibly the first row) may be cut
    off mid-object. Scan for ``"name": {...}`` pairs and keep every
    object that carries a bench latency key — partial first rows
    simply fail to decode and are skipped."""
    dec = json.JSONDecoder()
    rows = {}
    for m in re.finditer(r'"([A-Za-z0-9_./:-]+)":\s*\{', text):
        try:
            row, _ = dec.raw_decode(text, m.end() - 1)
        except ValueError:
            continue
        if isinstance(row, dict) and any(k in row for k in _LATENCY_KEYS):
            rows[m.group(1)] = row
    return rows or None


def _expand_wire_rows(rows: dict) -> dict:
    """Expand each row's ``wire_runs`` sub-list (the v1-vs-v2 transport
    ladder on the federation scale-out row) into first-class
    pseudo-rows named ``<row>.wire_v<protocol>_n<shards>`` so the
    per-key gates see every (protocol, shard-count) cell."""
    out = dict(rows)
    for name, row in rows.items():
        runs = row.get("wire_runs") if isinstance(row, dict) else None
        if not isinstance(runs, list):
            continue
        for run in runs:
            if not isinstance(run, dict):
                continue
            proto, shards = run.get("protocol"), run.get("shards")
            if proto is None or shards is None:
                continue
            out[f"{name}.wire_v{proto}_n{shards}"] = run
    return out


def load_rows(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        obj = json.load(fh)
    rows = _rows_from_obj(obj)
    if rows is None:
        raise SystemExit(
            f"bench_diff: {path}: no bench rows found (expected a "
            '{"details": ...} object, a BENCH_*.json wrapper whose tail '
            "embeds one, or a bare row mapping)"
        )
    return _expand_wire_rows(rows)


def _latency(row: dict):
    for k in _LATENCY_KEYS:
        v = row.get(k)
        if isinstance(v, (int, float)) and v > 0:
            return k, float(v)
    return None, None


def diff_rows(old: dict, new: dict, threshold: float) -> dict:
    findings = []
    improvements = []
    info = []
    added = sorted(set(new) - set(old))
    removed = sorted(set(old) - set(new))
    for name in removed:
        findings.append({
            "row": name, "kind": "removed",
            "msg": f"{name}: row present in OLD but missing from NEW "
                   "(bench coverage narrowed)",
        })
    for name in sorted(set(old) & set(new)):
        o, n = old[name], new[name]
        ok_key, ov = _latency(o)
        nk_key, nv = _latency(n)
        if ov is not None and nv is not None:
            delta = (nv - ov) / ov
            key = nk_key if nk_key == ok_key else f"{ok_key}->{nk_key}"
            if delta > threshold:
                findings.append({
                    "row": name, "kind": "regression",
                    "msg": f"{name}: {key} {ov:.4f}s -> {nv:.4f}s "
                           f"(+{delta:.1%}, threshold {threshold:.0%})",
                })
            elif delta < -threshold:
                improvements.append(
                    f"{name}: {key} {ov:.4f}s -> {nv:.4f}s ({delta:.1%})"
                )
        for k in _PARITY_KEYS:
            if o.get(k) is True and n.get(k) is not True:
                state = "flipped false" if k in n else "vanished"
                findings.append({
                    "row": name, "kind": "parity",
                    "msg": f"{name}: {k} {state} (was true in OLD)",
                })
        for k in _COMPILE_KEYS:
            oc, nc = o.get(k), n.get(k)
            if isinstance(nc, (int, float)) and nc > (
                oc if isinstance(oc, (int, float)) else 0
            ):
                findings.append({
                    "row": name, "kind": "compiles",
                    "msg": f"{name}: {k} {oc if oc is not None else 0} "
                           f"-> {nc} (measured repeats started compiling)",
                })
        for k in sorted(set(o) | set(n)):
            lower, higher = _is_wire_lower(k), _is_wire_higher(k)
            if not (lower or higher):
                continue
            ow, nw = o.get(k), n.get(k)
            if not isinstance(ow, (int, float)) or not isinstance(
                nw, (int, float)
            ) or ow <= 0:
                continue
            delta = (nw - ow) / ow
            worse = delta > threshold if lower else delta < -threshold
            better = delta < -threshold if lower else delta > threshold
            if worse:
                findings.append({
                    "row": name, "kind": "regression",
                    "msg": f"{name}: {k} {ow:g} -> {nw:g} ({delta:+.1%}, "
                           f"{'lower' if lower else 'higher'}-is-better, "
                           f"threshold {threshold:.0%})",
                })
            elif better:
                improvements.append(f"{name}: {k} {ow:g} -> {nw:g} ({delta:+.1%})")
        for k in sorted(set(o) | set(n)):
            if not _is_info_key(k):
                continue
            oi, ni = o.get(k), n.get(k)
            if oi == ni:
                continue
            info.append(f"{name}: {k} {oi} -> {ni}")
    return {
        "rows_old": len(old),
        "rows_new": len(new),
        "added": added,
        "removed": removed,
        "findings": findings,
        "improvements": improvements,
        "info": info,
        "ok": not findings,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff",
        description="Diff two bench result files (regressions, parity "
                    "flips, compile-budget changes).",
    )
    ap.add_argument("old", help="baseline bench JSON (details/wrapper/rows)")
    ap.add_argument("new", help="candidate bench JSON (same shapes accepted)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative p50 regression threshold (default 0.15)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one machine-readable summary line")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero when any finding fired")
    args = ap.parse_args(argv)

    summary = diff_rows(
        load_rows(args.old), load_rows(args.new), args.threshold
    )
    for f in summary["findings"]:
        print(f"bench_diff: [{f['kind']}] {f['msg']}")
    for line in summary["improvements"]:
        print(f"bench_diff: [improved] {line}")
    for line in summary["info"]:
        print(f"bench_diff: [info] {line}")
    for name in summary["added"]:
        print(f"bench_diff: [added] {name}: new row in NEW")
    print(
        "bench_diff:",
        "ok" if summary["ok"] else f"{len(summary['findings'])} finding(s)",
        f"({summary['rows_old']} -> {summary['rows_new']} rows,"
        f" threshold {args.threshold:.0%})",
    )
    if args.as_json:
        print(json.dumps(summary, sort_keys=True))
    return 1 if (args.strict and not summary["ok"]) else 0


if __name__ == "__main__":
    sys.exit(main())
