#!/usr/bin/env python3
"""Static verification gate — the role of the reference's
`make verify` (Makefile:14-18 -> hack/verify-gofmt.sh, verify-golint.sh,
verify-boilerplate.sh), for a Python/C++ tree.

Runs, in order:

1. `compileall` — every tracked .py must byte-compile (syntax gate);
2. `tabnanny` — no ambiguous indentation;
3. an AST linter (stdlib-only, because this image ships no ruff/mypy
   and installs are off): unused imports (F401), bare except (E722),
   `== None` / `!= None` comparisons — both operand sides — (E711),
   mutable default arguments (B006), f-strings without placeholders
   (F541);
4. the domain-aware analysis suite (python -m kube_batch_tpu.analysis):
   lock-discipline (KBT-L*), JAX hazards (KBT-J*), registry consistency
   (KBT-R*), snapshot escape (KBT-S*), lock-order/deadlock (KBT-D*),
   against the committed hack/lint-baseline.toml (reason-less entries
   always fail; stale entries fail under ``--strict``), then the
   trace-level program auditor (python -m kube_batch_tpu.analysis.trace,
   KBT-P*: jaxpr callbacks, f64 leaks, captured constants, donation,
   cross-tier signature drift) under JAX_PLATFORMS=cpu against
   hack/trace-baseline.toml; with ``--interleave``, also the
   interleaving model checker (python -m
   kube_batch_tpu.analysis.interleave, KBT-I*: every distinguishable
   thread schedule of the fixed streaming/takeover scenarios,
   counterexamples replayable by trace id) against
   hack/interleave-baseline.toml;
5. ruff + mypy when importable (CI images that carry them get the full
   gate; their absence degrades to the stdlib checks, loudly — unless
   ``--strict``, which makes a missing tool a FAILURE, so an image
   rebuild that silently drops ruff/mypy cannot turn the gate green);
   mypy covers api/, framework/, conf/ and recovery/;
6. the chaos smoke (kube_batch_tpu.faults.smoke): one injected fault per
   subsystem — solver, native boundary, cache write, watch hub, lease
   elector — plus a seeded cache-mutation-detector violation, each
   through a real scheduling path, asserting binds still land;
6b. the wire-codec self-check (python -m kube_batch_tpu.apis.wire
   --json): seeded property round-trips over every kind — binary
   (KBW2) and JSON framings must decode back to equal objects, deltas
   must patch old into new field-for-field, and the binary framing
   must not be larger than JSON on the aggregate corpus;
7. the encode-cache parity smoke (python -m kube_batch_tpu.ops.encode_cache):
   warm and 1%-node-churn encodes must be byte-identical to a fresh
   cold encode on a seeded snapshot (KBT_ENCODE_CACHE default-on),
   then the pipelined-cycle parity smoke (same module, ``--pipeline``):
   one seeded world scheduled with KBT_PIPELINE off and on must bind
   pod-for-pod identically, with the pipelined run's dispatch deferred
   through the fence and the arena ping-ponging its device banks;
8. the streaming smoke (python -m kube_batch_tpu.streaming --json):
   event-driven micro-cycles must bind every arrival AND place it on
   the same node a pure full-cycle twin picks (parity), with at least
   one micro-cycle actually taken;
9. the obs tracing smoke (python -m kube_batch_tpu.obs --json): a
   seeded two-shard federated run over live loopback backends with a
   forced stale-dispatch conflict must produce a complete span tree
   (check_tree clean) whose conflicted gang.bind joins the arbiter's
   store.bind spans in one trace (cross-process propagation over the
   backend headers), fsck-clean, with the JSONL + Chrome trace pair
   exported. ``--obs`` requests it explicitly; it runs by default;
10. the explain forensics smoke (python -m kube_batch_tpu.obs.explain
    --json): on a seeded cluster with one stuck gang per feasibility
    plane, the batched device forensics must match the serial twin
    byte-for-byte, report each gang's designed dominant reason and
    would-fit-if planes, and land those reasons on PodGroup conditions;
11. the fleet-aggregation smoke (python -m kube_batch_tpu.obs.fleet
    --json) at 2 and 4 shards: merged fleet percentiles must land
    within the sketch's declared relative-error bound of the pooled
    raw samples;
12. the admission smoke (python -m kube_batch_tpu.admission --json):
    the deterministic virtual-clock 5x-overload plant — with lanes +
    the fleet-SLO brownout ladder armed the protected lane must hold
    its tail SLO with zero shed while the unprotected OFF twin
    collapses, and every shed decision must carry Retry-After
    guidance;
13. the node-class compression smoke (python -m
    kube_batch_tpu.ops.class_solve --json): serial, uncompressed and
    KBT_CLASS_COMPRESS=1 schedules of a seeded pooled fleet must bind
    pod-for-pod identically across two cycles, with in-solve splits
    and second-cycle re-merges both exercised.

With ``--bench-diff OLD NEW``, two bench artifacts (fresh bench.py
output or archived BENCH_*.json wrappers) are regression-gated via
hack/bench_diff.py --strict: >15% p50 regressions, parity flips,
compile-budget changes and vanished rows all fail the gate. With
``--bench-diff`` and no paths, the two newest ``BENCH_*.json`` in the
repo root are auto-discovered (mtime order, name as tie-break) and
diffed oldest-of-the-pair -> newest.

With ``--chaos``, two more gates run: the chaos-marked pytest subset
(tests/test_faults.py + tests/test_recovery.py + tests/test_federation.py
— fault drills, the crash-consistent failover e2e, the conflict chaos
drill), and ``kube_batch_tpu.recovery.fsck`` against a seeded journal
fixture (a known half-confirmed WAL must fsck clean with the expected
orphan count, and ``--strict`` must gate on it); plus the real-clock
admission storm drill (``python -m kube_batch_tpu.admission --storm
--json --duration 4`` — the three-cell ON/OFF/KILL comparison: the
protected lane's tail held under 5x overload, the OFF twin measurably
worse, and a mid-storm shard kill recovered with zero journal
orphans).

With ``--federation``, the federation gate runs: the wire-path smoke
(``python -m kube_batch_tpu.federation --json`` — N schedulers over one
loopback store process, exactly-once binds, fsck-clean union placement,
parity with a single-scheduler twin), a seeded in-process
two-scheduler conflict drill whose loser must win its refresh-retry and
leave store truth fsck-clean, and the kill-and-adopt drill
(``python -m kube_batch_tpu.federation --json --kill-one`` — one of
four leased shard owners killed mid-``bind_many``; a survivor must
adopt the orphaned slot within the lease window, reconcile the dead
owner's journal, and finish every gang exactly once, fsck-clean), and
the streaming-federation smoke (``python -m kube_batch_tpu.federation
--json --streaming`` — shards on event-driven micro-cycles absorbing
peer binds as occupancy patches must reach parity with the classic
federated run, micro-cycles actually taken, exactly-once, fsck-clean,
pumps and listeners shut down clean).

Exit 0 iff every gate is clean.
Usage:  python hack/verify.py [--strict] [--chaos] [--federation]
                              [--obs] [--interleave] [--json]
                              [--bench-diff [OLD.json NEW.json]]

``--json`` appends one machine-readable summary line to stdout
(per-gate pass/fail + finding counts) so bench/CI can record the
gate's state in artifacts.

CI/the deployment image run ``--strict`` (the Dockerfile installs ruff +
mypy via the ``dev`` extra); the bare container, which cannot install
packages, runs the default lenient mode.
"""

from __future__ import annotations

import ast
import compileall
import io
import os
import subprocess
import sys
import tabnanny
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = ["kube_batch_tpu", "tests", "bench.py", "__graft_entry__.py", "hack"]

# Names a module may import without using (re-export / side-effect
# registration idioms used deliberately in this codebase).
SIDE_EFFECT_IMPORTS = {"kube_batch_tpu.actions", "kube_batch_tpu.plugins"}


def py_files() -> list[str]:
    out = []
    for t in TARGETS:
        p = os.path.join(REPO, t)
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d not in ("__pycache__",)]
            out.extend(os.path.join(root, f) for f in files if f.endswith(".py"))
    return sorted(out)


class _Lint(ast.NodeVisitor):
    """The checks: F401 / E722 / E711 / B006 / F541."""

    def __init__(self, path: str, tree: ast.AST, source: str) -> None:
        self.path = path
        self.problems: list[tuple[int, str]] = []
        self.imported: dict[str, tuple[int, str]] = {}  # name -> (line, full)
        self.used: set[str] = set()
        self.source = source
        self.visit(tree)
        self._flush_imports(tree)

    def _flush_imports(self, tree: ast.AST) -> None:
        exported = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        if isinstance(node.value, (ast.List, ast.Tuple)):
                            exported = {
                                e.value
                                for e in node.value.elts
                                if isinstance(e, ast.Constant)
                            }
        is_init = os.path.basename(self.path) == "__init__.py"
        for name, (line, full) in self.imported.items():
            if name in self.used or name in exported or full in SIDE_EFFECT_IMPORTS:
                continue
            if is_init:
                continue  # package __init__ re-exports are the point
            if name.startswith("_"):
                continue
            # a `# noqa` on the import line silences it, same as ruff
            src_line = self.source.splitlines()[line - 1]
            if "noqa" in src_line:
                continue
            self.problems.append((line, f"F401 unused import: {full}"))

    # -- imports ------------------------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        # TYPE_CHECKING blocks import names for quoted annotations the
        # runtime never loads — exempt them (ruff resolves the quoted
        # usage instead; the stdlib linter exempts the block).
        t = node.test
        if (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
            isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
        ):
            self.visit(t)  # the guard itself uses the TYPE_CHECKING name
            for n in node.orelse:
                self.visit(n)
            return
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            self.imported[name] = (node.lineno, a.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directive, not a binding
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            self.imported[name] = (node.lineno, f"{node.module}.{a.name}")

    # -- usage --------------------------------------------------------------
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    # -- checks -------------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.problems.append((node.lineno, "E722 bare except"))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # check BOTH sides of each comparison: `None == x` puts the
        # constant in node.left (or, chained, in the previous
        # comparator), which the comparators-only loop missed
        operands = [node.left] + list(node.comparators)
        for op, left, right in zip(node.ops, operands, operands[1:]):
            if isinstance(op, (ast.Eq, ast.NotEq)) and any(
                isinstance(o, ast.Constant) and o.value is None
                for o in (left, right)
            ):
                self.problems.append(
                    (node.lineno, "E711 comparison to None (use `is`)")
                )
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self.problems.append(
                    (d.lineno, "B006 mutable default argument")
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue) -> None:
        # visit the expression only: a format spec is itself a synthetic
        # JoinedStr and must not trip F541
        self.visit(node.value)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problems.append((node.lineno, "F541 f-string without placeholders"))
        self.generic_visit(node)


def run_ast_lint(files: list[str]) -> int:
    n = 0
    for path in files:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, path)
        except SyntaxError:
            continue  # compileall already reported it
        lint = _Lint(path, tree, source)
        for line, msg in sorted(lint.problems):
            rel = os.path.relpath(path, REPO)
            print(f"{rel}:{line}: {msg}")
            n += 1
    return n


def run_optional(tool: str, args: list[str]) -> int | None:
    """Run ruff/mypy when the image carries them; None = unavailable."""
    probe = subprocess.run(
        [sys.executable, "-m", tool, "--version"],
        capture_output=True,
    )
    if probe.returncode != 0:
        return None
    res = subprocess.run([sys.executable, "-m", tool, *args], cwd=REPO)
    return res.returncode


def seeded_journal_fixture(path: str) -> None:
    """A known WAL: 3 bind intents for one gang, first confirmed —
    exactly what a leader killed after 1 of 3 bulk writes leaves."""
    lines = [
        '{"rec":"intent","seq":1,"cycle":4,"op":"bind","gang":"default/g0","pod":"default/p0","node":"n0"}',
        '{"rec":"intent","seq":2,"cycle":4,"op":"bind","gang":"default/g0","pod":"default/p1","node":"n1"}',
        '{"rec":"intent","seq":3,"cycle":4,"op":"bind","gang":"default/g0","pod":"default/p2","node":"n0"}',
        '{"rec":"confirm","seq":1}',
    ]
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")


def run_chaos_gate(env: dict) -> bool:
    """--chaos: the chaos-marked test subset + fsck on a seeded journal.
    Returns True when clean."""
    import json
    import tempfile

    ok = True
    res = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests", "-q", "-m", "chaos",
            "-p", "no:cacheprovider",
        ],
        cwd=REPO, env=env,
    )
    if res.returncode != 0:
        print("verify: chaos test subset FAILED")
        ok = False
    with tempfile.TemporaryDirectory() as tmp:
        fixture = os.path.join(tmp, "seeded.wal")
        seeded_journal_fixture(fixture)
        res = subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.recovery.fsck", "--json", fixture],
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        summary = {}
        if res.returncode == 0:
            try:
                summary = json.loads(res.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                pass
        if (
            res.returncode != 0
            or summary.get("intents") != 3
            or summary.get("orphaned") != 2
            or summary.get("corrupt_lines") != 0
        ):
            print(f"verify: recovery.fsck on the seeded journal FAILED ({summary})")
            ok = False
        # --strict must refuse a journal with in-flight intents
        res = subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.recovery.fsck", "--strict", fixture],
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        if res.returncode != 1:
            print("verify: recovery.fsck --strict did not gate on orphans")
            ok = False
    return ok


# The seeded two-scheduler conflict drill: both caches snapshot the
# same store version, both dispatch onto ONE node — the second dispatch
# must lose its optimistic check and win the refresh-retry; store truth
# must end fsck-clean with all six pods bound.
_FED_DRILL = """
import json
from kube_batch_tpu.api.job_info import job_key
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache import ClusterStore
from kube_batch_tpu.federation import FederatedCache, fsck, shard_index
from kube_batch_tpu.testing import (
    build_node, build_pod, build_pod_group, build_queue, build_resource_list,
)

store = ClusterStore()
store.create_queue(build_queue("default"))
store.create_node(
    build_node("n0", build_resource_list(cpu=16, memory="16Gi", pods=64))
)
for g in ("ga", "gb"):
    store.create_pod_group(build_pod_group(g, min_member=3))
    for m in range(3):
        store.create_pod(build_pod(
            name=f"{g}-p{m}", group_name=g,
            req=build_resource_list(cpu=1, memory="512Mi"),
        ))
caches = {
    g: FederatedCache(
        store, shard=shard_index(job_key("default", g), 2), shards=2,
        shard_key="gang",
    )
    for g in ("ga", "gb")
}
for c in caches.values():
    c.snapshot()  # same version: the second dispatch conflicts for real
for g, c in caches.items():
    job = c.jobs[job_key("default", g)]
    pending = list(job.task_status_index[TaskStatus.PENDING].values())
    c.bind_many([(t, "n0") for t in pending])
violations = fsck(store)
bound = sum(1 for p in store.list("pods") if p.node_name)
ok = not violations and bound == 6
print(json.dumps({"ok": ok, "bound": bound, "fsck_violations": violations}))
raise SystemExit(0 if ok else 1)
"""


def run_federation_gate(env: dict) -> dict:
    """--federation: the wire-path smoke (python -m
    kube_batch_tpu.federation --json), the seeded in-process
    two-scheduler conflict drill above, and the kill-and-adopt drill
    (python -m kube_batch_tpu.federation --json --kill-one): kill one
    of four shard owners mid-bind_many and require a survivor to adopt
    the orphaned slot within the lease window with zero lost or
    duplicate binds. Returns a summary for --json."""
    import json

    env = dict(env)
    # a shard spec or key armed in the shell would skew both halves
    env.pop("KBT_FEDERATION", None)
    env.pop("KBT_SHARD_KEY", None)
    ok = True
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.federation", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    summary: dict = {}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: federation smoke produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    if res.returncode != 0 or not summary.get("ok", False):
        print(f"verify: federation smoke FAILED ({summary})")
        ok = False
    res = subprocess.run(
        [sys.executable, "-c", _FED_DRILL],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    drill: dict = {}
    try:
        drill = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        pass
    if res.returncode != 0 or not drill.get("ok", False):
        print(res.stdout, res.stderr, sep="\n")
        print(f"verify: federation two-scheduler conflict drill FAILED ({drill})")
        ok = False
    # the kill-and-adopt drill (no --strict: the unowned-window fsck
    # observation is timing-dependent and covered deterministically by
    # tests/test_resharding.py)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.federation", "--json", "--kill-one"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    kill: dict = {}
    try:
        kill = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: federation kill drill produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    if res.returncode != 0 or not kill.get("ok", False):
        print(f"verify: federation kill-and-adopt drill FAILED ({kill})")
        ok = False
    # the streaming-federation smoke (ISSUE 18 tentpole): N shards on
    # event-driven micro-cycles absorbing peer binds as occupancy
    # patches — parity with the classic federated run, micro-cycles
    # actually taken, exactly-once, fsck clean, pumps and listeners
    # shut down clean
    env_st = dict(env)
    env_st.pop("KBT_STREAMING", None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.federation", "--json",
         "--streaming"],
        cwd=REPO, env=env_st, capture_output=True, text=True,
    )
    stream: dict = {}
    try:
        stream = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: streaming-federation smoke produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    if res.returncode != 0 or not stream.get("ok", False):
        print(f"verify: streaming-federation smoke FAILED ({stream})")
        ok = False
    return {
        "ok": ok,
        "shards": summary.get("shards"),
        "bound": summary.get("bound"),
        "exactly_once": summary.get("exactly_once"),
        "union_parity": summary.get("union_parity"),
        "drill_bound": drill.get("bound"),
        "kill_adopter": kill.get("adopter"),
        "kill_takeover_s": kill.get("takeover_s"),
        "kill_mttr_s": kill.get("mttr_s"),
        "streaming_micro_cycles": stream.get("micro_cycles"),
        "streaming_parity": stream.get("parity"),
    }


def run_obs_gate(env: dict) -> dict:
    """Default gate (and --obs): the tracing end-to-end self-check
    (python -m kube_batch_tpu.obs --json). Two federated shards over
    live loopback backends, a forced stale-dispatch conflict, and the
    smoke's own assertions: complete span tree, the conflicted
    gang.bind joined by the arbiter-side store.bind in one trace,
    fsck-clean store, JSONL + Chrome trace exported."""
    import json

    env = dict(env)
    # a tracing/federation override armed in the shell would skew the
    # smoke (it arms KBT_TRACE and the conf itself)
    for var in ("KBT_TRACE", "KBT_FEDERATION", "KBT_SHARD_KEY",
                "KBT_FLIGHT_RECORDER"):
        env.pop(var, None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.obs", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    summary: dict = {}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: obs tracing smoke produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and summary.get("ok", False)
    if not ok:
        print(f"verify: obs tracing smoke FAILED ({summary})")
    return {
        "ok": ok,
        "spans": summary.get("spans"),
        "conflicted_gang_binds": summary.get("conflicted_gang_binds"),
        "remote_spans_joined": summary.get("remote_spans_joined"),
        "tree_violations": len(summary.get("tree_violations") or []),
    }


def run_explain_gate(env: dict) -> dict:
    """Default gate: the unschedulability-forensics self-check
    (python -m kube_batch_tpu.obs.explain --json). A seeded cluster
    with one stuck gang per feasibility plane plus a bound control:
    the batched device forensics must agree byte-for-byte with the
    serial twin (parity), every gang must report its designed dominant
    reason, the would-fit-if planes must flag the designed single
    fixes, and the reasons must land on PodGroup conditions."""
    import json

    env = dict(env)
    # an explain/tracing override armed in the shell would skew the
    # smoke (it arms KBT_EXPLAIN itself)
    for var in ("KBT_EXPLAIN", "KBT_TRACE", "KBT_FEDERATION",
                "KBT_SHARD_KEY", "KBT_FLIGHT_RECORDER"):
        env.pop(var, None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.obs.explain", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    summary: dict = {}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: explain forensics smoke produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and summary.get("ok", False)
    if not ok:
        print(f"verify: explain forensics smoke FAILED ({summary})")
    return {
        "ok": ok,
        "parity": summary.get("parity"),
        "reasons_ok": summary.get("reasons_ok"),
        "would_fit_if_ok": summary.get("would_fit_if_ok"),
        "conditions_ok": summary.get("conditions_ok"),
    }


def run_fleet_gate(env: dict) -> dict:
    """Default gate: the fleet-aggregation self-check
    (python -m kube_batch_tpu.obs.fleet --json) at BOTH 2 and 4
    loopback shards. Per-shard SLO sketches served over live HTTP
    observatories, scraped and merged by the aggregator: merged
    p50/p90/p99 must land within the sketch's declared relative-error
    bound of the pooled-raw nearest-rank quantiles, with exactly-once
    binds and an fsck-clean store asserted in-row."""
    import json

    env = dict(env)
    # overrides armed in the shell would skew the smoke (it arms
    # KBT_FLEET itself and runs a federated world)
    for var in ("KBT_FLEET", "KBT_TRACE", "KBT_FEDERATION",
                "KBT_SHARD_KEY", "KBT_FLIGHT_RECORDER"):
        env.pop(var, None)
    out: dict = {"ok": True}
    for shards in (2, 4):
        res = subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.obs.fleet", "--json",
             "--shards", str(shards)],
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        summary: dict = {}
        try:
            summary = json.loads(res.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            print(f"verify: fleet obs smoke ({shards} shards) produced "
                  "no parseable summary")
            print(res.stdout, res.stderr, sep="\n")
        ok = res.returncode == 0 and summary.get("ok", False)
        if not ok:
            print(f"verify: fleet obs smoke FAILED at {shards} shards "
                  f"({summary})")
            out["ok"] = False
        out[f"shards_{shards}"] = {
            "ok": ok,
            "max_rel_err": summary.get("max_rel_err"),
            "rel_err_bound": summary.get("rel_err_bound"),
            "exactly_once": summary.get("exactly_once"),
            "fsck_violations": len(summary.get("fsck_violations") or []),
        }
    return out


def run_bench_diff_gate(old: str, new: str) -> dict:
    """--bench-diff OLD NEW: hack/bench_diff.py in --strict mode — a
    >15% p50 regression, a parity flip, a compile-budget change or a
    vanished row in NEW vs OLD fails the gate."""
    import json

    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "hack", "bench_diff.py"),
         old, new, "--json", "--strict"],
        cwd=REPO, capture_output=True, text=True,
    )
    summary: dict = {}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: bench_diff produced no parseable summary")
    if res.returncode != 0 or not summary.get("ok", False):
        print(res.stdout.rstrip())
        print(f"verify: bench diff FAILED ({old} -> {new})")
    return {
        "ok": res.returncode == 0 and summary.get("ok", False),
        "findings": len(summary.get("findings", [])),
        "rows": summary.get("rows_new"),
    }


def run_analysis_gate(strict: bool) -> dict:
    """The domain-aware suite as a subprocess (same pattern as the fsck
    gate: the CLI is the contract). Returns a summary dict for --json."""
    import json

    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis", "--json"]
        + (["--strict"] if strict else []),
        cwd=REPO, capture_output=True, text=True,
    )
    summary: dict = {"ok": False, "counts": {}}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: analysis suite produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and summary.get("ok", False)
    if not ok:
        for f in summary.get("findings", []) + summary.get("baseline_errors", []):
            print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
        if strict:
            for f in summary.get("stale", []):
                print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
        print("verify: analysis suite FAILED "
              "(python -m kube_batch_tpu.analysis --explain CODE for any code)")
    return {
        "ok": ok,
        "counts": summary.get("counts", {}),
        "suppressed": summary.get("suppressed", 0),
        "baseline_errors": len(summary.get("baseline_errors", [])),
        "stale": len(summary.get("stale", [])),
    }


def run_threads_gate(strict: bool) -> dict:
    """The concurrency sanitizer as its own gate (python -m
    kube_batch_tpu.analysis.threads): beyond the KBT-T pass the default
    suite already runs, the dedicated CLI also executes the seeded
    fixture self-check AND the RaceWitness determinism drills, so a
    regression in either detector fails the build even while the live
    tree is clean."""
    import json

    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis.threads", "--json"]
        + (["--strict"] if strict else []),
        cwd=REPO, capture_output=True, text=True,
    )
    summary: dict = {"ok": False, "counts": {}}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: threads analyzer produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and summary.get("ok", False)
    self_probs = summary.get("selfcheck") or {}
    problems = list(self_probs.get("static", ["?"])) + list(
        self_probs.get("witness", [])
    )
    if not ok:
        for f in summary.get("findings", []) + summary.get("baseline_errors", []):
            print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
        for p in problems:
            print(f"selfcheck: {p}")
        print("verify: concurrency sanitizer FAILED "
              "(python -m kube_batch_tpu.analysis.threads --explain CODE)")
    return {
        "ok": ok,
        "counts": summary.get("counts", {}),
        "suppressed": summary.get("suppressed", 0),
        "selfcheck_ok": not problems,
        "stale": len(summary.get("stale", [])),
    }


def run_trace_gate(strict: bool) -> dict:
    """The jaxpr-level trace auditor (python -m
    kube_batch_tpu.analysis.trace) under JAX_PLATFORMS=cpu. Same
    contract as the AST suite gate; per-code counts ride the --json
    summary. Unlike every other gate this one traces the real solver
    programs, so it runs last among the analysis gates (a broken
    kernel fails here with a traceback, not a lint)."""
    import json

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis.trace", "--json"]
        + (["--strict"] if strict else []),
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    summary: dict = {"ok": False, "counts": {}}
    try:
        summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print("verify: trace audit produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and summary.get("ok", False)
    if not ok:
        for f in summary.get("findings", []) + summary.get("baseline_errors", []):
            print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
        if strict:
            for f in summary.get("stale", []):
                print(f"{f['path']}:{f['line']}: {f['code']} {f['message']}")
        print("verify: trace audit FAILED "
              "(python -m kube_batch_tpu.analysis.trace --explain CODE)")
    return {
        "ok": ok,
        "counts": summary.get("counts", {}),
        "suppressed": summary.get("suppressed", 0),
        "entries": summary.get("entries", {}),
        "stale": len(summary.get("stale", [])),
    }


def run_interleave_gate(strict: bool) -> dict:
    """The interleaving model checker (python -m
    kube_batch_tpu.analysis.interleave) under JAX_PLATFORMS=cpu: the
    four fixed streaming/takeover scenarios through every
    distinguishable schedule. Opt-in via --interleave (it runs real
    micro/full cycles per schedule, ~tens of solves); the Dockerfile
    build runs it --strict so the shipped image's scenarios are proven
    clean. Counterexamples print with their replay command."""
    import json

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.analysis.interleave", "--json"]
        + (["--strict"] if strict else []),
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    summary: dict = {}
    try:
        summary = json.loads(res.stdout)
    except ValueError:
        print("verify: interleave explorer produced no parseable summary")
        print(res.stdout, res.stderr, sep="\n")
    ok = res.returncode == 0 and bool(summary)
    if not ok:
        for f in summary.get("findings", []):
            print(f)
        print("verify: interleave exploration FAILED (replay the trace id "
              "with python -m kube_batch_tpu.analysis.interleave --replay)")
    return {
        "ok": ok,
        "schedules": sum(
            s.get("schedules", 0) for s in summary.get("scenarios", [])
        ),
        "counterexamples": sum(
            len(s.get("counterexamples", [])) for s in summary.get("scenarios", [])
        ),
        "suppressed": summary.get("suppressed", 0),
    }


class _TimedGates(dict):
    """Gate-summary dict that stamps per-gate wall-clock (seconds since
    the previous gate finished) onto each entry as it is recorded, so
    slow gates (interleave, chaos) are visible in the ``--json``
    machine summary without touching every call site."""

    def __init__(self) -> None:
        super().__init__()
        self._mark = time.perf_counter()

    def __setitem__(self, key, value):
        now = time.perf_counter()
        if isinstance(value, dict) and "seconds" not in value:
            value = dict(value, seconds=round(now - self._mark, 3))
        self._mark = now
        super().__setitem__(key, value)


def main(argv: list[str] | None = None) -> int:
    import json

    argv = sys.argv[1:] if argv is None else argv
    strict = "--strict" in argv
    chaos = "--chaos" in argv
    as_json = "--json" in argv
    interleave = "--interleave" in argv
    federation = "--federation" in argv
    bench_diff: tuple[str, str] | None = None
    if "--bench-diff" in argv:
        i = argv.index("--bench-diff")
        paths = [a for a in argv[i + 1:i + 3] if not a.startswith("--")]
        if len(paths) == 1:
            print("verify: --bench-diff takes two bench JSON paths (OLD NEW) "
                  "or none, to auto-discover the two newest BENCH_*.json")
            return 2
        if not paths:
            import glob

            found = sorted(
                glob.glob(os.path.join(REPO, "BENCH_*.json")),
                key=lambda p: (os.path.getmtime(p), p),
            )
            if len(found) < 2:
                print("verify: --bench-diff auto-discovery needs at least "
                      "two BENCH_*.json artifacts in the repo root")
                return 2
            bench_diff = (found[-2], found[-1])
            print("verify: bench-diff auto-discovered "
                  f"{os.path.basename(found[-2])} -> "
                  f"{os.path.basename(found[-1])}")
        else:
            bench_diff = (paths[0], paths[1])
        argv = argv[:i] + argv[i + 1 + len(paths):]
    unknown = [
        a for a in argv
        if a not in ("--strict", "--chaos", "--json", "--interleave",
                     "--federation", "--obs")
    ]
    if unknown:
        print(f"verify: unknown argument(s): {' '.join(unknown)}")
        return 2
    files = py_files()
    failed = False
    gates: dict = _TimedGates()

    # 1. syntax
    ok = compileall.compile_dir(
        os.path.join(REPO, "kube_batch_tpu"), quiet=2, force=False
    )
    for single in files:
        ok = compileall.compile_file(single, quiet=2) and ok
    gates["compileall"] = {"ok": bool(ok)}
    if not ok:
        print("verify: compileall FAILED")
        failed = True

    # 2. indentation — tabnanny prints NannyNag diagnostics to STDOUT
    # (only I/O/token errors go to stderr), so both streams gate
    import contextlib

    tab_problems = 0
    for path in files:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
            tabnanny.check(path)
        if buf.getvalue():
            print(buf.getvalue().strip())
            tab_problems += 1
    gates["tabnanny"] = {"ok": tab_problems == 0, "flagged": tab_problems}
    if tab_problems:
        print(f"verify: tabnanny flagged {tab_problems} file(s)")
        failed = True

    # 3. AST lint
    n = run_ast_lint(files)
    gates["ast_lint"] = {"ok": n == 0, "findings": n}
    if n:
        print(f"verify: AST lint found {n} problem(s)")
        failed = True

    # 4. the domain-aware analysis suite (always on: it is stdlib-only,
    # so the bare image runs it; --strict additionally rejects stale
    # baseline entries)
    gates["analysis"] = run_analysis_gate(strict)
    if not gates["analysis"]["ok"]:
        failed = True

    # 4a. the concurrency sanitizer's own CLI (KBT-T0xx + RaceWitness):
    # runs the seeded fixture self-check and the witness determinism
    # drills on top of the live-tree pass the suite gate above did
    gates["threads"] = run_threads_gate(strict)
    if not gates["threads"]["ok"]:
        failed = True

    # 4b. the trace-level program auditor (KBT-P0xx): jaxpr lints +
    # donation + cross-tier signature drift over the real solver entry
    # points, on abstract inputs under JAX_PLATFORMS=cpu
    gates["trace_audit"] = run_trace_gate(strict)
    if not gates["trace_audit"]["ok"]:
        failed = True

    # 4c. (--interleave) the interleaving model checker (KBT-I0xx):
    # every distinguishable schedule of the fixed streaming/takeover
    # scenarios, with counterexamples replayable by trace id
    if interleave:
        gates["interleave"] = run_interleave_gate(strict)
        if not gates["interleave"]["ok"]:
            failed = True

    # 5. the full generic gate, when available (mypy beyond api/ per
    # VERDICT item 7: framework, conf and recovery carry the concurrency
    # and failover contracts, where a None slip is a 3am page)
    for tool, args in (
        ("ruff", ["check", "kube_batch_tpu"]),
        ("mypy", [
            "--ignore-missing-imports",
            "kube_batch_tpu/api",
            "kube_batch_tpu/framework",
            "kube_batch_tpu/conf",
            "kube_batch_tpu/recovery",
        ]),
    ):
        rc = run_optional(tool, args)
        if rc is None:
            gates[tool] = {"ok": not strict, "status": "unavailable"}
            if strict:
                print(f"verify: {tool} unavailable — FAILED (--strict: "
                      "install the 'dev' extra: pip install -e '.[dev]')")
                failed = True
            else:
                print(f"verify: {tool} unavailable in this image — skipped "
                      "(stdlib gates above still ran; --strict to require)")
        else:
            gates[tool] = {"ok": rc == 0, "status": "ran"}
            if rc != 0:
                print(f"verify: {tool} FAILED")
                failed = True

    # 6. chaos smoke — the failure drills must actually work here
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KBT_MIN_DEVICE_PAIRS="0",
        KBT_CACHE_MUTATION_DETECTOR="1",
    )
    env.pop("KBT_FAULTS", None)  # a drill armed in the shell would skew it
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.faults.smoke"], cwd=REPO, env=env
    )
    gates["chaos_smoke"] = {"ok": res.returncode == 0}
    if res.returncode != 0:
        print("verify: chaos smoke FAILED")
        failed = True

    # 6b. wire-codec self-check: seeded round-trip property pass over
    # every kind in both framings (python -m kube_batch_tpu.apis.wire).
    # A codec override armed in the shell must not skew it.
    env_wc = dict(env)
    env_wc.pop("KBT_WIRE_CODEC", None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.apis.wire", "--json"],
        cwd=REPO, env=env_wc, capture_output=True, text=True,
    )
    wire_summary: dict = {}
    try:
        wire_summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        pass
    wire_ok = res.returncode == 0 and wire_summary.get("ok", False)
    gates["wire_codec"] = {
        "ok": wire_ok,
        "cases": wire_summary.get("cases"),
        "json_bytes": wire_summary.get("json_bytes"),
        "binary_bytes": wire_summary.get("binary_bytes"),
    }
    if not wire_ok:
        print(res.stdout, res.stderr, sep="\n")
        print("verify: wire codec self-check FAILED")
        failed = True

    # 7. encode-cache parity smoke: warm and 1%-churn encodes must be
    # byte-identical to a fresh cold encode on a seeded snapshot
    # (python -m kube_batch_tpu.ops.encode_cache). Runs with the cache
    # at its default-on state — a shell override must not skew the gate.
    env_ec = dict(env)
    env_ec.pop("KBT_ENCODE_CACHE", None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.ops.encode_cache"],
        cwd=REPO, env=env_ec,
    )
    gates["encode_cache_smoke"] = {"ok": res.returncode == 0}
    if res.returncode != 0:
        print("verify: encode-cache parity smoke FAILED")
        failed = True

    # 7a. pipelined-cycle parity smoke: the same seeded world scheduled
    # with KBT_PIPELINE off then on must bind pod-for-pod identically,
    # with the pipelined run's dispatch actually deferred through the
    # fence and the arena ping-ponging its device banks
    # (python -m kube_batch_tpu.ops.encode_cache --pipeline). Pipeline
    # overrides armed in the shell must not skew either half.
    env_pl = dict(env_ec)
    for var in ("KBT_PIPELINE", "KBT_PIPELINE_FENCE_TIMEOUT_S",
                "KBT_EXCHANGE_BATCH"):
        env_pl.pop(var, None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.ops.encode_cache", "--pipeline"],
        cwd=REPO, env=env_pl,
    )
    gates["pipeline_smoke"] = {"ok": res.returncode == 0}
    if res.returncode != 0:
        print("verify: pipelined-cycle parity smoke FAILED")
        failed = True

    # 7b. streaming smoke: micro-cycles bind every arrival and agree
    # bind-for-bind with a full-cycle twin (python -m
    # kube_batch_tpu.streaming). The detector env from the chaos gate
    # stays on — micro-cycles must hold the no-mutation contract too.
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.streaming", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    stream_summary: dict = {}
    try:
        stream_summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        pass
    stream_ok = (
        res.returncode == 0
        and stream_summary.get("ok", False)
        and stream_summary.get("parity", False)
        and stream_summary.get("micro_cycles", 0) > 0
    )
    gates["streaming_smoke"] = {
        "ok": stream_ok,
        "micro_cycles": stream_summary.get("micro_cycles", 0),
        "p50_bind_ms": stream_summary.get("p50_bind_ms"),
    }
    if not stream_ok:
        print(res.stdout, res.stderr, sep="\n")
        print("verify: streaming smoke FAILED")
        failed = True

    # 7c. obs tracing smoke: span tree + cross-process propagation +
    # conflicted-bind join over the real wire path (--obs requests it
    # explicitly; it is part of the default gate set)
    gates["obs_tracing_smoke"] = run_obs_gate(env)
    if not gates["obs_tracing_smoke"]["ok"]:
        failed = True

    # 7c-bis. explain forensics smoke: batched device forensics vs the
    # serial twin on the seeded per-plane stuck-gang cluster (python -m
    # kube_batch_tpu.obs.explain). Part of the default gate set.
    gates["explain_smoke"] = run_explain_gate(env)
    if not gates["explain_smoke"]["ok"]:
        failed = True

    # 7c-ter. fleet observability smoke: per-shard sketches scraped and
    # merged over live loopback HTTP at 2 AND 4 shards, merged
    # quantiles within the sketch's error bound of pooled raw (python
    # -m kube_batch_tpu.obs.fleet). Part of the default gate set.
    gates["fleet_obs_smoke"] = run_fleet_gate(env)
    if not gates["fleet_obs_smoke"]["ok"]:
        failed = True

    # 7c-quater. admission smoke: the deterministic 5x-overload plant
    # (python -m kube_batch_tpu.admission --json) — the protected lane
    # holds its SLO tail with zero shed while the admission-OFF twin
    # collapses, the brownout ladder escalates and recovers without
    # flapping, and every shed carries Retry-After guidance. Part of
    # the default gate set (virtual clock: sub-second wall time).
    env_adm = dict(env)
    for var in ("KBT_ADMISSION", "KBT_ADMISSION_RATE",
                "KBT_ADMISSION_BURST", "KBT_ADMISSION_BACKLOG",
                "KBT_ADMISSION_P99_SLO_S", "KBT_ADMISSION_BAND",
                "KBT_ADMISSION_INTERVAL_S", "KBT_ADMISSION_MIN_RATE",
                "KBT_FLEET"):
        env_adm.pop(var, None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.admission", "--json"],
        cwd=REPO, env=env_adm, capture_output=True, text=True,
    )
    adm_summary: dict = {}
    try:
        adm_summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        pass
    adm_on = adm_summary.get("on") or {}
    adm_ok = res.returncode == 0 and adm_summary.get("ok", False)
    gates["admission_smoke"] = {
        "ok": adm_ok,
        "tail_p99_s": adm_on.get("tail_p99_s"),
        "high_shed": ((adm_on.get("counts") or {}).get("high") or {}).get("shed"),
        "level_final": adm_on.get("level_final"),
    }
    if not adm_ok:
        print(res.stdout, res.stderr, sep="\n")
        print("verify: admission smoke FAILED")
        failed = True

    # 7c-quinquies. node-class compressed solve smoke (python -m
    # kube_batch_tpu.ops.class_solve --json): the same seeded world
    # scheduled serial / uncompressed / KBT_CLASS_COMPRESS=1 must bind
    # pod-for-pod identically across two cycles (the second re-using
    # the class table with binds applied, so splits and re-merges both
    # fire), with the compressed tier actually engaged. Part of the
    # default gate set; shell overrides must not skew either half.
    env_cls = dict(env)
    for var in ("KBT_CLASS_COMPRESS", "KBT_MESH", "KBT_MESH_PALLAS"):
        env_cls.pop(var, None)
    res = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.ops.class_solve", "--json"],
        cwd=REPO, env=env_cls, capture_output=True, text=True,
    )
    cls_summary: dict = {}
    try:
        cls_summary = json.loads(res.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        pass
    cls_ok = (
        res.returncode == 0
        and cls_summary.get("ok", False)
        and cls_summary.get("parity_cycle1", False)
        and cls_summary.get("parity_cycle2", False)
    )
    gates["class_solve_smoke"] = {
        "ok": cls_ok,
        "class_count": cls_summary.get("class_count"),
        "compression_ratio": cls_summary.get("compression_ratio"),
        "splits": cls_summary.get("splits"),
    }
    if not cls_ok:
        print(res.stdout, res.stderr, sep="\n")
        print("verify: class-solve parity smoke FAILED")
        failed = True

    # 7d. --federation: the wire-path smoke + the seeded two-scheduler
    # conflict drill (optimistic concurrency over the extracted backend)
    if federation:
        gates["federation"] = run_federation_gate(env)
        if not gates["federation"]["ok"]:
            failed = True

    # 8. --chaos: the full chaos-marked suite + fsck on a seeded journal
    if chaos:
        chaos_ok = run_chaos_gate(env)
        gates["chaos"] = {"ok": chaos_ok}
        if not chaos_ok:
            failed = True

        # 8b. the admission storm drill (real-clock, ~1 min): the
        # three-cell ON/OFF/KILL comparison — protected-lane tail held
        # under 5x overload, the OFF twin measurably worse, and
        # mid-storm shard death recovered with zero orphans
        env_storm = dict(env_adm)
        res = subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.admission", "--storm",
             "--json", "--duration", "4"],
            cwd=REPO, env=env_storm, capture_output=True, text=True,
        )
        storm: dict = {}
        try:
            storm = json.loads(res.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            print("verify: admission storm drill produced no parseable summary")
            print(res.stdout, res.stderr, sep="\n")
        storm_ok = res.returncode == 0 and storm.get("ok", False)
        gates["admission_storm"] = {
            "ok": storm_ok,
            "on_high_p99_s": (storm.get("on") or {}).get(
                "lane_p99_s", {}).get("high"),
            "kill_mttr_s": (storm.get("kill") or {}).get("mttr_s"),
        }
        if not storm_ok:
            print(f"verify: admission storm drill FAILED ({storm})")
            failed = True

    # 9. --bench-diff OLD NEW: regression-gate two bench artifacts
    # (hack/bench_diff.py --strict — p50 regressions, parity flips,
    # compile-budget changes, vanished rows)
    if bench_diff is not None:
        gates["bench_diff"] = run_bench_diff_gate(*bench_diff)
        if not gates["bench_diff"]["ok"]:
            failed = True

    print("verify:", "FAILED" if failed else "ok",
          f"({len(files)} files)")
    if as_json:
        print(json.dumps({
            "ok": not failed,
            "strict": strict,
            "files": len(files),
            "gates": gates,
        }, sort_keys=True))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
