"""glog-style leveled logging for the whole package.

The reference narrates every scheduling decision through glog's
verbosity levels (``glog.V(3).Infof`` / ``glog.V(4).Infof`` throughout
pkg/scheduler). This module maps that onto stdlib logging:

- ``V(n).infof(...)`` emits only when the configured verbosity >= n
  (set via ``set_verbosity`` or the ``KB_TPU_V`` env var, like glog's
  ``-v`` flag);
- ``errorf`` / ``warningf`` / ``infof`` are unconditional, at the
  matching stdlib severities;
- the line format mirrors glog's ``I0729 18:22:08.123456 file.py:42]``.

Everything funnels through one stdlib logger ("kube_batch_tpu") so host
applications can re-route it with ordinary logging handlers.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_logger = logging.getLogger("kube_batch_tpu")
_verbosity = int(os.environ.get("KB_TPU_V", "0"))


class _GlogFormatter(logging.Formatter):
    _SEV = {"DEBUG": "I", "INFO": "I", "WARNING": "W", "ERROR": "E", "CRITICAL": "F"}

    def format(self, record: logging.LogRecord) -> str:
        t = time.localtime(record.created)
        return (
            f"{self._SEV.get(record.levelname, 'I')}"
            f"{t.tm_mon:02d}{t.tm_mday:02d} "
            f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}."
            f"{int(record.msecs * 1000):06d} "
            f"{record.filename}:{record.lineno}] {record.getMessage()}"
        )


def _ensure_handler() -> None:
    if not _logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(_GlogFormatter())
        _logger.addHandler(h)
        _logger.setLevel(logging.DEBUG)
        _logger.propagate = False


def set_verbosity(v: int) -> None:
    """Equivalent of glog's ``-v`` flag."""
    global _verbosity
    _verbosity = int(v)


def get_verbosity() -> int:
    return _verbosity


class _Verbose:
    __slots__ = ("enabled",)

    def __init__(self, enabled: bool) -> None:
        self.enabled = enabled

    def infof(self, fmt: str, *args) -> None:
        if self.enabled:
            _ensure_handler()
            _logger.info(fmt % args if args else fmt, stacklevel=2)


def V(level: int) -> _Verbose:  # noqa: N802 (glog parity)
    return _Verbose(_verbosity >= level)


def infof(fmt: str, *args) -> None:
    _ensure_handler()
    _logger.info(fmt % args if args else fmt, stacklevel=2)


def warningf(fmt: str, *args) -> None:
    _ensure_handler()
    _logger.warning(fmt % args if args else fmt, stacklevel=2)


def errorf(fmt: str, *args) -> None:
    _ensure_handler()
    _logger.error(fmt % args if args else fmt, stacklevel=2)
