"""Native (C++) hot loops with a pure-Python fallback.

`lib` is the compiled `_hotloops` module, or None when it cannot be
built/loaded (no toolchain, unsupported platform) or is disabled via
``KBT_NATIVE=0`` — callers must keep their Python path for that case.
The build is lazy and cached next to the source (native/build.py).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("kube_batch_tpu.native")

lib = None

if os.environ.get("KBT_NATIVE", "1") != "0":
    try:
        from kube_batch_tpu.native import build as _build

        _build.ensure()
        from kube_batch_tpu.native import _hotloops as lib  # noqa: F401
    except Exception as e:  # noqa: BLE001 -- any failure means fallback
        log.info("native hot loops unavailable (%s); using Python loops", e)
        lib = None
