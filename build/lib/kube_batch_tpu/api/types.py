"""Task status state machine (reference pkg/scheduler/api/types.go:26-84)."""

from __future__ import annotations

from enum import IntEnum


class TaskStatus(IntEnum):
    """10-state task lifecycle (reference types.go:26-58). IntEnum so the
    status doubles as the tensor encoding on the XLA path."""

    PENDING = 0      # waiting in queue
    ALLOCATED = 1    # resources assigned, not dispatched (gang barrier holds it)
    PIPELINED = 2    # assigned onto releasing resources; dispatch when freed
    BINDING = 3      # bind RPC in flight
    BOUND = 4        # bound to host, kubelet not started it yet
    RUNNING = 5
    RELEASING = 6    # being deleted / preempted
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9

    def __str__(self) -> str:  # "Pending" etc., matching reference labels
        return self.name.capitalize()


# Statuses that count as "holding resources" (reference helpers.go:64-71).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED}
)


def allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


_DISALLOWED_TRANSITIONS: frozenset[tuple[TaskStatus, TaskStatus]] = frozenset(
    {
        # Terminal states never transition back to active scheduling states.
        (TaskStatus.SUCCEEDED, TaskStatus.PENDING),
        (TaskStatus.SUCCEEDED, TaskStatus.ALLOCATED),
        (TaskStatus.SUCCEEDED, TaskStatus.PIPELINED),
        (TaskStatus.SUCCEEDED, TaskStatus.BINDING),
        (TaskStatus.FAILED, TaskStatus.ALLOCATED),
        (TaskStatus.FAILED, TaskStatus.PIPELINED),
        (TaskStatus.FAILED, TaskStatus.BINDING),
    }
)


def validate_status_update(old: TaskStatus, new: TaskStatus) -> None:
    """Guard task status transitions. The reference stub allows everything
    (types.go:82-84); this rebuild rejects the transitions that would
    corrupt the gang barrier's ready-count accounting (a terminal task
    re-entering the allocated set). Raises ValueError on a disallowed
    transition."""
    if (old, new) in _DISALLOWED_TRANSITIONS:
        raise ValueError(f"invalid task status transition {old!s} -> {new!s}")


class ValidateResult:
    """Result of a JobValid check (reference api/types.go:69-80)."""

    __slots__ = ("passed", "reason", "message")

    def __init__(self, passed: bool, reason: str = "", message: str = "") -> None:
        self.passed = passed
        self.reason = reason
        self.message = message

    def __repr__(self) -> str:
        return f"ValidateResult(passed={self.passed}, reason={self.reason!r})"
