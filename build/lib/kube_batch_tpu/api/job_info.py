"""TaskInfo + JobInfo: the gang unit and its members
(reference pkg/scheduler/api/job_info.go)."""

from __future__ import annotations

from typing import Optional

from kube_batch_tpu.apis.types import (
    GROUP_NAME_ANNOTATION_KEY,
    Pod,
    PodDisruptionBudget,
    PodGroup,
)
from kube_batch_tpu.api.helpers import (
    get_pod_resource_request,
    get_pod_resource_without_init_containers,
    get_task_status,
)
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import (
    TaskStatus,
    allocated_status,
    validate_status_update,
)


def pod_key(pod: Pod) -> str:
    """namespace/name key (reference helpers.go:27-33)."""
    return f"{pod.namespace}/{pod.name}"


def task_key(task: "TaskInfo") -> str:
    return task.uid


def job_key(namespace: str, group_name: str) -> str:
    return f"{namespace}/{group_name}"


def get_job_id(pod: Pod) -> str:
    """Gang membership from the group-name annotation
    (reference job_info.go:57-67)."""
    gn = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION_KEY, "")
    if gn:
        return job_key(pod.namespace, gn)
    return ""


class TaskInfo:
    """One pod as seen by the scheduler (reference job_info.go:36-124)."""

    __slots__ = (
        "uid",
        "job",
        "name",
        "namespace",
        "resreq",
        "init_resreq",
        "node_name",
        "status",
        "priority",
        "volume_ready",
        "pod",
    )

    def __init__(self, pod: Pod) -> None:
        self.uid: str = pod.metadata.uid
        self.job: str = get_job_id(pod)
        self.name: str = pod.name
        self.namespace: str = pod.namespace
        # Resreq: what the task consumes while running (no init containers);
        # InitResreq: what it takes to launch it — used for admission checks
        # (reference job_info.go:44-48, allocate.go:86,157).
        self.resreq: Resource = get_pod_resource_without_init_containers(pod)
        self.init_resreq: Resource = get_pod_resource_request(pod)
        self.node_name: str = pod.node_name
        self.status: TaskStatus = get_task_status(pod)
        self.priority: int = pod.priority if pod.priority is not None else 1
        self.volume_ready: bool = False
        self.pod: Pod = pod

    def clone(self) -> "TaskInfo":
        ti = TaskInfo.__new__(TaskInfo)
        ti.uid = self.uid
        ti.job = self.job
        ti.name = self.name
        ti.namespace = self.namespace
        ti.resreq = self.resreq.clone()
        ti.init_resreq = self.init_resreq.clone()
        ti.node_name = self.node_name
        ti.status = self.status
        ti.priority = self.priority
        ti.volume_ready = self.volume_ready
        ti.pod = self.pod
        return ti

    def clone_for_residency(self) -> "TaskInfo":
        """Clone that shares the Resource objects. The node task-map copy
        (reference node_info.go:117) needs an independent *status* so later
        caller-side status flips cannot corrupt accounting; resource values
        are never mutated on a TaskInfo after construction (no call site
        does — the accounting arithmetic mutates node/job aggregates only),
        so sharing them is exact and saves two Resource copies per
        assignment on the bulk replay path."""
        ti = TaskInfo.__new__(TaskInfo)
        ti.uid = self.uid
        ti.job = self.job
        ti.name = self.name
        ti.namespace = self.namespace
        ti.resreq = self.resreq
        ti.init_resreq = self.init_resreq
        ti.node_name = self.node_name
        ti.status = self.status
        ti.priority = self.priority
        ti.volume_ready = self.volume_ready
        ti.pod = self.pod
        return ti

    def __repr__(self) -> str:
        return (
            f"Task ({self.uid}:{self.namespace}/{self.name}): job {self.job}, "
            f"status {self.status}, pri {self.priority}, resreq {self.resreq}"
        )


class FitError:
    """Human-readable histogram of why a job did not fit
    (reference job_info.go:340-372)."""

    def __init__(self, nodes_fit_delta: dict[str, Resource]) -> None:
        self.nodes_fit_delta = nodes_fit_delta

    def __str__(self) -> str:
        if not self.nodes_fit_delta:
            return "0 nodes are available"
        reasons: dict[str, int] = {}
        for delta in self.nodes_fit_delta.values():
            if delta.get("cpu") < 0:
                reasons["cpu"] = reasons.get("cpu", 0) + 1
            if delta.get("memory") < 0:
                reasons["memory"] = reasons.get("memory", 0) + 1
            for name, q in delta.scalars.items():
                if q < 0:
                    reasons[name] = reasons.get(name, 0) + 1
        parts = sorted(f"{v} insufficient {k}" for k, v in reasons.items())
        return f"0/{len(self.nodes_fit_delta)} nodes are available, {', '.join(parts)}."


class JobInfo:
    """The gang unit — one PodGroup (or legacy PDB) worth of tasks
    (reference job_info.go:127-426). Maintains the TaskStatusIndex and the
    Allocated/TotalRequest aggregates through every mutation."""

    def __init__(self, uid: str, *tasks: TaskInfo) -> None:
        self.uid = uid
        self.name = ""
        self.namespace = ""
        self.queue = ""
        self.priority = 0
        self.node_selector: dict[str, str] = {}
        self.min_available = 0
        self.nodes_fit_delta: dict[str, Resource] = {}
        self.task_status_index: dict[TaskStatus, dict[str, TaskInfo]] = {}
        self.tasks: dict[str, TaskInfo] = {}
        self.allocated = Resource.empty()
        self.total_request = Resource.empty()
        self.creation_timestamp = 0.0
        self.pod_group: Optional[PodGroup] = None
        self.pdb: Optional[PodDisruptionBudget] = None
        for t in tasks:
            self.add_task_info(t)

    # -- pod group / pdb binding -------------------------------------------

    def set_pod_group(self, pg: PodGroup) -> None:
        """reference job_info.go:183-192."""
        self.name = pg.name
        self.namespace = pg.metadata.namespace
        self.min_available = pg.spec.min_member
        self.queue = pg.spec.queue
        self.creation_timestamp = pg.metadata.creation_timestamp
        self.pod_group = pg

    def unset_pod_group(self) -> None:
        self.pod_group = None

    def set_pdb(self, pdb: PodDisruptionBudget) -> None:
        """Legacy gang source (reference job_info.go:195-203)."""
        self.name = pdb.name
        self.namespace = pdb.metadata.namespace
        self.min_available = pdb.min_available
        self.creation_timestamp = pdb.metadata.creation_timestamp
        self.pdb = pdb

    def unset_pdb(self) -> None:
        self.pdb = None

    # -- task bookkeeping ---------------------------------------------------

    def get_tasks(self, *statuses: TaskStatus) -> list[TaskInfo]:
        """Clones of all tasks in the given statuses (reference job_info.go:210-222)."""
        out: list[TaskInfo] = []
        for status in statuses:
            for task in self.task_status_index.get(status, {}).values():
                out.append(task.clone())
        return out

    def _add_task_index(self, ti: TaskInfo) -> None:
        self.task_status_index.setdefault(ti.status, {})[ti.uid] = ti

    def _delete_task_index(self, ti: TaskInfo) -> None:
        tasks = self.task_status_index.get(ti.status)
        if tasks is not None:
            tasks.pop(ti.uid, None)
            if not tasks:
                del self.task_status_index[ti.status]

    def add_task_info(self, ti: TaskInfo) -> None:
        """reference job_info.go:233-242."""
        self.tasks[ti.uid] = ti
        self._add_task_index(ti)
        self.total_request.add(ti.resreq)
        if allocated_status(ti.status):
            self.allocated.add(ti.resreq)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """Delete + re-add under the new status so every index stays
        consistent (reference job_info.go:245-259)."""
        validate_status_update(task.status, status)
        self.delete_task_info(task)
        task.status = status
        self.add_task_info(task)

    def delete_task_info(self, ti: TaskInfo) -> None:
        """reference job_info.go:272-287."""
        task = self.tasks.get(ti.uid)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> "
                f"in job <{self.namespace}/{self.name}>"
            )
        self.total_request.sub(task.resreq)
        if allocated_status(task.status):
            self.allocated.sub(task.resreq)
        del self.tasks[task.uid]
        self._delete_task_index(task)

    def clone(self) -> "JobInfo":
        """reference job_info.go:290-322."""
        info = JobInfo(self.uid)
        info.name = self.name
        info.namespace = self.namespace
        info.queue = self.queue
        info.priority = self.priority
        info.min_available = self.min_available
        info.node_selector = dict(self.node_selector)
        info.creation_timestamp = self.creation_timestamp
        info.pod_group = self.pod_group
        info.pdb = self.pdb
        for task in self.tasks.values():
            info.add_task_info(task.clone())
        return info

    # -- gang predicates ----------------------------------------------------

    def ready_task_num(self) -> int:
        """Tasks holding resources or finished OK (reference job_info.go:375-386)."""
        n = 0
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.SUCCEEDED:
                n += len(tasks)
        return n

    def waiting_task_num(self) -> int:
        """Pipelined tasks (reference job_info.go:389-398)."""
        return len(self.task_status_index.get(TaskStatus.PIPELINED, {}))

    def valid_task_num(self) -> int:
        """Tasks that could ever satisfy the gang (reference job_info.go:401-413)."""
        n = 0
        for status, tasks in self.task_status_index.items():
            if (
                allocated_status(status)
                or status == TaskStatus.SUCCEEDED
                or status == TaskStatus.PIPELINED
                or status == TaskStatus.PENDING
            ):
                n += len(tasks)
        return n

    def ready(self) -> bool:
        """Gang barrier: enough tasks hold resources (reference job_info.go:416-420)."""
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        """reference job_info.go:423-426."""
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    def fit_error(self) -> str:
        return str(FitError(self.nodes_fit_delta))

    def __repr__(self) -> str:
        return (
            f"Job ({self.uid}): namespace {self.namespace} ({self.queue}), "
            f"name {self.name}, minAvailable {self.min_available}, "
            f"tasks {len(self.tasks)}"
        )
