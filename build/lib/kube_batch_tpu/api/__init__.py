"""L3 in-memory scheduling model (reference pkg/scheduler/api/).

Pure data layer: no dependency on the cache or framework. ``Resource`` is
both the serial-path arithmetic type and the row type of the dense tensors
built by kube_batch_tpu.ops.encode.
"""

from kube_batch_tpu.api.resource_info import (
    GPU_RESOURCE_NAME,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    Resource,
)
from kube_batch_tpu.api.types import (
    ALLOCATED_STATUSES,
    TaskStatus,
)
from kube_batch_tpu.api.helpers import (
    get_task_status,
    merge_errors,
    min_resource,
    share,
)
from kube_batch_tpu.api.job_info import FitError, JobInfo, TaskInfo, job_key, pod_key, task_key
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.cluster_info import ClusterInfo

__all__ = [
    "ALLOCATED_STATUSES",
    "ClusterInfo",
    "FitError",
    "GPU_RESOURCE_NAME",
    "JobInfo",
    "MIN_MEMORY",
    "MIN_MILLI_CPU",
    "MIN_MILLI_SCALAR",
    "NodeInfo",
    "QueueInfo",
    "Resource",
    "TaskInfo",
    "TaskStatus",
    "get_task_status",
    "job_key",
    "merge_errors",
    "min_resource",
    "pod_key",
    "share",
    "task_key",
]
