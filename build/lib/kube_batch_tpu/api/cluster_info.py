"""ClusterInfo: the per-cycle snapshot triple
(reference pkg/scheduler/api/cluster_info.go:22-26)."""

from __future__ import annotations

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo


class ClusterInfo:
    __slots__ = ("jobs", "nodes", "queues")

    def __init__(
        self,
        jobs: dict[str, JobInfo] | None = None,
        nodes: dict[str, NodeInfo] | None = None,
        queues: dict[str, QueueInfo] | None = None,
    ) -> None:
        self.jobs: dict[str, JobInfo] = jobs or {}
        self.nodes: dict[str, NodeInfo] = nodes or {}
        self.queues: dict[str, QueueInfo] = queues or {}

    def __repr__(self) -> str:
        return (
            f"Cluster: jobs {len(self.jobs)}, nodes {len(self.nodes)}, "
            f"queues {len(self.queues)}"
        )
