"""Scheduling queue ordered by a caller-supplied less-fn
(reference pkg/scheduler/util/priority_queue.go:26-100).

The less-fn returns True when the left item should pop before the right
item, exactly like the reference's ``api.LessFn``. The item that the
less-fn ranks first pops first; ties keep insertion order.

Implementation note (documented deviation): the reference backs this with
``container/heap``. A heap evaluates the comparator only along sift
paths, so when keys mutate while items sit in the heap (proportion queue
shares and drf job shares change after every allocation —
proportion.go:202-223, drf.go:135-154) the pop order becomes an accident
of heap shape. Here ``pop`` re-evaluates the comparator across the live
items and returns the currently-best one — the order the policy *means*.
For static keys this is exactly heap behavior (every comparator here
falls back to creation-time/uid, a total order, so ties cannot occur);
for dynamic keys it is deterministic freshest-order selection, which the
vectorized kernel reproduces exactly (ops/kernels.py selection keys).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

LessFn = Callable[[Any, Any], bool]


class PriorityQueue:
    """reference priority_queue.go:26-67."""

    def __init__(self, less_fn: Optional[LessFn] = None) -> None:
        self._less_fn = less_fn
        self._items: list[Any] = []  # insertion order (tie-break)

    def push(self, value: Any) -> None:
        self._items.append(value)

    def pop(self) -> Any:
        if not self._items:
            return None
        less = self._less_fn
        best = 0
        if less is not None:
            for i in range(1, len(self._items)):
                # strict comparison keeps the earliest-inserted of ties
                if less(self._items[i], self._items[best]):
                    best = i
        return self._items.pop(best)

    def empty(self) -> bool:
        return not self._items

    def __len__(self) -> int:
        return len(self._items)
