"""Node filtering/scoring helpers (reference pkg/scheduler/util/scheduler_helper.go).

The reference fans these loops out over 16 goroutines
(scheduler_helper.go:34-109). Here the serial implementations stay simple
and deterministic — they are the correctness oracle; the vectorized
replacement for the same loops is kube_batch_tpu.ops (feasibility mask +
score matrix computed on-device in one jitted call).

Documented deviation: the reference's SelectBestNode picks randomly among
equal-score nodes (scheduler_helper.go:127-138). Both paths here break
ties deterministically by position in the node list so that the serial
path and the XLA path are comparable assignment-for-assignment in the
property tests.
"""

from __future__ import annotations

from typing import Callable, Optional

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo

PredicateFn = Callable[[TaskInfo, NodeInfo], None]  # raises on failure
NodeOrderMapFn = Callable[[TaskInfo, NodeInfo], tuple[dict[str, float], float]]
NodeOrderReduceFn = Callable[[TaskInfo, dict[str, list[tuple[str, int]]]], dict[str, float]]


def get_node_list(nodes: dict[str, NodeInfo]) -> list[NodeInfo]:
    """Deterministic node list: sorted by name (reference GetNodeList
    iterates a Go map — random order; sorting keeps the serial path
    reproducible)."""
    return [nodes[name] for name in sorted(nodes)]


def predicate_nodes(
    task: TaskInfo, nodes: list[NodeInfo], fn: PredicateFn
) -> list[NodeInfo]:
    """Filter nodes that pass the predicate (reference
    scheduler_helper.go:34-57). Predicates signal failure by raising."""
    out: list[NodeInfo] = []
    for node in nodes:
        try:
            fn(task, node)
        except Exception:
            continue
        out.append(node)
    return out


def prioritize_nodes(
    task: TaskInfo,
    nodes: list[NodeInfo],
    map_fn: NodeOrderMapFn,
    reduce_fn: Optional[NodeOrderReduceFn] = None,
) -> dict[float, list[NodeInfo]]:
    """Score nodes and bucket them by score (reference
    scheduler_helper.go:60-109): per-node map phase collects per-plugin
    map-scores (floored to int, matching HostPriority.Score) plus the
    plain order score; the reduce phase may normalize map-scores; final
    score = reduced map total + order score."""
    plugin_node_scores: dict[str, list[tuple[str, int]]] = {}
    order_scores: dict[str, float] = {}
    for node in nodes:
        map_scores, order_score = map_fn(task, node)
        for plugin, score in map_scores.items():
            plugin_node_scores.setdefault(plugin, []).append((node.name, int(score // 1)))
        order_scores[node.name] = order_score

    reduced: dict[str, float] = {}
    if reduce_fn is not None:
        reduced = reduce_fn(task, plugin_node_scores)

    node_scores: dict[float, list[NodeInfo]] = {}
    for node in nodes:
        score = reduced.get(node.name, 0.0) + order_scores.get(node.name, 0.0)
        node_scores.setdefault(score, []).append(node)
    return node_scores


def sort_nodes(node_scores: dict[float, list[NodeInfo]]) -> list[NodeInfo]:
    """Nodes in descending score order (reference scheduler_helper.go:112-124)."""
    out: list[NodeInfo] = []
    for score in sorted(node_scores, reverse=True):
        out.extend(node_scores[score])
    return out


def select_best_node(node_scores: dict[float, list[NodeInfo]]) -> Optional[NodeInfo]:
    """Highest-scoring node; deterministic first-of-bucket tie-break
    (deviation from the reference's random pick, see module docstring)."""
    best: Optional[list[NodeInfo]] = None
    max_score = float("-inf")
    for score, bucket in node_scores.items():
        if score > max_score and bucket:
            max_score = score
            best = bucket
    return best[0] if best else None
