"""Scheduler utilities: priority queue + node predicate/score helpers
(reference pkg/scheduler/util/)."""

from kube_batch_tpu.utils.priority_queue import PriorityQueue
from kube_batch_tpu.utils.scheduler_helper import (
    get_node_list,
    predicate_nodes,
    prioritize_nodes,
    select_best_node,
    sort_nodes,
)

__all__ = [
    "PriorityQueue",
    "get_node_list",
    "predicate_nodes",
    "prioritize_nodes",
    "select_best_node",
    "sort_nodes",
]
