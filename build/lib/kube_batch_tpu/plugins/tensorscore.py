"""tensorscore plugin: nodeorder's scores, computed as whole-node-axis
vectors (SURVEY.md section 2.7d — vectorized scoring exposed through the
plugin registry so a conf can toggle it).

Scores are policy-identical to the nodeorder plugin (same float64
formulas via nodeorder.vectorized_least_balanced, same weights
arguments), but the per-(task, node) calls the serial actions make
during PrioritizeNodes (scheduler_helper.go:60-109) are served from one
numpy pass per (task, session-state):

- the per-node Used vectors are re-read from the live NodeInfo objects
  on each (task, ssn.state_seq) memo miss — one O(N) attribute sweep per
  scored task instead of O(N) *per-plugin-formula* Python arithmetic.
  Reading live state (rather than mirroring events) keeps the scores
  correct under every mutation path, including xla_allocate's bulk
  replay, which updates node accounting without firing session events;
- preferred node-affinity sums are memoized per task (pod specs are
  immutable within a session);
- InterPodAffinity reuses nodeorder's full symmetric-weight algorithm,
  memoized per (task, ssn.state_seq), with nodeorder's own
  no-terms-anywhere fast path.

Conf usage — swap it in for nodeorder::

    tiers:
    - plugins:
      - name: priority
      - name: gang
    - plugins:
      - name: drf
      - name: predicates
      - name: proportion
      - name: tensorscore

The xla_allocate action treats it exactly like nodeorder (same policy
envelope, same weights).
"""

from __future__ import annotations

import numpy as np

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.plugins.nodeorder import (
    BALANCED_RESOURCE_WEIGHT,
    LEAST_REQUESTED_WEIGHT,
    NODE_AFFINITY_WEIGHT,
    POD_AFFINITY_WEIGHT,
    any_pod_affinity_terms,
    interpod_affinity_scores,
    vectorized_least_balanced,
)


class TensorScorePlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "tensorscore"

    def on_session_open(self, ssn: Session) -> None:
        least_req_w = self.arguments.get_int(LEAST_REQUESTED_WEIGHT, 1)
        balanced_w = self.arguments.get_int(BALANCED_RESOURCE_WEIGHT, 1)
        node_aff_w = self.arguments.get_int(NODE_AFFINITY_WEIGHT, 1)
        pod_aff_w = self.arguments.get_int(POD_AFFINITY_WEIGHT, 1)

        names = sorted(ssn.nodes)
        row_of = {name: i for i, name in enumerate(names)}
        nodes = [ssn.nodes[name] for name in names]
        n = len(nodes)
        cap_cpu = np.asarray([nd.allocatable.milli_cpu for nd in nodes], np.float64)
        cap_mem = np.asarray([nd.allocatable.memory for nd in nodes], np.float64)
        zeros = np.zeros(n, np.float64)

        # live Used sweep, shared across every task scored at one state_seq
        used_memo: dict = {"seq": -1, "cpu": zeros, "mem": zeros}

        def used_vectors():
            if used_memo["seq"] != ssn.state_seq:
                used_memo["seq"] = ssn.state_seq
                used_memo["cpu"] = np.asarray(
                    [nd.used.milli_cpu for nd in nodes], np.float64
                )
                used_memo["mem"] = np.asarray(
                    [nd.used.memory for nd in nodes], np.float64
                )
            return used_memo["cpu"], used_memo["mem"]

        # -- per-task lazy vectors ----------------------------------------
        node_aff_cache: dict[str, np.ndarray] = {}

        def node_aff_vec(task: TaskInfo) -> np.ndarray:
            aff = task.pod.affinity
            if aff is None or not aff.node_affinity_preferred:
                return zeros
            vec = node_aff_cache.get(task.uid)
            if vec is None:
                vec = np.asarray(
                    [
                        float(
                            sum(
                                w
                                for w, term in aff.node_affinity_preferred
                                if term.matches(nd.node.labels if nd.node else {})
                            )
                        )
                        for nd in nodes
                    ],
                    np.float64,
                )
                node_aff_cache[task.uid] = vec
            return vec

        interpod_memo: dict = {"uid": None, "seq": -1, "vec": zeros, "active": None}

        def interpod_vec(task: TaskInfo) -> np.ndarray:
            if interpod_memo["active"] is None:
                all_tasks = (t for j in ssn.jobs.values() for t in j.tasks.values())
                interpod_memo["active"] = any_pod_affinity_terms(ssn.nodes, all_tasks)
            if not interpod_memo["active"]:
                return zeros
            if interpod_memo["uid"] != task.uid or interpod_memo["seq"] != ssn.state_seq:
                scores = interpod_affinity_scores(task, ssn.nodes)
                interpod_memo["uid"] = task.uid
                interpod_memo["seq"] = ssn.state_seq
                interpod_memo["vec"] = np.asarray(
                    [scores[name] for name in names], np.float64
                )
            return interpod_memo["vec"]

        memo: dict = {"uid": None, "seq": -1, "scores": zeros}

        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            if memo["uid"] != task.uid or memo["seq"] != ssn.state_seq:
                used_cpu, used_mem = used_vectors()
                least, balanced = vectorized_least_balanced(
                    used_cpu + task.resreq.milli_cpu,
                    used_mem + task.resreq.memory,
                    cap_cpu,
                    cap_mem,
                )
                memo["uid"] = task.uid
                memo["seq"] = ssn.state_seq
                memo["scores"] = (
                    least * least_req_w
                    + balanced * balanced_w
                    + node_aff_vec(task) * node_aff_w
                    + interpod_vec(task) * pod_aff_w
                )
            row = row_of.get(node.name)
            return float(memo["scores"][row]) if row is not None else 0.0

        ssn.add_node_order_fn(self.name, node_order_fn)


def new(arguments: Arguments) -> Plugin:
    return TensorScorePlugin(arguments)
