"""priority plugin: higher pod/PriorityClass value schedules first
(reference pkg/scheduler/plugins/priority/priority.go:39-80)."""

from __future__ import annotations

from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session


class PriorityPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "priority"

    def on_session_open(self, ssn: Session) -> None:
        def task_order_fn(l: TaskInfo, r: TaskInfo) -> int:
            # Higher priority pops first (priority.go:39-57).
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name, task_order_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            # priority.go:61-77.
            if l.priority > r.priority:
                return -1
            if l.priority < r.priority:
                return 1
            return 0

        ssn.add_job_order_fn(self.name, job_order_fn)


def new(arguments: Arguments) -> Plugin:
    return PriorityPlugin(arguments)
