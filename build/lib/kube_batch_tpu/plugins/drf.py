"""drf plugin: dominant-resource fairness across jobs
(reference pkg/scheduler/plugins/drf/drf.go:29-171)."""

from __future__ import annotations

from kube_batch_tpu.api.helpers import share
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import allocated_status
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.event import Event, EventHandler
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session

SHARE_DELTA = 1e-6  # drf.go:29


class _DrfAttr:
    __slots__ = ("share", "allocated")

    def __init__(self) -> None:
        self.share = 0.0
        self.allocated = Resource.empty()


class DrfPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.job_attrs: dict[str, _DrfAttr] = {}

    @property
    def name(self) -> str:
        return "drf"

    def _calculate_share(self, allocated: Resource) -> float:
        """share = max over resources of allocated/total (drf.go:161-171)."""
        res = 0.0
        for rn in self.total_resource.resource_names():
            s = share(allocated.get(rn), self.total_resource.get(rn))
            if s > res:
                res = s
        return res

    def _update_share(self, attr: _DrfAttr) -> None:
        attr.share = self._calculate_share(attr.allocated)

    def on_session_open(self, ssn: Session) -> None:
        # Session precompute: totals + per-job allocated (drf.go:60-83).
        for node in ssn.nodes.values():
            self.total_resource.add(node.allocatable)
        for job in ssn.jobs.values():
            attr = _DrfAttr()
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
            self._update_share(attr)
            self.job_attrs[job.uid] = attr

        def preemptable_fn(preemptor: TaskInfo, preemptees: list[TaskInfo]) -> list[TaskInfo]:
            """Victim is evictable only if the preemptor's post-allocation
            share stays below (or within epsilon of) the victim's
            post-eviction share (drf.go:85-112)."""
            victims: list[TaskInfo] = []
            latt = self.job_attrs[preemptor.job]
            lalloc = latt.allocated.clone().add(preemptor.resreq)
            ls = self._calculate_share(lalloc)
            allocations: dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    allocations[preemptee.job] = self.job_attrs[preemptee.job].allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                rs = self._calculate_share(ralloc)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name, preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            """Lower share schedules first (drf.go:114-132)."""
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name, job_order_fn)

        def on_allocate(event: Event) -> None:
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event: Event) -> None:
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate, deallocate_func=on_deallocate)
        )

    def on_session_close(self, ssn: Session) -> None:
        self.total_resource = Resource.empty()
        self.job_attrs = {}


def new(arguments: Arguments) -> Plugin:
    return DrfPlugin(arguments)
