"""L5b plugins — the policies (reference pkg/scheduler/plugins/).

Importing this package registers every built-in plugin builder with the
framework registry (reference plugins/factory.go:31-42 does the same via
blank imports from main.go:33-34).
"""

from kube_batch_tpu.plugins.factory import register_all_plugins

register_all_plugins()
