"""predicates plugin: node feasibility checks
(reference pkg/scheduler/plugins/predicates/predicates.go:57-203).

The reference chains eight upstream k8s predicate libs; here each check is
implemented directly against the in-process object model, in the same
order, failing fast with PredicateError:

1. max task num (pod count)          predicates.go:70-72
2. node condition                    predicates.go:75-86
3. node unschedulable (cordon)       predicates.go:89-100
4. node selector + node affinity     predicates.go:103-114
5. host ports                        predicates.go:117-128
6. taints/tolerations                predicates.go:131-142
7. memory/disk/pid pressure          predicates.go:145-184
8. pod (anti-)affinity               predicates.go:187-199

Every check is a pure function of (pod spec, node spec, resident pods) so
the XLA path can evaluate 1-7 as precomputed boolean masks over the
task x node grid (kube_batch_tpu.ops.encode builds them with the same
functions); 8 is pairwise-dynamic and stays host-side.
"""

from __future__ import annotations

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.apis.types import Node, Pod
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session


class PredicateError(Exception):
    """A predicate rejected (task, node); the message mirrors the
    reference's error strings."""


# -- pure checks (shared with ops.encode) -----------------------------------


def check_max_task_num(node: NodeInfo, current_tasks: int) -> bool:
    """predicates.go:70-72: room for one more pod."""
    return node.allocatable.max_task_num > current_tasks


def check_node_condition(node: Node) -> bool:
    """CheckNodeConditionPredicate: Ready and no OutOfDisk /
    NetworkUnavailable (predicates.go:75-86)."""
    ready = False
    for c in node.conditions:
        if c.type == "Ready":
            ready = c.status == "True"
        elif c.type == "OutOfDisk" and c.status == "True":
            return False
        elif c.type == "NetworkUnavailable" and c.status == "True":
            return False
    return ready


def check_node_unschedulable(pod: Pod, node: Node) -> bool:
    """CheckNodeUnschedulablePredicate (predicates.go:89-100): cordoned
    nodes accept only pods tolerating the unschedulable taint."""
    if not node.unschedulable:
        return True
    for tol in pod.tolerations:
        if tol.key == "node.kubernetes.io/unschedulable" or (
            tol.operator == "Exists" and not tol.key
        ):
            return True
    return False


def check_node_selector(pod: Pod, node: Node) -> bool:
    """PodMatchNodeSelector (predicates.go:103-114): plain nodeSelector
    labels AND required node-affinity terms (OR across terms)."""
    for key, value in pod.node_selector.items():
        if node.labels.get(key) != value:
            return False
    if pod.affinity is not None and pod.affinity.node_affinity_required:
        if not any(
            term.matches(node.labels) for term in pod.affinity.node_affinity_required
        ):
            return False
    return True


def check_host_ports(pod: Pod, node: NodeInfo) -> bool:
    """PodFitsHostPorts (predicates.go:117-128)."""
    wanted = {p for c in pod.containers for p in c.ports}
    if not wanted:
        return True
    used = {
        p
        for task in node.tasks.values()
        for c in task.pod.containers
        for p in c.ports
    }
    return not (wanted & used)


def check_taints(pod: Pod, node: Node) -> bool:
    """PodToleratesNodeTaints (predicates.go:131-142): every NoSchedule /
    NoExecute taint must be tolerated (PreferNoSchedule is soft)."""
    for taint in node.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(tol.tolerates(taint) for tol in pod.tolerations):
            return False
    return True


def check_pressure(node: Node) -> bool:
    """Memory/Disk/PID pressure conditions (predicates.go:145-184)."""
    for c in node.conditions:
        if c.type in ("MemoryPressure", "DiskPressure", "PIDPressure") and c.status == "True":
            return False
    return True


def _selector_matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def check_pod_affinity(pod: Pod, node: NodeInfo, all_nodes: dict[str, NodeInfo]) -> bool:
    """Required pod (anti-)affinity over topology domains
    (predicates.go:187-199). Topology domain = set of nodes sharing the
    term's topology_key label value with the candidate node."""
    if pod.affinity is None:
        return True
    aff = pod.affinity
    if not aff.pod_affinity_required and not aff.pod_anti_affinity_required:
        return True

    def domain_pods(topology_key: str):
        node_labels = node.node.labels if node.node else {}
        domain_value = node_labels.get(topology_key)
        for other in all_nodes.values():
            other_labels = other.node.labels if other.node else {}
            if topology_key == "kubernetes.io/hostname":
                in_domain = other.name == node.name
            else:
                in_domain = (
                    domain_value is not None
                    and other_labels.get(topology_key) == domain_value
                )
            if in_domain:
                for task in other.tasks.values():
                    yield task.pod

    for term in aff.pod_affinity_required:
        if not any(
            _selector_matches(term.label_selector, p.metadata.labels)
            for p in domain_pods(term.topology_key)
        ):
            return False
    for term in aff.pod_anti_affinity_required:
        if any(
            _selector_matches(term.label_selector, p.metadata.labels)
            for p in domain_pods(term.topology_key)
            if p is not pod
        ):
            return False
    return True


class PredicatesPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "predicates"

    def on_session_open(self, ssn: Session) -> None:
        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            if node.node is None:
                raise PredicateError(f"node <{node.name}> has no node object")
            if not check_max_task_num(node, len(node.tasks)):
                raise PredicateError(
                    f"node <{node.name}> can not allow more task running on it"
                )
            if not check_node_condition(node.node):
                raise PredicateError(
                    f"node <{node.name}> are not available to schedule task "
                    f"<{task.namespace}/{task.name}>"
                )
            if not check_node_unschedulable(task.pod, node.node):
                raise PredicateError(
                    f"task <{task.namespace}/{task.name}> node <{node.name}> "
                    f"set to unschedulable"
                )
            if not check_node_selector(task.pod, node.node):
                raise PredicateError(
                    f"node <{node.name}> didn't match task "
                    f"<{task.namespace}/{task.name}> node selector"
                )
            if not check_host_ports(task.pod, node):
                raise PredicateError(
                    f"node <{node.name}> didn't have available host ports for "
                    f"task <{task.namespace}/{task.name}>"
                )
            if not check_taints(task.pod, node.node):
                raise PredicateError(
                    f"task <{task.namespace}/{task.name}> does not tolerate "
                    f"node <{node.name}> taints"
                )
            if not check_pressure(node.node):
                raise PredicateError(
                    f"node <{node.name}> under pressure, can not schedule task "
                    f"<{task.namespace}/{task.name}>"
                )
            if not check_pod_affinity(task.pod, node, ssn.nodes):
                raise PredicateError(
                    f"task <{task.namespace}/{task.name}> affinity/anti-affinity "
                    f"failed on node <{node.name}>"
                )

        ssn.add_predicate_fn(self.name, predicate_fn)


def new(arguments: Arguments) -> Plugin:
    return PredicatesPlugin(arguments)
