"""conformance plugin: never evict critical pods
(reference pkg/scheduler/plugins/conformance/conformance.go:41-63)."""

from __future__ import annotations

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session

SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"
NAMESPACE_SYSTEM = "kube-system"


class ConformancePlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "conformance"

    def on_session_open(self, ssn: Session) -> None:
        def evictable_fn(evictor: TaskInfo, evictees: list[TaskInfo]) -> list[TaskInfo]:
            victims: list[TaskInfo] = []
            for evictee in evictees:
                class_name = evictee.pod.priority_class_name
                if (
                    class_name == SYSTEM_CLUSTER_CRITICAL
                    or class_name == SYSTEM_NODE_CRITICAL
                    or evictee.namespace == NAMESPACE_SYSTEM
                ):
                    continue
                victims.append(evictee)
            return victims

        ssn.add_preemptable_fn(self.name, evictable_fn)
        ssn.add_reclaimable_fn(self.name, evictable_fn)


def new(arguments: Arguments) -> Plugin:
    return ConformancePlugin(arguments)
