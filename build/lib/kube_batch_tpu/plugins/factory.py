"""Plugin registration (reference pkg/scheduler/plugins/factory.go:31-42)."""

from __future__ import annotations

from kube_batch_tpu.framework.registry import register_plugin_builder


def register_all_plugins() -> None:
    from kube_batch_tpu.plugins import (
        conformance,
        drf,
        gang,
        nodeorder,
        predicates,
        priority,
        proportion,
        tensorscore,
    )

    register_plugin_builder("priority", priority.new)
    register_plugin_builder("gang", gang.new)
    register_plugin_builder("conformance", conformance.new)
    register_plugin_builder("drf", drf.new)
    register_plugin_builder("proportion", proportion.new)
    register_plugin_builder("predicates", predicates.new)
    register_plugin_builder("nodeorder", nodeorder.new)
    register_plugin_builder("tensorscore", tensorscore.new)
