"""L0 object model: the framework's "CRD" layer.

Python equivalents of the reference's API objects — PodGroup and Queue
(reference pkg/apis/scheduling/v1alpha1/types.go:93-209) plus lightweight
stand-ins for the core-v1 objects the scheduler consumes (Pod, Node,
PriorityClass, PodDisruptionBudget). There is no real Kubernetes here;
these are the wire objects of the in-process cluster state store
(kube_batch_tpu.cache) and of the synthetic workload generators
(kube_batch_tpu.models).
"""

from kube_batch_tpu.apis.types import (
    Affinity,
    Container,
    GROUP_NAME_ANNOTATION_KEY,
    Node,
    NodeCondition,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodAffinityTerm,
    PodCondition,
    PodDisruptionBudget,
    PodGroup,
    PodGroupCondition,
    PodGroupPhase,
    PodGroupSpec,
    PodGroupStatus,
    PodPhase,
    PriorityClass,
    Queue,
    QueueSpec,
    QueueStatus,
    Toleration,
    Taint,
)

__all__ = [
    "Affinity",
    "Container",
    "GROUP_NAME_ANNOTATION_KEY",
    "Node",
    "NodeCondition",
    "NodeSelectorTerm",
    "ObjectMeta",
    "Pod",
    "PodAffinityTerm",
    "PodCondition",
    "PodDisruptionBudget",
    "PodGroup",
    "PodGroupCondition",
    "PodGroupPhase",
    "PodGroupSpec",
    "PodGroupStatus",
    "PodPhase",
    "PriorityClass",
    "Queue",
    "QueueSpec",
    "QueueStatus",
    "Toleration",
    "Taint",
]
