"""L5a actions — the pipeline stages (reference pkg/scheduler/actions/).

Importing this package registers every built-in action with the framework
registry (reference actions/factory.go:29-35).
"""

from kube_batch_tpu.actions.factory import register_all_actions

register_all_actions()
