"""reclaim action: cross-queue reclaim for underserved queues — victims
are Running tasks of *other* queues, vetted by Reclaimable (proportion's
deserved share), evicted directly (no statement)
(reference pkg/scheduler/actions/reclaim/reclaim.go:42-198).

`run_reclaim` is the full control flow, parameterized over the node walk
(predicate-passing nodes in name order, reclaim.go:113-128) and an
optional post-pipeline hook so the vectorized xla_reclaim action can
share it (same pattern as actions/preempt.run_preempt)."""

from __future__ import annotations

from typing import Callable, Optional

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.utils import PriorityQueue, get_node_list

FeasibleFn = Callable[[Session, TaskInfo], list[NodeInfo]]


def serial_feasible(ssn: Session, task: TaskInfo) -> list[NodeInfo]:
    """Predicate-passing nodes, name order (reclaim.go:113-118)."""
    out = []
    for node in get_node_list(ssn.nodes):
        try:
            ssn.predicate_fn(task, node)
        except Exception:
            continue
        out.append(node)
    return out


def run_reclaim(
    ssn: Session,
    feasible_fn: FeasibleFn = serial_feasible,
    on_pipeline: Optional[Callable[[TaskInfo, str], None]] = None,
) -> None:
    """The full reclaim pass (reclaim.go:54-186)."""
    queues = PriorityQueue(ssn.queue_order_fn)
    seen_queues: set[str] = set()
    preemptors_map: dict[str, PriorityQueue] = {}
    preemptor_tasks: dict[str, PriorityQueue] = {}

    for job in ssn.jobs.values():
        if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
            continue
        queue = ssn.queues.get(job.queue)
        if queue is None:
            continue
        if queue.name not in seen_queues:
            seen_queues.add(queue.name)
            queues.push(queue)
        if job.task_status_index.get(TaskStatus.PENDING):
            if job.queue not in preemptors_map:
                preemptors_map[job.queue] = PriorityQueue(ssn.job_order_fn)
            preemptors_map[job.queue].push(job)
            preemptor_tasks[job.uid] = PriorityQueue(ssn.task_order_fn)
            for task in job.task_status_index[TaskStatus.PENDING].values():
                preemptor_tasks[job.uid].push(task)

    while not queues.empty():
        queue = queues.pop()
        if ssn.overused(queue):
            continue

        jobs = preemptors_map.get(queue.name)
        if jobs is None or jobs.empty():
            continue
        job = jobs.pop()

        tasks = preemptor_tasks.get(job.uid)
        if tasks is None or tasks.empty():
            continue
        task = tasks.pop()

        assigned = False
        for node in feasible_fn(ssn, task):
            resreq = task.init_resreq.clone()
            reclaimed = Resource.empty()

            # Running tasks of other queues (reclaim.go:130-143).
            reclaimees = []
            for resident in node.tasks.values():
                if resident.status != TaskStatus.RUNNING:
                    continue
                resident_job = ssn.jobs.get(resident.job)
                if resident_job is None:
                    continue
                if resident_job.queue != job.queue:
                    reclaimees.append(resident.clone())
            victims = ssn.reclaimable(task, reclaimees)
            if not victims:
                continue

            all_res = Resource.empty()
            for v in victims:
                all_res.add(v.resreq)
            if all_res.less(resreq):
                continue

            for reclaimee in victims:
                try:
                    ssn.evict(reclaimee, "reclaim")
                except Exception:
                    continue
                reclaimed.add(reclaimee.resreq)
                if resreq.less_equal(reclaimed):
                    break

            if task.init_resreq.less_equal(reclaimed):
                ssn.pipeline(task, node.name)
                if on_pipeline is not None:
                    on_pipeline(task, node.name)
                assigned = True
                break

        if assigned:
            queues.push(queue)


class ReclaimAction(Action):
    @property
    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn: Session) -> None:
        run_reclaim(ssn)


def new() -> Action:
    return ReclaimAction()
