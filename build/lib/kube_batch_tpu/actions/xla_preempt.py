"""xla_preempt action: preempt with a vectorized candidate-node scan.

The serial preempt action's hot loop is the same per-task node scan as
allocate's (reference pkg/scheduler/actions/preempt/preempt.go:176-256:
`util.PredicateNodes` + `util.PrioritizeNodes` over every node for every
starved preemptor task, 16-goroutine fan-out in Go). This action keeps
the reference's control flow — queue-by-queue preemptor heaps, Statement
speculation with commit/discard, victim selection by task order
(preempt.go:81-170) — entirely host-side, and replaces only the
per-preemptor node scan with one vectorized pass over the encoder's
(task-group x node-group) predicate matrices and the nodeorder score
formulas.

Design note (SURVEY.md section 7(b)): unlike the allocate solve — a
>50k-iteration sequential loop that lives on-device as a fused Pallas
kernel (ops/pallas_solve.py) — the preempt scan is one O(N x R) data-
parallel pass per preemptor with Statement mutations between scans. At
cluster sizes (N <= 100k nodes) that pass is microseconds of SIMD work,
far below a single host<->device round-trip, so it runs as float64 numpy:
bit-identical to the serial float64 oracle (including score tie-breaks),
which keeps `xla_preempt ≡ preempt` exact rather than
float32-approximate. The matrices it reads are the same ones the device
path consumes (ops/encode.py).

Scan-visible dynamic state: a Statement changes node residency only
through `pipeline` (evict flips a resident Running->Releasing, which
changes neither pod count, ports, nor Used — node_info.go:168-174), so
the mirror updates on pipeline/unpipeline alone; `_ScanStatement` keeps
it in sync through discard rollbacks.

Tasks whose pod spec carries required pod (anti-)affinity are pairwise-
dynamic (predicates.go:187-199) and scan serially, exactly like the
allocate hybrid routes them host-side.
"""

from __future__ import annotations

from kube_batch_tpu.actions.scan import ScanStatement, VectorScan
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session


class XlaPreemptAction(Action):
    """Drop-in replacement for the serial preempt action (conf
    ``actions: "...,xla_preempt,..."``): the shared run_preempt driver
    (actions/preempt.py) with the vectorized node scan and the
    mirror-syncing Statement."""

    @property
    def name(self) -> str:
        return "xla_preempt"

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.envelope import scan_supported
        from kube_batch_tpu.actions.preempt import PreemptAction, run_preempt, serial_candidates

        if not scan_supported(ssn):
            # VectorScan hardcodes the built-in predicate set and the
            # nodeorder/tensorscore score model; an unmodeled plugin in
            # the conf would silently diverge from the serial oracle.
            PreemptAction().execute(ssn)
            return

        scan = VectorScan(ssn)

        def candidates(s: Session, preemptor: TaskInfo):
            selected = scan.candidates(preemptor)
            if selected is None:
                # host-only task (required pod affinity / scan disabled):
                # the serial predicate walk, allocate-hybrid twin
                return serial_candidates(s, preemptor)
            return selected

        run_preempt(
            ssn,
            statement_factory=lambda s: ScanStatement(s, scan),
            candidates_fn=candidates,
        )


def new() -> Action:
    return XlaPreemptAction()
