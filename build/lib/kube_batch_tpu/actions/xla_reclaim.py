"""xla_reclaim action: reclaim with the vectorized predicate scan.

The serial reclaim walks every node per starved task, running the full
predicate chain inline (reference reclaim.go:113-128 — the same hot loop
shape as preempt's, minus scoring: first feasible node with enough
cross-queue victims wins, in node order). This action reuses the shared
`run_reclaim` driver (actions/reclaim.py) with `VectorScan.feasible` —
one numpy pass over the encoder's dedup'd predicate matrices per task —
and keeps victim vetting (Reclaimable), direct evicts, and the pipeline
exactly serial.

Evicts flip residents Running->Releasing (no scan-visible change);
pipelines update the scan mirrors through the on_pipeline hook. Host-only
tasks and out-of-envelope snapshots walk serially per task.
"""

from __future__ import annotations

from kube_batch_tpu.actions.scan import VectorScan
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session


class XlaReclaimAction(Action):
    @property
    def name(self) -> str:
        return "xla_reclaim"

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.envelope import scan_supported
        from kube_batch_tpu.actions.reclaim import ReclaimAction, run_reclaim, serial_feasible

        if not scan_supported(ssn):
            # Same envelope rule as xla_preempt: unmodeled predicate or
            # node-order plugins fall back to the serial action.
            ReclaimAction().execute(ssn)
            return

        scan = VectorScan(ssn)

        def feasible(s: Session, task):
            nodes = scan.feasible(task)
            if nodes is None:
                return serial_feasible(s, task)
            return nodes

        run_reclaim(ssn, feasible_fn=feasible, on_pipeline=scan.on_pipeline)


def new() -> Action:
    return XlaReclaimAction()
