"""Policy-envelope checks shared by the vectorized actions.

The device/vector paths (xla_allocate's fused solve, the VectorScan
behind xla_preempt/xla_reclaim) hardwire the reference's *default* conf
semantics: priority/gang ordering, drf/proportion shares, the built-in
predicate chain (predicates.go:57-203) and the nodeorder score formulas
(nodeorder.go:155-222). A conf that registers anything else — an unknown
plugin contributing predicate or node-order fns, or a non-default enable
flag — would make the vector paths silently diverge from the serial
oracle, so every vectorized action checks its envelope here and falls
back to the serial action for the cycle when outside it.
"""

from __future__ import annotations

from kube_batch_tpu.framework.session import Session

# Plugins whose session hooks the vector paths model exactly (priority/
# gang ordering + barrier, drf/proportion shares, predicates masks,
# nodeorder score) or that register nothing the allocate/preempt/reclaim
# scans consult beyond victim vetting, which stays host-side
# (conformance).
SUPPORTED_PLUGINS = {
    "priority",
    "gang",
    "conformance",
    "drf",
    "predicates",
    "proportion",
    "nodeorder",
    "tensorscore",  # nodeorder's scores served as vectors — same policy
}

# The per-plugin enable flags the conf schema knows (conf/__init__.py);
# the vector paths model the all-defaults (True) configuration of each.
ENABLE_FLAGS = (
    "enabled_job_order",
    "enabled_job_ready",
    "enabled_job_pipelined",
    "enabled_task_order",
    "enabled_preemptable",
    "enabled_reclaimable",
    "enabled_queue_order",
    "enabled_predicate",
    "enabled_node_order",
)


def scan_supported(ssn: Session) -> bool:
    """True when every configured plugin's predicate/score contribution is
    one the vectorized node scan models (VectorScan hardcodes the built-in
    predicate set and the nodeorder/tensorscore score formulas). Tier
    *order* does not matter here — preempt/reclaim control flow stays
    host-side and reads the session fn chains directly — but the
    predicates plugin must be *present*: without it the serial chain
    treats every node as feasible while the scan would still apply the
    hardwired masks."""
    names = []
    for tier in ssn.tiers:
        for option in tier.plugins:
            if option.name not in SUPPORTED_PLUGINS:
                return False
            if not all(getattr(option, flag, True) for flag in ENABLE_FLAGS):
                return False
            names.append(option.name)
    return "predicates" in names


def kernel_supported(ssn: Session) -> bool:
    """True when the tiers describe exactly the policy the allocate kernel
    models: every plugin in the supported set with default enable flags
    (`scan_supported`), plus the job-order chain reading
    priority -> gang -> (drf) and predicates present for the masks. The
    reference's default conf (util.go:31-42) passes. Anything else would
    make the kernel silently diverge from the serial oracle, so the
    action falls back."""
    if not scan_supported(ssn):
        return False
    order = [o.name for tier in ssn.tiers for o in tier.plugins]
    if "priority" not in order or "gang" not in order or "predicates" not in order:
        return False
    if order.index("priority") > order.index("gang"):
        return False
    # drf's job-order key sits after priority and gang in the kernel's
    # selection tuple; a conf ordering drf earlier would chain differently.
    if "drf" in order and order.index("drf") < order.index("gang"):
        return False
    return True
