"""backfill action: place BestEffort (zero-request) pending tasks on the
first node passing predicates (reference
pkg/scheduler/actions/backfill/backfill.go:41-76)."""

from __future__ import annotations

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.utils import get_node_list


class BackfillAction(Action):
    @property
    def name(self) -> str:
        return "backfill"

    def execute(self, ssn: Session) -> None:
        for job in ssn.jobs.values():
            if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            for task in list(job.task_status_index.get(TaskStatus.PENDING, {}).values()):
                if not task.init_resreq.is_empty():
                    continue
                for node in get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception:
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except Exception:
                        continue
                    break


def new() -> Action:
    return BackfillAction()
