"""Action registration (reference pkg/scheduler/actions/factory.go:29-35)."""

from __future__ import annotations

from kube_batch_tpu.framework.registry import register_action


def register_all_actions() -> None:
    from kube_batch_tpu.actions import (
        allocate,
        backfill,
        enqueue,
        preempt,
        reclaim,
        xla_backfill,
    )

    register_action(enqueue.new())
    register_action(allocate.new())
    register_action(backfill.new())
    register_action(preempt.new())
    register_action(reclaim.new())
    # numpy-only (no jax): available even on hosts without a device stack
    register_action(xla_backfill.new())

    # The vectorized TPU path needs jax; without it the scheduler still
    # works serially and a conf naming xla_allocate fails at load time.
    try:
        from kube_batch_tpu.actions import xla_allocate, xla_preempt, xla_reclaim

        register_action(xla_allocate.new())
        register_action(xla_preempt.new())
        register_action(xla_reclaim.new())
    except ImportError:
        pass
