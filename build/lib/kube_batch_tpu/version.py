"""Version stamping (reference pkg/version/version.go:25-33)."""

from __future__ import annotations

import platform
import sys

# Stamped at release; overridable at build/packaging time, like the
# reference's -ldflags -X injection.
VERSION = "0.1.0"
GIT_SHA = "Not provided."
BUILT = "Not provided."
API_VERSION = "v1alpha1"


def info(api_version: str = API_VERSION) -> list[str]:
    """reference version.go:42-52."""
    return [
        f"API Version: {api_version}",
        f"Version: {VERSION}",
        f"Git SHA: {GIT_SHA}",
        f"Built At: {BUILT}",
        f"Python Version: {platform.python_version()}",
        f"Platform: {sys.platform}/{platform.machine()}",
    ]


def print_version_and_exit(api_version: str = API_VERSION) -> None:
    """reference version.go:36-40."""
    for line in info(api_version):
        print(line)
    raise SystemExit(0)
