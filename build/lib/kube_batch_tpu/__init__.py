"""kube_batch_tpu — a TPU-native batch/gang scheduling framework.

Re-implements the full capability surface of kube-batch (reference:
/root/reference, a Go gang scheduler for Kubernetes) with the scheduling
core redesigned for JAX/XLA: the cluster snapshot is encoded as dense
task x node resource tensors and the allocate/backfill/preempt decisions
are computed as a vectorized bin-packing solve under ``jax.jit`` on TPU.

Layer map (mirrors reference SURVEY.md section 1):

- ``apis``      — L0 object model (PodGroup, Queue, Pod-like specs)
- ``api``       — L3 in-memory scheduling model (Resource, TaskInfo, ...)
- ``cache``     — L2 cluster-state cache (event handlers, snapshot)
- ``framework`` — L4 session + extension-point registry
- ``actions``   — L5a pipeline stages (enqueue/allocate/backfill/preempt/reclaim)
- ``plugins``   — L5b policies (priority/gang/drf/proportion/predicates/nodeorder/conformance)
- ``ops``       — the TPU compute path: snapshot->tensor encoder + vectorized kernels
- ``parallel``  — device mesh / sharding for multi-chip solves
- ``models``    — synthetic workload generators (gang, TFJob/MPIJob mixes)
- ``utils``     — priority queue + scheduler helpers
- ``conf``      — scheduler configuration schema + loader
- ``metrics``   — latency histograms / counters
- ``cli``       — queue CLI
- ``server``    — process entry / scheduler loop driver
"""

__version__ = "0.1.0"
