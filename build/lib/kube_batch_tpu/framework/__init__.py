"""L4 framework: session + extension-point registry
(reference pkg/scheduler/framework/)."""

from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.event import Event, EventHandler
from kube_batch_tpu.framework.interface import Action, Cache, Plugin
from kube_batch_tpu.framework.registry import (
    cleanup_plugin_builders,
    get_action,
    get_plugin_builder,
    register_action,
    register_plugin_builder,
)
from kube_batch_tpu.framework.session import Session, close_session, open_session
from kube_batch_tpu.framework.statement import Statement

__all__ = [
    "Action",
    "Arguments",
    "Cache",
    "Event",
    "EventHandler",
    "Plugin",
    "Session",
    "Statement",
    "cleanup_plugin_builders",
    "close_session",
    "get_action",
    "get_plugin_builder",
    "open_session",
    "register_action",
    "register_plugin_builder",
]
