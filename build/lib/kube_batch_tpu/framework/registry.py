"""Global plugin-builder and action registries
(reference pkg/scheduler/framework/plugins.go:30-72)."""

from __future__ import annotations

import threading
from typing import Callable, Optional

from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Action, Plugin

PluginBuilder = Callable[[Arguments], Plugin]

_mutex = threading.Lock()
_plugin_builders: dict[str, PluginBuilder] = {}
_actions: dict[str, Action] = {}


def register_plugin_builder(name: str, builder: PluginBuilder) -> None:
    with _mutex:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[PluginBuilder]:
    with _mutex:
        return _plugin_builders.get(name)


def cleanup_plugin_builders() -> None:
    with _mutex:
        _plugin_builders.clear()


def register_action(action: Action) -> None:
    with _mutex:
        _actions[action.name] = action


def get_action(name: str) -> Optional[Action]:
    with _mutex:
        return _actions.get(name)
