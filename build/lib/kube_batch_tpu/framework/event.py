"""Session events: callbacks that keep plugin-internal state (DRF shares,
proportion allocations) in sync per assignment
(reference pkg/scheduler/framework/event.go:24-32)."""

from __future__ import annotations

from typing import Callable, Optional

from kube_batch_tpu.api.job_info import TaskInfo


class Event:
    __slots__ = ("task",)

    def __init__(self, task: TaskInfo) -> None:
        self.task = task


class EventHandler:
    __slots__ = ("allocate_func", "deallocate_func")

    def __init__(
        self,
        allocate_func: Optional[Callable[[Event], None]] = None,
        deallocate_func: Optional[Callable[[Event], None]] = None,
    ) -> None:
        self.allocate_func = allocate_func
        self.deallocate_func = deallocate_func
