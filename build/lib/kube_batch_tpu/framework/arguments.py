"""Plugin arguments: string map + typed parse helpers
(reference pkg/scheduler/framework/arguments.go:26-46)."""

from __future__ import annotations

from typing import Mapping, Optional


class Arguments(dict):
    """``map[string]string`` with GetInt semantics: missing/empty/bad
    values leave the default untouched (reference arguments.go:33-46)."""

    def __init__(self, data: Optional[Mapping[str, str]] = None) -> None:
        super().__init__({str(k): str(v) for k, v in (data or {}).items()})

    def get_int(self, key: str, default: int) -> int:
        value = self.get(key, "")
        if not value:
            return default
        try:
            return int(value)
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        value = self.get(key, "")
        if not value:
            return default
        try:
            return float(value)
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool) -> bool:
        value = self.get(key, "").lower()
        if not value:
            return default
        if value in ("true", "1", "yes"):
            return True
        if value in ("false", "0", "no"):
            return False
        return default
