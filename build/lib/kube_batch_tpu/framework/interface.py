"""Action / Plugin / Cache interfaces
(reference pkg/scheduler/framework/interface.go:20-41,
pkg/scheduler/cache/interface.go:27-78)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import TYPE_CHECKING, Optional, Protocol

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo

if TYPE_CHECKING:
    from kube_batch_tpu.framework.session import Session


class Action(ABC):
    """A pipeline stage (reference interface.go:20-33)."""

    @property
    @abstractmethod
    def name(self) -> str: ...

    def initialize(self) -> None:
        return None

    @abstractmethod
    def execute(self, ssn: "Session") -> None: ...

    def uninitialize(self) -> None:
        return None


class Plugin(ABC):
    """A policy hook provider (reference interface.go:35-41). Plugins are
    re-instantiated from their builder every session."""

    @property
    @abstractmethod
    def name(self) -> str: ...

    @abstractmethod
    def on_session_open(self, ssn: "Session") -> None: ...

    def on_session_close(self, ssn: "Session") -> None:
        return None


class Cache(Protocol):
    """What a Session needs from the cluster cache
    (reference cache/interface.go:27-56)."""

    def snapshot(self) -> ClusterInfo: ...

    def bind(self, task: TaskInfo, hostname: str) -> None: ...

    def evict(self, task: TaskInfo, reason: str) -> None: ...

    def update_job_status(self, job: JobInfo) -> Optional[JobInfo]: ...

    def record_job_status_event(self, job: JobInfo) -> None: ...

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None: ...

    def bind_volumes(self, task: TaskInfo) -> None: ...
