"""kube_batch_tpu.ops: the TPU compute path.

The reference schedules serially — per task, a 16-goroutine scan over all
nodes for predicates and priorities (reference
pkg/scheduler/util/scheduler_helper.go:34-109) inside the allocate loop
(actions/allocate/allocate.go:94-190). Here the same cycle is one XLA
program: the cluster snapshot is encoded as struct-of-arrays tensors
(`encode`), and a jitted `lax.while_loop` performs the full
queue/job/task-ordered, gang-aware assignment with every per-node scan
vectorized (`kernels`). The serial actions remain the correctness oracle;
property tests pin serial ≡ XLA assignment-for-assignment.
"""

from kube_batch_tpu.ops.encode import EncodedSnapshot, encode_session
from kube_batch_tpu.ops.kernels import solve_allocate

__all__ = ["EncodedSnapshot", "encode_session", "solve_allocate"]
