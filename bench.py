"""Benchmark: serial reference path vs the XLA allocate path, end to end.

Methodology follows the reference's kubemark density tests
(test/e2e/benchmark.go:49-281) but hollow-state in-process: generate a
synthetic cluster (kube_batch_tpu.models), open a session under the
reference's *default* conf (util.go:31-42 — drf + proportion active, all
in the kernel's envelope), run one full allocate action, measure
wall-clock **for the whole session mutation** — encode + solve + replay
+ gang dispatch — not just the device solve.

Per config the XLA path runs ``1 warm + N`` sessions on fresh identical
clusters and reports min plus p50/p90/p99 (the percentile shape of
test/e2e/metric_util.go:45-68; min is the steady-state headline because
host-side Python time is load-sensitive).

Serial twins (VERDICT r3 item 2 — measured, not extrapolated):
- gang_example / 1k x 100 / multi-tenant / 10k x 1k: measured in-run
  (the 10k serial costs ~50 s — the price of an honest twin);
- 50k x 5k: the serial loop costs ~26 min (O(tasks x nodes) Python at
  ~6 us/pair), so it is measured when ``KBT_BENCH_FULL_SERIAL=1`` and
  otherwise reported from ``SERIAL_MEASURED`` — a number measured with
  that flag on this host class, stamped with its provenance, never
  extrapolated. ``vs_baseline`` is serial_s / xla_s at the 50k x 5k
  headline config.

Prints ONE JSON line:
  {"metric": "xla_session_seconds_50k_5k", "value": <seconds>,
   "unit": "s", "vs_baseline": <serial_s / xla_s at 50k x 5k>}

The north-star target (BASELINE.md) is value < 1.0 on a TPU chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Before any jax-touching import: the mesh-validation row runs the
# conf-selected sharded program on 8 virtual CPU devices (the real
# backend stays the default for every other row).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import (
    besteffort_mix,
    gang_example,
    multi_queue,
    multi_tenant_ml,
    preempt_contended,
    preempt_mix,
    synthetic,
)
from kube_batch_tpu.testing import FakeCache

# The reference's default conf (util.go:31-42).
TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# Serial twins measured offline with KBT_BENCH_FULL_SERIAL=1 (one run,
# however slow — VERDICT r3 item 2). Re-measure by setting the flag.
SERIAL_MEASURED = {
    # one uncontended run, 50000 binds equal to the xla path's; ~6 us
    # per (task,node) pair, linear — consistent with the in-run
    # 10k x 1k serial twin (10M pairs ≈ 52 s)
    "preempt_50k_5k": {
        "seconds": 1569.5,
        "provenance": "KBT_BENCH_FULL_SERIAL=1, 2026-07-30, bench host",
    },
    # one full run (2h28m), 100000 binds equal to the xla path's;
    # superlinear vs 50k (5.6x time for 4x pairs — candidate lists grow)
    "preempt_100k_10k": {
        "seconds": 8850.9,
        "provenance": "one full serial run, 2026-07-30, bench host",
    },
}


def tiers():
    return parse_scheduler_conf(TIERS_YAML).tiers


def run_session(cluster, action_name: str, action_args=None):
    """One full scheduling session; returns (seconds, binds, timings)."""
    import gc

    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers(), action_args)
    action = get_action(action_name)
    # collect the garbage of cluster construction OUTSIDE the timed
    # region; a gen2 sweep over a 50k-pod object graph inside it adds
    # hundreds of ms that have nothing to do with the scheduler
    gc.collect()
    t0 = time.perf_counter()
    action.execute(ssn)
    dt = time.perf_counter() - t0
    binds = dict(cache.binder.binds)  # task -> node, the actual placements
    close_session(ssn)
    return dt, binds, dict(getattr(action, "last_timings", {}))


def percentile(sorted_vals, p):
    """metric_util.go:45-68 shape: nearest-rank on the sorted sample."""
    import math

    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, math.ceil(p / 100 * len(sorted_vals)) - 1))
    return sorted_vals[k]


def timed(make_cluster, action_name: str, warm: bool, repeats: int = 2,
          action_args=None):
    """Warm run (jit compile at this bucket size) on a twin cluster, then
    N measured runs on fresh identical clusters. Returns
    (best_run, sorted_times)."""
    if warm:
        run_session(make_cluster(), action_name, action_args)
    best = None
    times = []
    for _ in range(repeats):
        res = run_session(make_cluster(), action_name, action_args)
        times.append(res[0])
        if best is None or res[0] < best[0]:
            best = res
    return best, sorted(times)


def main() -> None:
    from kube_batch_tpu.ops import enable_compilation_cache

    enable_compilation_cache()
    # The bench validates the DEVICE path against the serial baseline on
    # every row, including the tiny gang config — disable the production
    # size floor that would route small snapshots to the serial allocator
    # (the floor itself is covered by tests/test_xla_allocate.py).
    os.environ.setdefault("KBT_MIN_DEVICE_PAIRS", "0")
    details = {}
    full_serial = os.environ.get("KBT_BENCH_FULL_SERIAL") == "1"

    def record(name, make_cluster, serial, sessions=5, action_args=None,
               env=None):
        saved = {}
        for k, v in (env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            (xla_s, binds, t), times = timed(
                make_cluster, "xla_allocate", warm=True, repeats=sessions,
                action_args=action_args,
            )
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        entry = {
            "xla_s": round(xla_s, 4),
            "binds": len(binds),
            "sessions": sessions,
            "p50_s": round(percentile(times, 50), 4),
        }
        if sessions >= 5:
            # tail percentiles are only honest with enough samples; a
            # short row (big configs) reports median + min only
            entry["p90_s"] = round(percentile(times, 90), 4)
            entry["p99_s"] = round(percentile(times, 99), 4)
        for k, v in t.items():
            entry[k] = round(v, 4)
        if serial == "live" or (serial == "cached" and full_serial):
            (serial_s, s_binds, _), _ = timed(
                make_cluster, "allocate", warm=False, repeats=1
            )
            entry["serial_s"] = round(serial_s, 4)
            # PLACEMENT equality, not just counts (VERDICT r4 item 4):
            # with the comparison-dtype numerics (api/numerics.py) the
            # f32 device solve and the serial float oracle are
            # bind-for-bind identical — x64 off.
            assert s_binds == binds, (
                f"{name}: serial/xla placements diverge on "
                f"{sum(1 for k in s_binds if k in binds and binds[k] != s_binds[k]) + len(set(binds) ^ set(s_binds))} tasks"
            )
            entry["placements_equal_serial"] = True
        elif serial == "cached":
            cached = SERIAL_MEASURED.get(name)
            if cached is not None:
                entry["serial_s"] = cached["seconds"]
                entry["serial_s_note"] = "measured once via " + cached["provenance"]
        details[name] = entry
        return entry

    record("gang_example", gang_example, serial="live")
    record("synthetic_1k_100", lambda: synthetic(1000, 100), serial="live")
    record("multi_queue_10k_1k", lambda: multi_queue(10_000, 1000), serial="live")
    e50k = record("preempt_50k_5k", lambda: preempt_mix(50_000, 5000), serial="cached")
    record("multi_tenant_ml", lambda: multi_tenant_ml(), serial="live")
    # Scale headroom rows (SURVEY section 8's 100k claim + the v5e
    # VMEM-budget envelope at 4x the reference's headline, measured):
    record(
        "preempt_100k_10k",
        lambda: preempt_mix(100_000, 10_000),
        serial="cached",
    )
    record(
        "preempt_200k_20k",
        lambda: preempt_mix(200_000, 20_000),
        serial="none",
        sessions=5,
    )
    # The single-chip envelope row (VERDICT r4 item 5): a full session —
    # encode + solve + replay + dispatch — at 8x the reference's headline
    # scale, END TO END (replacing the README's former solve-only claim).
    record(
        "preempt_400k_40k",
        lambda: preempt_mix(400_000, 40_000),
        serial="none",
        sessions=2,
    )

    # -- mesh-path evidence (VERDICT r4 item 2) ---------------------------
    # (a) The conf-selected sharded solve on the 8-device virtual CPU
    #     mesh: validates that the production multi-chip path (GSPMD
    #     node-axis sharding through the real action) compiles, executes
    #     and binds at 10k scale every bench run. The TIME is a virtual-
    #     CPU number — shape validation, not a TPU latency claim
    #     (placement parity vs single-chip is test-asserted at the same
    #     scale in tests/test_parallel.py).
    # Ask for more devices than any host offers and let the action's own
    # resolver clamp to the largest power of two available (ONE source of
    # truth for the clamp, xla_allocate._resolve_mesh); normally 8 via
    # this module's injected device-count flag — an ambient XLA_FLAGS can
    # clamp lower, and the engaged size is recorded as mesh_devices.
    mesh_row = record(
        "multi_queue_10k_1k_meshcpu",
        lambda: multi_queue(10_000, 1000),
        serial="none",
        sessions=2,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
    )
    # the sharded path degrades to single-chip with only a warning on
    # any resolver/solver failure — the row is evidence only if a real
    # multi-device mesh ENGAGED (loud failure, never a silent skip)
    mesh_row["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    assert mesh_row["mesh_devices"] >= 2, (
        "mesh row ran single-chip; sharded path did not engage"
    )
    assert mesh_row["binds"] == details["multi_queue_10k_1k"]["binds"], (
        "mesh path bind count diverged from single-chip"
    )
    # (b) The per-chip price floor of the mesh path's program: the XLA
    #     while-loop twin (what ShardedSolver shards) on the single real
    #     chip at the headline config. Measured r5: solve time is ~flat
    #     in node count (3.8 s @1250 nodes -> 4.2 s @20k nodes, 50k
    #     tasks), i.e. per-iteration sequential-step latency dominates
    #     and node-axis sharding cannot buy latency — the mesh path is
    #     for capacity/deployment topology, not speed (README "Multi-chip"
    #     for the full analysis).
    record(
        "preempt_50k_5k_xla1",
        lambda: preempt_mix(50_000, 5000),
        serial="none",
        sessions=2,
        env={"KBT_PALLAS": "0"},
    )

    # preempt's hot scan, serial vs vectorized, same config (secondary)
    def preempt_session(action_name):
        cache = FakeCache(preempt_contended())
        ssn = open_session(cache, tiers())
        action = get_action(action_name)
        t0 = time.perf_counter()
        action.execute(ssn)
        dt = time.perf_counter() - t0
        evicts = len(cache.evictor.evicts)
        close_session(ssn)
        return dt, evicts

    xp_s, xp_ev = preempt_session("xla_preempt")
    sp_s, sp_ev = preempt_session("preempt")
    assert xp_ev == sp_ev, f"preempt evicts diverge: {sp_ev} vs {xp_ev}"
    details["preempt_contended"] = {
        "xla_s": round(xp_s, 4),
        "serial_s": round(sp_s, 4),
        "evicts": xp_ev,
    }

    # backfill's BestEffort walk, serial vs group-dedup'd scan, same
    # config (secondary): the serial cost is a full predicate chain per
    # (task, node) pair — 2M calls at this size
    def backfill_session(action_name):
        cache = FakeCache(besteffort_mix(2000, 1000))
        ssn = open_session(cache, tiers())
        action = get_action(action_name)
        t0 = time.perf_counter()
        action.execute(ssn)
        dt = time.perf_counter() - t0
        binds = dict(cache.binder.binds)  # task -> node, the actual placements
        close_session(ssn)
        return dt, binds

    xb_s, xb_binds = backfill_session("xla_backfill")
    sb_s, sb_binds = backfill_session("backfill")
    assert xb_binds == sb_binds, "backfill placements diverge"
    details["backfill_2k_1k"] = {
        "xla_s": round(xb_s, 4),
        "serial_s": round(sb_s, 4),
        "binds": len(xb_binds),
    }

    # Headline speedup at the headline config (VERDICT r3 item 2).
    serial_50k = e50k.get("serial_s")
    vs_baseline = (
        round(serial_50k / e50k["xla_s"], 2)
        if serial_50k and e50k["xla_s"]
        else None
    )

    print(json.dumps({"details": details}), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "xla_session_seconds_50k_5k",
                "value": e50k["xla_s"],
                "unit": "s",
                "vs_baseline": vs_baseline,
                # provenance of the serial side of vs_baseline, machine-
                # readable: "measured" = this run (KBT_BENCH_FULL_SERIAL),
                # "cached" = the provenance-stamped one-time measurement
                "baseline_source": (
                    "measured" if "serial_s_note" not in e50k else "cached"
                )
                if serial_50k
                else None,
            }
        )
    )


if __name__ == "__main__":
    main()
