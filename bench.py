"""Benchmark: serial reference path vs the XLA allocate path, end to end.

Methodology follows the reference's kubemark density tests
(test/e2e/benchmark.go:49-281) but hollow-state in-process: generate a
synthetic cluster (kube_batch_tpu.models), open a session under the
reference's *default* conf (util.go:31-42 — drf + proportion active, all
in the kernel's envelope), run one full allocate action, measure
wall-clock **for the whole session mutation** — encode + solve + replay
+ gang dispatch — not just the device solve (round-2 VERDICT items 1/5).

Every config runs the XLA path, including 50k x 5k (no env gate). The
serial twin is timed on the same configs where serial Python finishes in
bench-tolerable time (gang_example, 1k x 100, and the multi-tenant mix);
`vs_baseline` is the same-config speedup serial_s / xla_s at 1k x 100 —
a like-for-like end-to-end ratio (round-2 ADVICE item 2).

Prints ONE JSON line:
  {"metric": "xla_session_seconds_50k_5k", "value": <seconds>,
   "unit": "s", "vs_baseline": <serial_s / xla_s at 1k x 100>}

The north-star target (BASELINE.md) is value < 1.0 on a TPU chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import (
    gang_example,
    multi_queue,
    multi_tenant_ml,
    preempt_contended,
    preempt_mix,
    synthetic,
)
from kube_batch_tpu.testing import FakeCache

# The reference's default conf (util.go:31-42).
TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def tiers():
    return parse_scheduler_conf(TIERS_YAML).tiers


def run_session(cluster, action_name: str):
    """One full scheduling session; returns (seconds, binds, timings)."""
    import gc

    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers())
    action = get_action(action_name)
    # collect the garbage of cluster construction OUTSIDE the timed
    # region; a gen2 sweep over a 50k-pod object graph inside it adds
    # hundreds of ms that have nothing to do with the scheduler
    gc.collect()
    t0 = time.perf_counter()
    action.execute(ssn)
    dt = time.perf_counter() - t0
    binds = len(cache.binder.binds)
    close_session(ssn)
    return dt, binds, dict(getattr(action, "last_timings", {}))


def timed(make_cluster, action_name: str, warm: bool, repeats: int = 2):
    """Warm run (jit compile at this bucket size) on a twin cluster, then
    best-of-N measured runs on fresh identical clusters — host-side
    Python time (encode/replay) is load-sensitive, so the minimum is the
    honest steady-state latency."""
    if warm:
        run_session(make_cluster(), action_name)
    best = None
    for _ in range(repeats):
        res = run_session(make_cluster(), action_name)
        if best is None or res[0] < best[0]:
            best = res
    return best


def main() -> None:
    details = {}

    def record(name, make_cluster, serial: bool):
        xla_s, binds, t = timed(make_cluster, "xla_allocate", warm=True)
        entry = {"xla_s": round(xla_s, 4), "binds": binds}
        for k, v in t.items():
            entry[k] = round(v, 4)
        if serial:
            serial_s, s_binds, _ = timed(make_cluster, "allocate", warm=False, repeats=1)
            entry["serial_s"] = round(serial_s, 4)
            assert s_binds == binds, f"{name}: serial={s_binds} xla={binds} binds"
        details[name] = entry
        return entry

    record("gang_example", gang_example, serial=True)
    e1k = record("synthetic_1k_100", lambda: synthetic(1000, 100), serial=True)
    record("multi_queue_10k_1k", lambda: multi_queue(10_000, 1000), serial=False)
    e50k = record("preempt_50k_5k", lambda: preempt_mix(50_000, 5000), serial=False)
    record("multi_tenant_ml", lambda: multi_tenant_ml(), serial=True)

    # preempt's hot scan, serial vs vectorized, same config (secondary)
    def preempt_session(action_name):
        cache = FakeCache(preempt_contended())
        ssn = open_session(cache, tiers())
        action = get_action(action_name)
        t0 = time.perf_counter()
        action.execute(ssn)
        dt = time.perf_counter() - t0
        evicts = len(cache.evictor.evicts)
        close_session(ssn)
        return dt, evicts

    xp_s, xp_ev = preempt_session("xla_preempt")
    sp_s, sp_ev = preempt_session("preempt")
    assert xp_ev == sp_ev, f"preempt evicts diverge: {sp_ev} vs {xp_ev}"
    details["preempt_contended"] = {
        "xla_s": round(xp_s, 4),
        "serial_s": round(sp_s, 4),
        "evicts": xp_ev,
    }

    vs_baseline = round(e1k["serial_s"] / e1k["xla_s"], 2) if e1k["xla_s"] else None

    print(json.dumps({"details": details}), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "xla_session_seconds_50k_5k",
                "value": e50k["xla_s"],
                "unit": "s",
                "vs_baseline": vs_baseline,
            }
        )
    )


if __name__ == "__main__":
    main()
