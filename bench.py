"""Benchmark: serial reference path vs the XLA allocate path, end to end.

Methodology follows the reference's kubemark density tests
(test/e2e/benchmark.go:49-281) but hollow-state in-process: generate a
synthetic cluster (kube_batch_tpu.models), open a session under the
reference's *default* conf (util.go:31-42 — drf + proportion active, all
in the kernel's envelope), run one full allocate action, measure
wall-clock **for the whole session mutation** — encode + solve + replay
+ gang dispatch — not just the device solve.

Per config the XLA path runs ``1 warm + N`` sessions on fresh identical
clusters and reports min plus p50/p90/p99 (the percentile shape of
test/e2e/metric_util.go:45-68; min is the steady-state headline because
host-side Python time is load-sensitive).

Serial twins (VERDICT r3 item 2 — measured, not extrapolated):
- gang_example / 1k x 100 / multi-tenant / 10k x 1k: measured in-run
  (the 10k serial costs ~50 s — the price of an honest twin);
- 50k x 5k: the serial loop costs ~26 min (O(tasks x nodes) Python at
  ~6 us/pair), so it is measured when ``KBT_BENCH_FULL_SERIAL=1`` and
  otherwise reported from ``SERIAL_MEASURED`` — a number measured with
  that flag on this host class, stamped with its provenance, never
  extrapolated. ``vs_baseline`` is serial_s / xla_s at the 50k x 5k
  headline config.

Prints ONE JSON line:
  {"metric": "xla_session_seconds_50k_5k", "value": <seconds>,
   "unit": "s", "vs_baseline": <serial_s / xla_s at 50k x 5k>}

The north-star target (BASELINE.md) is value < 1.0 on a TPU chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Before any jax-touching import: the mesh-validation row runs the
# conf-selected sharded program on 8 virtual CPU devices (the real
# backend stays the default for every other row).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu import pipeline
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import (
    besteffort_mix,
    gang_example,
    multi_queue,
    multi_tenant_ml,
    preempt_contended,
    preempt_mix,
    synthetic,
    uniform_pool,
)
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

# The reference's default conf (util.go:31-42).
TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# Serial twins measured offline with KBT_BENCH_FULL_SERIAL=1 (one run,
# however slow — VERDICT r3 item 2). Re-measure by setting the flag.
SERIAL_MEASURED = {
    # one uncontended run, 50000 binds equal to the xla path's; ~6 us
    # per (task,node) pair, linear — consistent with the in-run
    # 10k x 1k serial twin (10M pairs ≈ 52 s)
    "preempt_50k_5k": {
        "seconds": 1569.5,
        "provenance": "KBT_BENCH_FULL_SERIAL=1, 2026-07-30, bench host",
    },
    # one full run (2h28m), 100000 binds equal to the xla path's;
    # superlinear vs 50k (5.6x time for 4x pairs — candidate lists grow)
    "preempt_100k_10k": {
        "seconds": 8850.9,
        "provenance": "one full serial run, 2026-07-30, bench host",
    },
}


def tiers():
    return parse_scheduler_conf(TIERS_YAML).tiers


def run_session(cluster, action_name: str, action_args=None):
    """One full scheduling session; returns (seconds, binds, timings)."""
    import gc

    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers(), action_args)
    action = get_action(action_name)
    # collect the garbage of cluster construction OUTSIDE the timed
    # region; a gen2 sweep over a 50k-pod object graph inside it adds
    # hundreds of ms that have nothing to do with the scheduler
    gc.collect()
    t0 = time.perf_counter()
    action.execute(ssn)
    dt = time.perf_counter() - t0
    # KBT_PIPELINE rows: the deferred replay/dispatch lands OUTSIDE the
    # timed region — that is the feature being measured. Join it before
    # reading the binder so binds stay complete, and before the next
    # repeat so sessions never overlap across the measurement boundary.
    pipeline.join_session(ssn)
    binds = dict(cache.binder.binds)  # task -> node, the actual placements
    close_session(ssn)
    return dt, binds, dict(getattr(action, "last_timings", {}))


def percentile(sorted_vals, p):
    """metric_util.go:45-68 shape: nearest-rank on the sorted sample."""
    import math

    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, math.ceil(p / 100 * len(sorted_vals)) - 1))
    return sorted_vals[k]


def timed(make_cluster, action_name: str, warm: bool, repeats: int = 2,
          action_args=None, compile_budget=None):
    """Warm run (jit compile at this bucket size) on a twin cluster, then
    N measured runs on fresh identical clusters. Returns
    (best_run, sorted_times, measured_compiles). The measured runs sit
    inside a CompileSentinel: after the warm run every repeat must hit
    the jit cache, so ``compile_budget=0`` turns a silent recompile (a
    shape bucket that stopped being stable, a new dict key riding the
    input pytree) into a loud bench failure instead of a mysteriously
    slow row."""
    from kube_batch_tpu.analysis.trace.sentinel import CompileSentinel

    if warm:
        run_session(make_cluster(), action_name, action_args)
    best = None
    times = []
    with CompileSentinel(f"bench:{action_name}", budget=compile_budget) as cs:
        for _ in range(repeats):
            res = run_session(make_cluster(), action_name, action_args)
            times.append(res[0])
            if best is None or res[0] < best[0]:
                best = res
    return best, sorted(times), cs.compiles


def reclaim_cluster(n_nodes=400):
    """Deterministic scale-up of tests/test_xla_reclaim's scene: qa
    (weight 1) holds 2 x 1-cpu running pods on each 2-cpu node; qb
    (weight 4) has n_nodes//4 pending 2-task gangs to reclaim for."""
    nodes = [
        build_node(f"n{i:04d}", build_resource_list(cpu=2, memory="2Gi", pods=8))
        for i in range(n_nodes)
    ]
    qa = build_queue("qa", weight=1)
    qb = build_queue("qb", weight=4)
    qa.metadata.creation_timestamp = 0.0
    qb.metadata.creation_timestamp = 1.0
    pods, pgs = [], []
    slot = 0
    for j in range((2 * n_nodes + 3) // 4):
        name = f"hog{j:04d}"
        pg = build_pod_group(name, queue="qa", min_member=0)
        pg.metadata.creation_timestamp = float(j)
        pgs.append(pg)
        for t in range(4):
            if slot >= 2 * n_nodes:
                break
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=f"n{slot // 2:04d}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=1,
                )
            )
            slot += 1
    for j in range(n_nodes // 4):
        name = f"starved{j:04d}"
        pg = build_pod_group(name, queue="qb", min_member=1)
        pg.metadata.creation_timestamp = float(j)
        pgs.append(pg)
        for t in range(2):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=5,
                )
            )
    return build_cluster(pods, nodes, pgs, [qa, qb])

def reclaim_session(action_name):
    cache = FakeCache(reclaim_cluster())
    ssn = open_session(cache, tiers())
    action = get_action(action_name)
    t0 = time.perf_counter()
    action.execute(ssn)
    dt = time.perf_counter() - t0
    evicts = list(cache.evictor.evicts)
    placements = {
        t.uid: (t.status, t.node_name)
        for j in ssn.jobs.values()
        for d in j.task_status_index.values()
        for t in d.values()
    }
    close_session(ssn)
    return dt, evicts, placements


def encode_cache_row(n_tasks: int = 100_000, n_nodes: int = 10_000) -> dict:
    """Warm-vs-cold encode (ISSUE 5 acceptance): the same session
    snapshot encoded twice (steady state: nothing changed between
    cycles), then re-encoded after a 1% node churn (label-flipping
    `set_node` replacements — the watch-event shape the dirty feed
    models). Parity is asserted in-row: the churned warm encode must be
    byte-identical to a fully cold encode of the same world."""
    from kube_batch_tpu.ops import encode_cache
    from kube_batch_tpu.ops.encode import encode_session

    cache = FakeCache(preempt_mix(n_tasks, n_nodes))
    ssn = open_session(cache, tiers())
    ec = encode_cache.get()

    def encode():
        t0 = time.perf_counter()
        enc = encode_session(
            ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64,
            drf=ssn.plugins.get("drf"),
            proportion=ssn.plugins.get("proportion"),
            session=ssn,
        )
        return time.perf_counter() - t0, enc

    from kube_batch_tpu.analysis.trace.sentinel import CompileSentinel

    ec.invalidate_all("bench")
    encode_cold_s, cold = encode()
    # Steady-state re-encode is pure host work riding the unit cache —
    # budget 0: an encode that starts compiling device programs has
    # grown a dependency the warm loop cannot afford.
    with CompileSentinel("bench:encode_warm", budget=0) as warm_cs:
        encode_warm_s, warm = encode()
    # 1% node churn: replace the Node object under 1% of NodeInfos
    for name in sorted(ssn.nodes)[: max(n_nodes // 100, 1)]:
        ni = ssn.nodes[name]
        node = build_node(
            name,
            build_resource_list(cpu=64, memory="256Gi", pods=110),
            labels={"bench/churned": "1"},
        )
        ni.set_node(node)
    encode_churn_s, churn = encode()
    warm_fraction = ec.warm_fraction
    ec.invalidate_all("bench")
    cold2_s, cold2 = encode()
    for k in cold2.arrays:
        a, b = np.asarray(cold2.arrays[k]), np.asarray(churn.arrays[k])
        assert a.shape == b.shape and np.array_equal(a, b), (
            f"churned warm encode diverges from cold on arrays[{k!r}]"
        )
    for k in cold.arrays:
        assert np.array_equal(
            np.asarray(cold.arrays[k]), np.asarray(warm.arrays[k])
        ), f"warm encode diverges from cold on arrays[{k!r}]"
    warm_speedup = round(encode_cold_s / encode_warm_s, 2)
    churn_speedup = round(encode_cold_s / encode_churn_s, 2)
    assert warm_speedup >= 2, (
        f"warm encode only {warm_speedup}x faster than cold; cache not engaging"
    )
    close_session(ssn)
    return {
        "tasks": n_tasks,
        "nodes": n_nodes,
        "encode_cold_s": round(encode_cold_s, 4),
        "encode_warm_s": round(encode_warm_s, 4),
        "encode_churn_s": round(encode_churn_s, 4),
        "warm_speedup": warm_speedup,
        "churn_speedup": churn_speedup,
        "warm_fraction": round(warm_fraction, 4),
        "warm_encode_compiles": warm_cs.compiles,
        "arrays_byte_identical": True,
        "note": (
            "same-session re-encode (steady state) and 1%-node-churn "
            "re-encode vs a cold encode; KBT_ENCODE_CACHE default-on"
        ),
    }


def sustained_arrival_row(
    resident_gangs: int = 1000,
    resident_members: int = 100,
    n_nodes: int = 1000,
    probe_gangs: int = 6,
    sustained_gangs: int = 40,
    arrival_members: int = 8,
    rate_pods_s: float = 400.0,
) -> dict:
    """Streaming mode (ISSUE 8): open-loop sustained arrivals against a
    100k-pod resident snapshot.

    A real ClusterStore is seeded with ``resident_gangs x
    resident_members`` bound Running pods on ``n_nodes`` nodes; one full
    cycle adopts the resident node table, then every subsequent bind
    goes through event-driven micro-cycles (the backstop period is 60 s,
    far past the row's window). Three phases:

    - warmup: two gangs pay the micro path's trace+compile;
    - probes: single-gang arrivals inside a ``CompileSentinel`` with
      budget 0 — the p50 here is the headline time-to-bind claim, and
      any recompile on a warm micro-cycle fails the row;
    - sustained: Poisson gang arrivals at ``rate_pods_s`` with node
      churn (label-flip updates through the resident patch path) every
      10th gang, reporting sustained pods/s and p50/p90/p99 per-pod
      time-to-bind.

    Parity is asserted in-row: a twin store with the same resident
    world and the same arrival set placed by ONE full cycle must be
    bind-for-bind identical. The conf carries no drf/proportion — micro
    tiers exclude the fairness sweeps by design, so the parity claim is
    stated over the plugin set both paths share.
    """
    import tempfile
    import threading
    import random as _random

    from kube_batch_tpu.analysis.trace.sentinel import CompileSentinel
    from kube_batch_tpu.apis.types import PodPhase
    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.cache.store import PODS, EventHandler
    from kube_batch_tpu.scheduler import Scheduler

    conf_tmpl = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: {streaming}
"""
    resident_pods = resident_gangs * resident_members

    def seed(store: ClusterStore) -> None:
        store.create_queue(build_queue("default"))
        for i in range(n_nodes):
            store.create_node(
                build_node(
                    f"n{i}", build_resource_list(cpu=128, memory="256Gi", pods=110)
                )
            )
        for g in range(resident_gangs):
            store.create_pod_group(
                build_pod_group(f"r{g}", min_member=resident_members)
            )
            for m in range(resident_members):
                store.create_pod(
                    build_pod(
                        name=f"r{g}-p{m}", group_name=f"r{g}",
                        node_name=f"n{(g * resident_members + m) % n_nodes}",
                        phase=PodPhase.RUNNING,
                        req=build_resource_list(cpu=1, memory="2Gi"),
                    )
                )

    # the arrival script, shared verbatim by both runs so creation order
    # (and with it job_order) is identical: (gang name, member count)
    script = (
        [(f"w{i}", arrival_members) for i in range(2)]
        + [(f"s{i}", arrival_members) for i in range(probe_gangs)]
        + [(f"a{i}", arrival_members) for i in range(sustained_gangs)]
    )

    def arrive(store, name, members, stamps=None):
        store.create_pod_group(build_pod_group(name, min_member=members))
        for m in range(members):
            key = f"default/{name}-p{m}"
            if stamps is not None:
                stamps[key] = time.perf_counter()
            store.create_pod(
                build_pod(
                    name=f"{name}-p{m}", group_name=name,
                    req=build_resource_list(cpu=1, memory="2Gi"),
                )
            )

    def churn(store, i):
        node = build_node(
            f"n{i}", build_resource_list(cpu=128, memory="256Gi", pods=110),
            labels={"bench/churned": "1"},
        )
        store.update_node(node)

    # -- streaming run -------------------------------------------------------
    store = ClusterStore()
    seed(store)
    binds: dict[str, tuple[float, str]] = {}  # pod key -> (stamp, node)

    def on_update(old, new):
        if not old.node_name and new.node_name:
            binds[f"{new.namespace}/{new.name}"] = (
                time.perf_counter(), new.node_name
            )

    store.add_event_handler(PODS, EventHandler(on_update=on_update))
    cache = SchedulerCache(store)
    arrivals: dict[str, float] = {}

    def gang_bound(name, members):
        return all(f"default/{name}-p{m}" in binds for m in range(members))

    def wait_gang(name, members, timeout=60.0):
        deadline = time.monotonic() + timeout
        while not gang_bound(name, members):
            if time.monotonic() > deadline:
                raise AssertionError(f"gang {name} not bound within {timeout}s")
            time.sleep(0.0002)

    probe_lat: list[float] = []
    with tempfile.TemporaryDirectory() as tmp:
        conf_path = os.path.join(tmp, "stream.yaml")
        with open(conf_path, "w", encoding="utf-8") as fh:
            fh.write(conf_tmpl.format(streaming="true"))
        sched = Scheduler(cache, scheduler_conf=conf_path, schedule_period=60.0)
        stop = threading.Event()
        t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 120.0
            while True:  # the initial full cycle adopts the resident table
                st = sched._stream_state
                if st is not None and st.valid:
                    break
                assert time.monotonic() < deadline, "resident table never adopted"
                time.sleep(0.01)
            it = iter(script)
            for name, members in (next(it), next(it)):  # warmup: compiles land
                arrive(store, name, members, arrivals)
                wait_gang(name, members)
            # warm single-gang probes: zero-compile enforced
            with CompileSentinel("bench:stream_micro_warm", budget=0) as cs:
                for _ in range(probe_gangs):
                    name, members = next(it)
                    t0 = time.perf_counter()
                    arrive(store, name, members, arrivals)
                    wait_gang(name, members)
                    probe_lat.append(time.perf_counter() - t0)
            # open-loop sustained phase: Poisson arrivals + node churn
            rng = _random.Random(7)
            sustained_start = time.perf_counter()
            for g in range(sustained_gangs):
                name, members = next(it)
                arrive(store, name, members, arrivals)
                if g % 10 == 9:
                    churn(store, g)  # resident node-patch path
                time.sleep(rng.expovariate(rate_pods_s / arrival_members))
            for g in range(sustained_gangs):
                wait_gang(f"a{g}", arrival_members)
            micro_cycles = sched.micro_cycles_run
        finally:
            stop.set()
            t.join(timeout=30.0)
    sustained_keys = [
        f"default/a{g}-p{m}"
        for g in range(sustained_gangs)
        for m in range(arrival_members)
    ]
    lat = sorted(binds[k][0] - arrivals[k] for k in sustained_keys)
    span = max(binds[k][0] for k in sustained_keys) - sustained_start
    stream_placed = {k: v[1] for k, v in binds.items()}
    probe_lat.sort()

    # -- full-cycle parity twin ---------------------------------------------
    twin = ClusterStore()
    seed(twin)
    for name, members in script:
        arrive(twin, name, members)
    for g in range(sustained_gangs):
        if g % 10 == 9:
            churn(twin, g)
    twin_cache = SchedulerCache(twin)
    with tempfile.TemporaryDirectory() as tmp:
        conf_path = os.path.join(tmp, "full.yaml")
        with open(conf_path, "w", encoding="utf-8") as fh:
            fh.write(conf_tmpl.format(streaming="false"))
        twin_sched = Scheduler(twin_cache, scheduler_conf=conf_path)
        twin_sched.run_once()
    twin_placed = {
        f"{p.namespace}/{p.name}": p.node_name
        for p in twin.list(PODS)
        if not p.name.startswith("r") and p.node_name
    }
    assert stream_placed == twin_placed, (
        f"streaming placements diverge from the full-cycle twin on "
        f"{len(set(stream_placed.items()) ^ set(twin_placed.items()))} entries"
    )
    p50_single_ms = percentile(probe_lat, 50) * 1e3
    assert p50_single_ms < 10.0, (
        f"single-gang p50 time-to-bind {p50_single_ms:.2f}ms >= 10ms target"
    )
    return {
        "resident_pods": resident_pods,
        "nodes": n_nodes,
        "arrival_pods": len(script) * arrival_members,
        "micro_cycles": micro_cycles,
        "p50_single_gang_bind_ms": round(p50_single_ms, 3),
        "measured_compiles": cs.compiles,
        "sustained_pods_per_s": round(len(sustained_keys) / span, 1),
        "offered_pods_per_s": rate_pods_s,
        "time_to_bind_p50_ms": round(percentile(lat, 50) * 1e3, 3),
        "time_to_bind_p90_ms": round(percentile(lat, 90) * 1e3, 3),
        "time_to_bind_p99_ms": round(percentile(lat, 99) * 1e3, 3),
        "placements_equal_full_cycle": True,
        "note": (
            "open-loop Poisson gang arrivals + node churn vs a resident "
            "100k-pod world; binds via event-driven micro-cycles (60s "
            "backstop period); conf without drf/proportion (micro tiers "
            "exclude the fairness sweeps); probes run under a zero-budget "
            "CompileSentinel"
        ),
    }


def failover_mttr_row(sessions: int = 5) -> dict:
    """Leader SIGKILL mid-`bind_many` -> first successful standby bind
    (see the call site for the simulation's honesty notes)."""
    import tempfile
    import threading  # noqa: F401  (kept parallel with server wiring)

    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.cache.cache import StoreBinder
    from kube_batch_tpu.cache.store import PODS, EventHandler
    from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import StoreLeaseElector

    lease_duration = 1.0
    gang_size, die_after = 64, 16

    class _Killed(BaseException):
        pass

    class DyingBinder(StoreBinder):
        def __init__(self, store, left):
            super().__init__(store)
            self.left = left

        def bind(self, pod, hostname):
            if self.left <= 0:
                raise _Killed()
            self.left -= 1
            super().bind(pod, hostname)

    conf = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""
    # The row measures the BULK path (one journaled statement for the
    # whole gang, killed mid-batch): pin the device path so the size
    # floor cannot reroute this small gang to per-bind serial dispatch.
    saved_floor = os.environ.get("KBT_MIN_DEVICE_PAIRS")
    os.environ["KBT_MIN_DEVICE_PAIRS"] = "0"
    mttrs, redispatched = [], 0
    with tempfile.TemporaryDirectory() as tmp:
        conf_path = os.path.join(tmp, "conf.yaml")
        with open(conf_path, "w", encoding="utf-8") as fh:
            fh.write(conf)
        for s in range(sessions):
            store = ClusterStore()
            store.create_queue(build_queue("default"))
            for i in range(8):
                store.create_node(
                    build_node(
                        f"n{i}", build_resource_list(cpu=32, memory="64Gi", pods=64)
                    )
                )
            store.create_pod_group(build_pod_group("gang", min_member=gang_size))
            for m in range(gang_size):
                store.create_pod(
                    build_pod(
                        name=f"p{m:03d}", group_name="gang",
                        req=build_resource_list(cpu=1, memory="512Mi"),
                    )
                )
            journal_path = os.path.join(tmp, f"leader-{s}.wal")
            leader_journal = WriteIntentJournal(journal_path)
            cache = SchedulerCache(
                store, binder=DyingBinder(store, die_after), journal=leader_journal
            )
            sched = Scheduler(cache, scheduler_conf=conf_path, schedule_period=0.05)
            leader = StoreLeaseElector(
                store, "kb-mttr", f"leader-{s}", lease_duration=lease_duration,
                renew_deadline=0.7, retry_period=0.1,
            )
            assert leader.acquire(blocking=False)
            first_bind = {}

            def on_update(old, new, fb=first_bind):
                if not old.node_name and new.node_name and "t" not in fb:
                    fb["t"] = time.perf_counter()

            try:
                sched.run_once()
            except _Killed:
                pass
            t_kill = time.perf_counter()
            first_bind.clear()  # only standby binds stop the clock
            store.add_event_handler(PODS, EventHandler(on_update=on_update))
            # standby: contends on the lease (crash path: waits out the
            # remaining window), then reconciles the journal
            standby = StoreLeaseElector(
                store, "kb-mttr", f"standby-{s}", lease_duration=lease_duration,
                renew_deadline=0.7, retry_period=0.1,
            )
            assert standby.acquire(blocking=True)
            standby_journal = WriteIntentJournal(journal_path)
            report = reconcile_journal(standby_journal, store)
            redispatched += report.redispatched
            assert "t" in first_bind, "standby never bound"
            assert all(p.node_name for p in store.list("pods")), "lost binds"
            mttrs.append(first_bind["t"] - t_kill)
            standby_journal.close()
            leader_journal.close()
            standby.release()
    if saved_floor is None:
        os.environ.pop("KBT_MIN_DEVICE_PAIRS", None)
    else:
        os.environ["KBT_MIN_DEVICE_PAIRS"] = saved_floor
    mttrs.sort()
    return {
        "sessions": sessions,
        "p50_s": round(percentile(mttrs, 50), 4),
        "p90_s": round(percentile(mttrs, 90), 4),
        "lease_duration_s": lease_duration,
        "gang_size": gang_size,
        "binds_landed_before_kill": die_after,
        "binds_redispatched_total": redispatched,
        "note": (
            "in-process SIGKILL simulation: write pool dies mid-bulk-bind; "
            "MTTR = leader death -> first standby bind (lease wait-out + "
            "journal reconciliation)"
        ),
    }


def federation_kill_mttr_row(sessions: int = 5) -> dict:
    """Federated kill-and-adopt MTTR (ISSUE 16): four leased shard
    owners over one store, one killed mid-``bind_many`` (its binder
    raises on every subsequent dispatch and its slot manager stops
    renewing without releasing — the SIGKILL shape). A survivor must
    win the expired slot lease, reconcile the dead owner's write-intent
    journal, and re-drive the orphaned backlog.

    MTTR = kill -> first bind landing in the victim's slot; the row
    reports p50/p90 over ``sessions`` runs plus the lease-takeover
    latencies. Correctness (exactly-once, union parity vs a
    single-scheduler twin, fsck-clean store, single adopter) is
    asserted per session by ``smoke_kill_one`` itself. Acceptance:
    p50 <= lease TTL + renew period.
    """
    from kube_batch_tpu.federation import smoke_kill_one

    lease_s, renew_s = 1.0, 0.25
    mttrs, takeovers = [], []
    for _ in range(sessions):
        out = smoke_kill_one(
            shards=4, gangs=16, members=2, lease_s=lease_s, renew_s=renew_s
        )
        assert out["ok"], f"kill drill failed: {out}"
        mttrs.append(out["mttr_s"])
        takeovers.append(out["takeover_s"])
    mttrs.sort()
    takeovers.sort()
    return {
        "sessions": sessions,
        "p50_s": round(percentile(mttrs, 50), 4),
        "p90_s": round(percentile(mttrs, 90), 4),
        "takeover_p50_s": round(percentile(takeovers, 50), 4),
        "takeover_p90_s": round(percentile(takeovers, 90), 4),
        "lease_duration_s": lease_s,
        "renew_period_s": renew_s,
        "shards": 4,
        "p50_within_lease_window": percentile(mttrs, 50) <= lease_s + renew_s,
        "note": (
            "leased-slot federation kill drill: victim's binder dies "
            "mid-bind_many, survivor adopts the expired slot lease, "
            "reconciles the dead WAL and re-drives the backlog; MTTR = "
            "kill -> first bind in the victim's slot"
        ),
    }


def admission_storm_row(duration_s: float = 8.0) -> dict:
    """Overload-hardened streaming federation (ISSUE 18): the live
    federated storm — Poisson tenant lanes offered at ~5x capacity
    against 2 streaming shards behind the admission front door — run
    as three cells: admission ON (the protected high lane's tail and
    zero shed), admission OFF (the measured collapse that motivates
    the gate), and ON + SIGKILL'd shard (adoption MTTR under sustained
    overload). Exactly-once, fsck, drain and listener hygiene are
    asserted per cell by the drill itself; this row flattens the
    headline numbers into directional bench_diff columns
    (``storm_high_p99_s``/``storm_mttr_s`` lower-better,
    ``storm_goodput_pods_per_s`` higher-better, ``storm_shed_*``
    informational)."""
    from kube_batch_tpu.admission import storm_row

    r = storm_row(shards=2, duration_s=duration_s)
    assert r["ok"], f"storm drill failed: {r}"
    on, off, kill = r["on"], r["off"], r["kill"]
    return {
        "duration_s": duration_s,
        "shards": on["shards"],
        "storm_goodput_pods_per_s": on["pods_per_s"],
        "storm_high_p99_s": on["lane_p99_s"].get("high"),
        "storm_mttr_s": kill["mttr_s"],
        "storm_shed_high": on["shed"].get("high", 0),
        "storm_shed_batch": on["shed"].get("batch", 0),
        "storm_shed_low": on["shed"].get("low", 0),
        # the collapse the gate prevents, kept for the narrative diff
        "off_high_p99_s": off["lane_p99_s"].get("high"),
        "off_bound": off["bound"],
        "brownout_level_final": on["brownout_level_final"],
        "journal_orphans": kill["journal_orphans"],
        "exactly_once": bool(
            on["exactly_once"] and off["exactly_once"] and kill["exactly_once"]
        ),
        "note": (
            "live federated storm, 3 cells (on/off/kill): per-tenant "
            "token-bucket lanes + fleet-SLO brownout ladder in front of "
            "2 streaming shards at ~5x offered load; MTTR cell kills "
            "one shard mid-storm and measures adoption recovery"
        ),
    }


def federation_scaleout_row(
    gangs: int = 5000,
    members: int = 10,
    n_nodes: int = 5000,
    shard_counts: tuple = (1, 2, 4, 8),
) -> dict:
    """Sharded federation scale-out (ISSUE 10): N active schedulers over
    ONE shared store (the in-process backend shape), each owning
    ``crc32(gang) mod N`` of a 50k-pod pending world, racing on full
    cluster capacity with optimistic conditional binds.

    Per shard count the row reports wall-clock to drain the backlog,
    aggregate binds/s, and the conflict economics from the metrics
    counters (``federation_conflicts_total{outcome}``,
    ``bind_retries_total``). Correctness is asserted in-row for every N:
    the union placement is fsck-clean (no orphans, no over-capacity
    node, no allocation-ledger drift) and every pod bound exactly once
    (a store-side handler counts ""->node transitions per pod).
    """
    import tempfile
    import threading

    from kube_batch_tpu import metrics
    from kube_batch_tpu.cache import ClusterStore
    from kube_batch_tpu.cache.store import PODS, EventHandler
    from kube_batch_tpu.federation import FederatedCache, fsck
    from kube_batch_tpu.scheduler import Scheduler

    # micro-conf without the O(cluster) fairness sweeps: the row measures
    # dispatch contention, not drf/proportion session-open cost
    conf = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""
    total = gangs * members

    def seed(store: ClusterStore) -> None:
        store.create_queue(build_queue("default"))
        for i in range(n_nodes):
            store.create_node(
                build_node(
                    f"n{i}", build_resource_list(cpu=16, memory="32Gi", pods=32)
                )
            )
        for g in range(gangs):
            store.create_pod_group(build_pod_group(f"f{g}", min_member=members))
            for m in range(members):
                store.create_pod(
                    build_pod(
                        name=f"f{g}-p{m}", group_name=f"f{g}",
                        req=build_resource_list(cpu=1, memory="1Gi"),
                    )
                )

    def conflict_totals() -> dict:
        return {
            "clean": metrics.federation_conflicts.value({"outcome": "clean"}),
            "won": metrics.federation_conflicts.value({"outcome": "won"}),
            "retried": metrics.federation_conflicts.value({"outcome": "retried"}),
            "lost": metrics.federation_conflicts.value({"outcome": "lost"}),
            "bind_retries": metrics.bind_retries.value(),
        }

    # the row measures GANG-transaction contention: pin the device path
    # so the size floor cannot reroute small worlds to per-pod serial
    # dispatch (which never opens an all-or-nothing gang transaction)
    saved_floor = os.environ.get("KBT_MIN_DEVICE_PAIRS")
    os.environ["KBT_MIN_DEVICE_PAIRS"] = "0"
    runs = []
    with tempfile.TemporaryDirectory() as tmp:
        conf_path = os.path.join(tmp, "fed.yaml")
        with open(conf_path, "w", encoding="utf-8") as fh:
            fh.write(conf)
        for shards in shard_counts:
            store = ClusterStore()
            seed(store)
            bind_counts: dict[str, int] = {}
            counts_lock = threading.Lock()

            def on_update(old, new, bc=bind_counts, lk=counts_lock):
                if not old.node_name and new.node_name:
                    with lk:
                        key = f"{new.namespace}/{new.name}"
                        bc[key] = bc.get(key, 0) + 1

            store.add_event_handler(PODS, EventHandler(on_update=on_update))
            before = conflict_totals()
            caches = [
                FederatedCache(store, shard=i, shards=shards, shard_key="gang")
                for i in range(shards)
            ]
            stop = threading.Event()
            threads = []
            t0 = time.perf_counter()
            for i, cache in enumerate(caches):
                sched = Scheduler(
                    cache, scheduler_conf=conf_path, schedule_period=0.02
                )
                th = threading.Thread(
                    target=sched.run, args=(stop,), name=f"kb-fed-{i}", daemon=True
                )
                th.start()
                threads.append(th)
            deadline = time.monotonic() + 600.0
            while time.monotonic() < deadline:
                with counts_lock:
                    done = len(bind_counts) >= total
                if done:
                    break
                time.sleep(0.01)
            drain_s = time.perf_counter() - t0
            stop.set()
            for th in threads:
                th.join(timeout=30.0)
            for cache in caches:
                cache.stop()
            after = conflict_totals()
            with counts_lock:
                doubles = sum(1 for v in bind_counts.values() if v > 1)
                bound = len(bind_counts)
            violations = fsck(store)
            assert bound == total, (
                f"federation N={shards}: {bound}/{total} pods bound"
            )
            assert doubles == 0, f"federation N={shards}: {doubles} double-binds"
            assert not violations, f"federation N={shards}: fsck {violations}"
            delta = {k: after[k] - before[k] for k in after}
            runs.append(
                {
                    "shards": shards,
                    "drain_s": round(drain_s, 3),
                    "binds_per_s": round(total / drain_s, 1),
                    "conflicts": {
                        k: int(delta[k])
                        for k in ("clean", "won", "retried", "lost")
                    },
                    "bind_retries": int(delta["bind_retries"]),
                    "exactly_once": True,
                    "fsck_clean": True,
                }
            )
    if saved_floor is None:
        os.environ.pop("KBT_MIN_DEVICE_PAIRS", None)
    else:
        os.environ["KBT_MIN_DEVICE_PAIRS"] = saved_floor
    return {
        "pods": total,
        "nodes": n_nodes,
        "gangs": gangs,
        "runs": runs,
        "note": (
            "N active FederatedCache schedulers over one shared store "
            "(in-process backend shape); optimistic conditional gang binds, "
            "losers re-snapshot + retry; exactly-once and fsck asserted per N"
        ),
    }


def federation_wire_runs(
    gangs: int = 200,
    members: int = 2,
    nodes: int = 100,
    shard_counts: tuple = (1, 2, 4, 8),
) -> list:
    """Wire-transport ladder (ISSUE 17): the NETWORKED federation shape —
    N scheduler processes' worth of LoopbackBackends over one real
    SchedulerServer on loopback — measured per (protocol, N) cell with
    the whole topology pinned to wire generation v1 (fresh-connection
    JSON, per-kind polling, per-gang conditional writes) vs v2 (pooled
    keep-alive, binary framing, delta long-poll, coalesced gang txns).

    Each cell is one subprocess (``python -m kube_batch_tpu.federation
    --json --wire-protocol P``): sequential in-process smokes leak
    scheduler threads and breaker state into each other's clocks, and a
    fresh interpreter also gives every cell the same cold-start bill.
    Every cell asserts its own exactly-once + union-parity + fsck bits
    (they ride the row for bench_diff's parity gate); a cell that fails
    them fails the bench. Columns: ``binds_per_s`` (wall-clock drain),
    ``wire_bytes_per_bind`` (protocol bytes both directions / binds),
    ``backend_rtt_p50_s`` (timed version round-trips), ``txn_batches``/
    ``txn_batch_mean`` (v2 coalescing depth; structurally 0 under v1).
    """
    import subprocess

    runs = []
    for shards in shard_counts:
        for proto, codec in ((1, "json"), (2, "binary")):
            cmd = [
                sys.executable, "-m", "kube_batch_tpu.federation", "--json",
                "--wire-protocol", str(proto), "--codec", codec,
                "--shards", str(shards), "--gangs", str(gangs),
                "--members", str(members), "--nodes", str(nodes),
                "--rtt-probes", "16", "--bulk",
            ]
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            res = subprocess.run(cmd, capture_output=True, text=True, env=env)
            try:
                row = json.loads(res.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                raise AssertionError(
                    f"wire cell v{proto} N={shards} produced no summary "
                    f"(rc={res.returncode}): {res.stderr[-2000:]}"
                )
            assert row.get("ok"), f"wire cell v{proto} N={shards}: {row}"
            assert row.get("exactly_once") and row.get("union_parity"), (
                f"wire cell v{proto} N={shards} lost correctness: {row}"
            )
            runs.append({
                "protocol": row["protocol"],
                "codec": row["codec"],
                "shards": shards,
                "pods": row["pods"],
                "elapsed_s": row["elapsed_s"],
                "binds_per_s": row["binds_per_s"],
                "wire_bytes_per_bind": row["wire_bytes_per_bind"],
                "backend_rtt_p50_s": row["backend_rtt_p50_s"],
                "txn_batches": row["txn_batches"],
                "txn_batch_mean": row["txn_batch_mean"],
                "exactly_once": row["exactly_once"],
                "union_parity": row["union_parity"],
                "fsck_clean": not row["fsck_violations"],
            })
    # the headline claim, asserted where the numbers are made: at the
    # contended shard counts the v2 transport must beat its in-row v1
    # twin on throughput and be strictly leaner per bind
    by_cell = {(r["protocol"], r["shards"]): r for r in runs}
    for n in (4, 8):
        v1, v2 = by_cell[(1, n)], by_cell[(2, n)]
        assert v2["binds_per_s"] > v1["binds_per_s"], (
            f"wire N={n}: v2 {v2['binds_per_s']} binds/s did not beat "
            f"v1 {v1['binds_per_s']}"
        )
        assert v2["wire_bytes_per_bind"] < v1["wire_bytes_per_bind"], (
            f"wire N={n}: v2 bytes/bind {v2['wire_bytes_per_bind']} not "
            f"below v1 {v1['wire_bytes_per_bind']}"
        )
    return runs


def main() -> None:
    from kube_batch_tpu.ops import enable_compilation_cache

    enable_compilation_cache()
    # The bench validates the DEVICE path against the serial baseline on
    # every row, including the tiny gang config — disable the production
    # size floor that would route small snapshots to the serial allocator
    # (the floor itself is covered by tests/test_xla_allocate.py).
    os.environ.setdefault("KBT_MIN_DEVICE_PAIRS", "0")
    details = {}
    binds_by_row = {}  # row name -> placement dict, for in-row parity asserts
    full_serial = os.environ.get("KBT_BENCH_FULL_SERIAL") == "1"

    def record(name, make_cluster, serial, sessions=5, action_args=None,
               env=None, compile_budget=None):
        deferred = (env or {}).get("KBT_PIPELINE", "").lower() in (
            "1", "true", "on", "yes"
        )
        saved = {}
        for k, v in (env or {}).items():
            saved[k] = os.environ.get(k)
            os.environ[k] = v
        if deferred:
            # a sticky degradation left over from an earlier row would
            # silently serialize this one and invalidate its column
            pipeline.reset()
        # per-row HBM watermark: the arena's high-water mark is monotonic
        # across uploads, so zero it here or a 400k row would pollute the
        # small rows after it
        arena = get_action("xla_allocate")._arena
        arena.hbm_watermark_bytes = 0
        overlap_fraction = None
        try:
            (xla_s, binds, t), times, compiles = timed(
                make_cluster, "xla_allocate", warm=True, repeats=sessions,
                action_args=action_args, compile_budget=compile_budget,
            )
            if deferred:
                assert pipeline.fence._dispatch_s > 0.0, (
                    f"{name}: KBT_PIPELINE row never deferred a dispatch "
                    "— the pipelined path did not engage"
                )
                assert pipeline.fence.degraded_reason is None, (
                    f"{name}: pipeline degraded mid-row: "
                    f"{pipeline.fence.degraded_reason}"
                )
                # capture the measured overlap BEFORE the finally's
                # pipeline.reset() clears it: join-window vs
                # dispatch-window intersection, not a wall-clock guess
                overlap_fraction = pipeline.fence.last_overlap_fraction
                assert overlap_fraction is not None, (
                    f"{name}: KBT_PIPELINE row recorded no overlap sample"
                )
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            if deferred:
                pipeline.reset()
        entry = {
            "xla_s": round(xla_s, 4),
            "binds": len(binds),
            "sessions": sessions,
            "p50_s": round(percentile(times, 50), 4),
            # compiles during the MEASURED repeats (the warm twin already
            # ran): nonzero means a row is paying trace+compile, not solve
            "measured_compiles": compiles,
        }
        if sessions >= 5:
            # tail percentiles are only honest with enough samples; a
            # short row (big configs) reports median + min only
            entry["p90_s"] = round(percentile(times, 90), 4)
            entry["p99_s"] = round(percentile(times, 99), 4)
        for k, v in t.items():
            entry[k] = round(v, 4)
        # Device-phase columns (ISSUE 14): HBM high-water mark of the
        # arena's resident slabs (both banks count in pipelined mode),
        # and — pipelined rows only — the measured overlap fraction.
        if arena.hbm_watermark_bytes:
            entry["arena_hbm_watermark_bytes"] = int(arena.hbm_watermark_bytes)
        if overlap_fraction is not None:
            entry["pipeline_overlap_fraction"] = round(overlap_fraction, 4)
        # Phase breakdown on every row (ISSUE 11): where the best run's
        # wall time went — encode vs solve vs dispatch (replay + write
        # submit) — from the action's own perf_counter bookkeeping, so
        # the timed region runs with KBT_TRACE off and the row costs no
        # tracing overhead. "other_s" is the untracked remainder
        # (session plumbing, plugin callbacks).
        if "encode_s" in t:
            phases = {
                "encode_s": round(t.get("encode_s", 0.0), 4),
                "solve_s": round(t.get("solve_s", 0.0), 4),
            }
            if deferred:
                # the dispatch ran outside the timed region (overlapped
                # with what would be the next cycle): report it as its
                # own column, excluded from the in-row wall accounting
                entry["dispatch_deferred_s"] = round(t.get("replay_s", 0.0), 4)
            else:
                phases["dispatch_s"] = round(t.get("replay_s", 0.0), 4)
            if "explain_s" in t and not deferred:
                # unschedulability forensics ran inside the measured
                # region (KBT_EXPLAIN on): surface it as its own column
                # so the <5%-of-xla_s overhead claim is measured, not
                # asserted. (With KBT_PIPELINE it rides the deferred
                # post-solve phase, outside the timed region.)
                phases["explain_s"] = round(t["explain_s"], 4)
            phases["other_s"] = round(
                max(0.0, xla_s - sum(phases.values())), 4
            )
            entry["phase_breakdown"] = phases
            # The breakdown must ACCOUNT for the row: other_s absorbs
            # any shortfall, so the sum can only diverge upward — and an
            # overshoot beyond 5% means the action's per-phase
            # bookkeeping double-counts wall time. Fail the row rather
            # than publish a breakdown that doesn't add up.
            total = sum(phases.values())
            assert abs(total - xla_s) <= 0.05 * xla_s + 1e-3, (
                f"{name}: phase_breakdown sums to {total:.4f}s, "
                f"{abs(total - xla_s) / max(xla_s, 1e-9):.1%} off "
                f"xla_s={xla_s:.4f}s"
            )
        if serial == "live" or (serial == "cached" and full_serial):
            (serial_s, s_binds, _), _, _ = timed(
                make_cluster, "allocate", warm=False, repeats=1
            )
            entry["serial_s"] = round(serial_s, 4)
            # PLACEMENT equality, not just counts (VERDICT r4 item 4):
            # with the comparison-dtype numerics (api/numerics.py) the
            # f32 device solve and the serial float oracle are
            # bind-for-bind identical — x64 off.
            assert s_binds == binds, (
                f"{name}: serial/xla placements diverge on "
                f"{sum(1 for k in s_binds if k in binds and binds[k] != s_binds[k]) + len(set(binds) ^ set(s_binds))} tasks"
            )
            entry["placements_equal_serial"] = True
        elif serial == "cached":
            cached = SERIAL_MEASURED.get(name)
            if cached is not None:
                entry["serial_s"] = cached["seconds"]
                entry["serial_s_note"] = "measured once via " + cached["provenance"]
        details[name] = entry
        binds_by_row[name] = binds
        return entry

    record("gang_example", gang_example, serial="live")
    record("synthetic_1k_100", lambda: synthetic(1000, 100), serial="live")
    record("multi_queue_10k_1k", lambda: multi_queue(10_000, 1000), serial="live")
    # Routine at-scale parity (VERDICT r5): one >=25k-task row with a
    # LIVE serial twin asserting placements_equal_serial on every bench
    # run — the 50k serial twin is too slow to re-measure each round
    # (~26 min), so this row is the standing at-scale honesty check
    # (~2.5 min serial at ~6us/pair).
    record("preempt_25k_1k", lambda: preempt_mix(25_000, 1000), serial="live")
    # The headline row pins its compile budget: after the warm twin, the
    # 5 measured 50k×5k sessions must not compile anything (ISSUE 7 —
    # CompileSentinel raises on a silent recompile instead of letting it
    # masquerade as solver regression).
    e50k = record("preempt_50k_5k", lambda: preempt_mix(50_000, 5000),
                  serial="cached", compile_budget=0)
    # The same headline config with KBT_PIPELINE (ISSUE 13): the
    # replay/dispatch phase is deferred off the timed region — the
    # overlap a cycle sequence gets for free — so the pipelined column
    # must (a) place bind-for-bind identically to the synchronous
    # column, (b) show the dispatch phase in its own deferred column,
    # and (c) be no slower; the speedup equals the dispatch share of
    # the synchronous cycle (README "Pipelined cycles" for the split).
    # Same zero-recompile budget as the synchronous headline row.
    p50k = record(
        "preempt_50k_5k_pipelined",
        lambda: preempt_mix(50_000, 5000),
        serial="none",
        compile_budget=0,
        env={"KBT_PIPELINE": "1"},
    )
    assert binds_by_row["preempt_50k_5k_pipelined"] == binds_by_row["preempt_50k_5k"], (
        "pipelined 50k placements diverge from the synchronous column"
    )
    p50k["placements_equal_synchronous"] = True
    assert p50k["dispatch_deferred_s"] > 0.0, (
        "pipelined 50k row shows no deferred dispatch"
    )
    p50k["p50_speedup_vs_sync_pct"] = round(
        100.0 * (1.0 - p50k["p50_s"] / e50k["p50_s"]), 1
    )
    assert p50k["p50_s"] <= 1.10 * e50k["p50_s"], (
        f"pipelined 50k p50 {p50k['p50_s']}s regressed past the "
        f"synchronous column {e50k['p50_s']}s"
    )
    record("multi_tenant_ml", lambda: multi_tenant_ml(), serial="live")
    # Scale headroom rows (SURVEY section 8's 100k claim + the v5e
    # VMEM-budget envelope at 4x the reference's headline, measured):
    record(
        "preempt_100k_10k",
        lambda: preempt_mix(100_000, 10_000),
        serial="cached",
    )
    record(
        "preempt_200k_20k",
        lambda: preempt_mix(200_000, 20_000),
        serial="none",
        sessions=5,
    )
    # The single-chip envelope row (VERDICT r4 item 5): a full session —
    # encode + solve + replay + dispatch — at 8x the reference's headline
    # scale, END TO END (replacing the README's former solve-only claim).
    # sessions=5 so the flagship row carries p50/p90/p99 like every other
    # row (VERDICT r5 Weak #3).
    e400k = record(
        "preempt_400k_40k",
        lambda: preempt_mix(400_000, 40_000),
        serial="none",
        sessions=5,
    )
    # Pipelined column at the envelope scale (ISSUE 13): at 400k the
    # dispatch phase is ~15% of the cycle (r5: 0.95s of 6.5s), so the
    # deferral is worth measuring here, not just at the headline size.
    p400k = record(
        "preempt_400k_40k_pipelined",
        lambda: preempt_mix(400_000, 40_000),
        serial="none",
        sessions=5,
        env={"KBT_PIPELINE": "1"},
    )
    assert binds_by_row["preempt_400k_40k_pipelined"] == binds_by_row["preempt_400k_40k"], (
        "pipelined 400k placements diverge from the synchronous column"
    )
    p400k["placements_equal_synchronous"] = True
    assert p400k["dispatch_deferred_s"] > 0.0, (
        "pipelined 400k row shows no deferred dispatch"
    )
    p400k["p50_speedup_vs_sync_pct"] = round(
        100.0 * (1.0 - p400k["p50_s"] / e400k["p50_s"]), 1
    )

    # -- node-class compressed solve (ISSUE 20) ---------------------------
    # The compression headline: the same snapshots solved with
    # KBT_CLASS_COMPRESS=1, bind-for-bind parity asserted in-row against
    # the uncompressed column, with the class table's own columns
    # (class_count / compression_ratio / splits / segments and the
    # group_s-vs-kernel_s solve-cost split) recorded from the action's
    # last_class_stats — the honesty evidence that the solve ran at
    # class granularity, not a silent fallback. `uniform_pool` is the
    # high-duplication world (dozens of classes across 40k nodes, ~1%
    # of nodes carrying churned residents); `preempt_mix` rides the
    # same columns at the flagship mix. sessions=2 like the other
    # auxiliary envelope rows — these are honesty columns, not tail
    # percentile claims.
    def class_columns(row):
        action = get_action("xla_allocate")
        row["solver"] = action.last_solver_tier
        stats = dict(action.last_class_stats or {})
        for k in ("class_count", "classes_valid", "splits", "remerges",
                  "segments", "c_pad", "group_s", "kernel_s"):
            if k in stats:
                row["class_" + k] = stats[k]
        if "compression_ratio" in stats:
            # exact key name: bench_diff gates this one directionally
            # (a shrink means the class key lost its duplication and
            # the solve is drifting back toward per-node cost)
            row["compression_ratio"] = stats["compression_ratio"]
        return row

    u400k = record(
        "uniform_pool_400k_40k",
        lambda: uniform_pool(400_000, 40_000, churn=0.01),
        serial="none",
        sessions=2,
    )
    u400k["solver"] = get_action("xla_allocate").last_solver_tier
    u400kc = record(
        "uniform_pool_400k_40k_classes",
        lambda: uniform_pool(400_000, 40_000, churn=0.01),
        serial="none",
        sessions=2,
        env={"KBT_CLASS_COMPRESS": "1"},
    )
    class_columns(u400kc)
    assert u400kc["solver"].startswith("class_"), (
        f"uniform 400k classes row solved on {u400kc['solver']!r} — the "
        "compressed layer never engaged, the row is not evidence"
    )
    assert binds_by_row["uniform_pool_400k_40k_classes"] == binds_by_row["uniform_pool_400k_40k"], (
        "compressed uniform 400k placements diverge from the "
        "uncompressed column"
    )
    u400kc["placements_equal_uncompressed"] = True
    u400kc["class_solve_speedup_vs_uncompressed"] = round(
        u400k["solve_s"] / u400kc["solve_s"], 2
    )
    # The >=5x solve-phase claim holds in the node-axis-dominated regime
    # — the XLA while-loop twin, whose per-iteration cost grows with the
    # node axis (measured ~linear on CPU hosts; see README). When the
    # uncompressed column solved on the fused Pallas rung instead
    # (TPU backends — per-iteration sequential-step latency dominates
    # and is ~flat in node count, README "Multi-chip"), the ratio
    # compares different kernels and is recorded info-only.
    if u400k["solver"] == "xla":
        assert u400kc["class_solve_speedup_vs_uncompressed"] >= 5.0, (
            f"high-duplication 400k row: compressed solve only "
            f"{u400kc['class_solve_speedup_vs_uncompressed']}x faster "
            f"than the uncompressed XLA twin (claimed >=5x)"
        )

    p400kc = record(
        "preempt_400k_40k_classes",
        lambda: preempt_mix(400_000, 40_000),
        serial="none",
        sessions=2,
        env={"KBT_CLASS_COMPRESS": "1"},
    )
    class_columns(p400kc)
    assert p400kc["solver"].startswith("class_"), (
        f"preempt 400k classes row solved on {p400kc['solver']!r} — the "
        "compressed layer never engaged, the row is not evidence"
    )
    assert binds_by_row["preempt_400k_40k_classes"] == binds_by_row["preempt_400k_40k"], (
        "compressed preempt 400k placements diverge from the "
        "uncompressed column"
    )
    p400kc["placements_equal_uncompressed"] = True

    # Zero warm recompiles under 1% node churn: every measured session
    # re-rolls the churned residents' requests (a fresh churn_salt), so
    # the class partition changes between sessions while the sticky
    # power-of-two slot bucket holds the compiled shapes — any recompile
    # inside the measured repeats raises via the CompileSentinel budget
    # (the class twin of the preempt_50k_5k compile-budget pin).
    churn_salt = iter(range(1, 100))
    record(
        "uniform_pool_400k_40k_classes_churn",
        lambda: uniform_pool(
            400_000, 40_000, churn=0.01, churn_salt=next(churn_salt)
        ),
        serial="none",
        sessions=2,
        env={"KBT_CLASS_COMPRESS": "1"},
        compile_budget=0,
    )
    class_columns(details["uniform_pool_400k_40k_classes_churn"])

    # Incremental encode cache: warm/cold/1%-churn encode split with
    # byte-parity asserted in-row (ISSUE 5).
    details["encode_cache_100k_10k"] = encode_cache_row()

    # -- mesh-path evidence (VERDICT r4 item 2) ---------------------------
    # (a) The conf-selected sharded solve on the 8-device virtual CPU
    #     mesh: validates that the production multi-chip path (GSPMD
    #     node-axis sharding through the real action) compiles, executes
    #     and binds at 10k scale every bench run. The TIME is a virtual-
    #     CPU number — shape validation, not a TPU latency claim
    #     (placement parity vs single-chip is test-asserted at the same
    #     scale in tests/test_parallel.py).
    # Ask for more devices than any host offers and let the action's own
    # resolver clamp to the largest power of two available (ONE source of
    # truth for the clamp, xla_allocate._resolve_mesh); normally 8 via
    # this module's injected device-count flag — an ambient XLA_FLAGS can
    # clamp lower, and the engaged size is recorded as mesh_devices.
    # KBT_MESH_PALLAS=0 pins this row to the GSPMD sharded-XLA rung —
    # with the blocked sharded-Pallas rung now the mesh default, this
    # row keeps the XLA rung (the degradation target) exercised.
    mesh_row = record(
        "multi_queue_10k_1k_meshcpu",
        lambda: multi_queue(10_000, 1000),
        serial="none",
        sessions=2,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "0"},
    )
    # the sharded path degrades to single-chip with only a warning on
    # any resolver/solver failure — the row is evidence only if a real
    # multi-device mesh ENGAGED (loud failure, never a silent skip)
    mesh_row["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    mesh_row["solver"] = get_action("xla_allocate").last_solver_tier
    assert mesh_row["mesh_devices"] >= 2, (
        "mesh row ran single-chip; sharded path did not engage"
    )
    assert mesh_row["solver"] == "sharded_xla", (
        f"mesh XLA row solved on {mesh_row['solver']}, not the sharded XLA rung"
    )
    assert mesh_row["binds"] == details["multi_queue_10k_1k"]["binds"], (
        "mesh path bind count diverged from single-chip"
    )

    # (c) The blocked sharded-Pallas rung on a BEYOND-ENVELOPE snapshot
    #     (ISSUE 2 acceptance): KBT_VMEM_BUDGET is forced between the
    #     per-shard block claim and the single-chip claim, so the
    #     single-chip Pallas gate refuses this snapshot while the
    #     per-shard gate admits it — capacity scaling with mesh size,
    #     with binds equal to the LIVE serial twin.
    from kube_batch_tpu.ops import pallas_solve
    from kube_batch_tpu.ops.encode import encode_session

    def mesh_budget(make_cluster, mesh_size):
        """A VMEM budget (bytes) that the full single-chip snapshot
        overflows but one mesh shard's node block fits."""
        ssn = open_session(FakeCache(make_cluster()), tiers())
        enc = encode_session(
            ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float32,
            drf=ssn.plugins.get("drf"),
            proportion=ssn.plugins.get("proportion"),
        )
        close_session(ssn)
        a = dict(enc.arrays)
        lo = pallas_solve.block_vmem_bytes(a, mesh_size)
        hi = pallas_solve.block_vmem_bytes(a, 1)
        assert lo < hi, "node axis too small to subdivide over the mesh"
        budget = (lo + hi) // 2
        saved = os.environ.get("KBT_VMEM_BUDGET")
        os.environ["KBT_VMEM_BUDGET"] = str(budget)
        try:
            # genuinely beyond the single-chip envelope at this budget
            assert not pallas_solve.supported(a)
            assert pallas_solve.mesh_supported(a, mesh_size)
        finally:
            if saved is None:
                os.environ.pop("KBT_VMEM_BUDGET", None)
            else:
                os.environ["KBT_VMEM_BUDGET"] = saved
        return budget

    budget = mesh_budget(lambda: multi_queue(10_000, 1000), 8)
    mp_row = record(
        "multi_queue_10k_1k_mesh_pallas_overflow",
        lambda: multi_queue(10_000, 1000),
        serial="live",
        sessions=2,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "auto", "KBT_VMEM_BUDGET": str(budget)},
    )
    mp_row["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    mp_row["solver"] = get_action("xla_allocate").last_solver_tier
    mp_row["vmem_budget_forced"] = int(budget)
    assert mp_row["mesh_devices"] >= 2, (
        "mesh-pallas overflow row ran single-chip"
    )
    assert mp_row["solver"] == "mesh_pallas", (
        f"overflow row solved on {mp_row['solver']}, not the mesh-Pallas rung"
    )

    # (d) The mesh-Pallas rung at the headline 50k x 5k config. On the
    #     virtual CPU mesh the per-iteration argmax exchange rides host
    #     shared memory — measured ~120us/iter exchange-free (mesh 1)
    #     vs ~330us/iter at mesh 8, i.e. the transport, not the block
    #     solve, is the floor here; see the README capacity-path section
    #     for the measured encode/solve/exchange/replay split and the
    #     ICI projection. Evidence captured: the rung engages at scale
    #     and binds match the single-chip Pallas row exactly.
    m50 = record(
        "preempt_50k_5k_mesh_pallas",
        lambda: preempt_mix(50_000, 5000),
        serial="none",
        sessions=2,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "auto"},
    )
    m50["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    m50["solver"] = get_action("xla_allocate").last_solver_tier
    m50["transport"] = "virtual-cpu-mesh (host shared memory, not ICI)"
    assert m50["mesh_devices"] >= 2, "50k mesh-pallas row ran single-chip"
    assert m50["solver"] == "mesh_pallas", (
        f"50k mesh row solved on {m50['solver']}, not the mesh-Pallas rung"
    )
    assert m50["binds"] == e50k["binds"], (
        "mesh-pallas 50k bind count diverged from single-chip"
    )

    # (d') The same mesh rung with the K-deep batched exchange
    #     (ISSUE 13): KBT_PIPELINE + KBT_EXCHANGE_BATCH=4 amortizes the
    #     per-iteration argmax exchange — the transport floor of (d) —
    #     over up to 4 gang iterations per all-gather. The committed
    #     iteration count is the amortization evidence; binds must stay
    #     identical to the unbatched mesh row.
    m50b = record(
        "preempt_50k_5k_mesh_pallas_pipelined",
        lambda: preempt_mix(50_000, 5000),
        serial="none",
        sessions=2,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "auto", "KBT_PIPELINE": "1",
             "KBT_EXCHANGE_BATCH": "4"},
    )
    m50b["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    m50b["solver"] = get_action("xla_allocate").last_solver_tier
    m50b["exchange_batched_iters"] = get_action("xla_allocate").last_batched_iters
    assert m50b["solver"] == "mesh_pallas", (
        f"batched mesh row solved on {m50b['solver']}, not the mesh-Pallas rung"
    )
    assert m50b["exchange_batched_iters"] > 0, (
        "batched mesh row committed no iterations from batches — the "
        "K-deep exchange never engaged"
    )
    assert binds_by_row["preempt_50k_5k_mesh_pallas_pipelined"] == binds_by_row["preempt_50k_5k_mesh_pallas"], (
        "batched mesh 50k placements diverge from the unbatched mesh row"
    )
    m50b["placements_equal_unbatched_mesh"] = True
    m50b["p50_speedup_vs_sync_pct"] = round(
        100.0 * (1.0 - m50b["p50_s"] / m50["p50_s"]), 1
    )

    # (e) The 1M-pod x 100k-node row (ISSUE 13): 20x the reference's
    #     headline scale, sized so ONLY the sharded path can hold it —
    #     KBT_VMEM_BUDGET forced between the per-shard block claim and
    #     the single-chip claim, exactly like (c). One session per
    #     column (cluster construction alone is ~40 s) under a
    #     zero-recompile budget; the pipelined column must bind
    #     identically to the synchronous one.
    budget1m = mesh_budget(lambda: preempt_mix(1_000_000, 100_000), 8)
    m1m = record(
        "preempt_1m_100k_mesh_pallas",
        lambda: preempt_mix(1_000_000, 100_000),
        serial="none",
        sessions=1,
        compile_budget=0,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "auto", "KBT_VMEM_BUDGET": str(budget1m)},
    )
    m1m["mesh_devices"] = get_action("xla_allocate").last_mesh_size
    m1m["solver"] = get_action("xla_allocate").last_solver_tier
    m1m["vmem_budget_forced"] = int(budget1m)
    assert m1m["mesh_devices"] >= 2, "1M row ran single-chip"
    assert m1m["solver"] == "mesh_pallas", (
        f"1M row solved on {m1m['solver']}, not the mesh-Pallas rung"
    )
    m1mp = record(
        "preempt_1m_100k_mesh_pallas_pipelined",
        lambda: preempt_mix(1_000_000, 100_000),
        serial="none",
        sessions=1,
        compile_budget=0,
        action_args={"xla_allocate": {"mesh": "cpu:512"}},
        env={"KBT_MESH_PALLAS": "auto", "KBT_VMEM_BUDGET": str(budget1m),
             "KBT_PIPELINE": "1", "KBT_EXCHANGE_BATCH": "4"},
    )
    m1mp["solver"] = get_action("xla_allocate").last_solver_tier
    m1mp["exchange_batched_iters"] = get_action("xla_allocate").last_batched_iters
    assert m1mp["solver"] == "mesh_pallas", (
        f"pipelined 1M row solved on {m1mp['solver']}, not the mesh-Pallas rung"
    )
    assert m1mp["exchange_batched_iters"] > 0, (
        "pipelined 1M row committed no iterations from batches"
    )
    assert binds_by_row["preempt_1m_100k_mesh_pallas_pipelined"] == binds_by_row["preempt_1m_100k_mesh_pallas"], (
        "pipelined 1M placements diverge from the synchronous column"
    )
    m1mp["placements_equal_synchronous"] = True
    m1mp["p50_speedup_vs_sync_pct"] = round(
        100.0 * (1.0 - m1mp["p50_s"] / m1m["p50_s"]), 1
    )
    # (b) The per-chip price floor of the mesh path's program: the XLA
    #     while-loop twin (what ShardedSolver shards) on the single real
    #     chip at the headline config. Measured r5: solve time is ~flat
    #     in node count (3.8 s @1250 nodes -> 4.2 s @20k nodes, 50k
    #     tasks), i.e. per-iteration sequential-step latency dominates
    #     and node-axis sharding cannot buy latency — the mesh path is
    #     for capacity/deployment topology, not speed (README "Multi-chip"
    #     for the full analysis).
    record(
        "preempt_50k_5k_xla1",
        lambda: preempt_mix(50_000, 5000),
        serial="none",
        sessions=2,
        env={"KBT_PALLAS": "0"},
    )

    # preempt's hot scan, serial vs vectorized, same config (secondary)
    def preempt_session(action_name):
        cache = FakeCache(preempt_contended())
        ssn = open_session(cache, tiers())
        action = get_action(action_name)
        t0 = time.perf_counter()
        action.execute(ssn)
        dt = time.perf_counter() - t0
        evicts = len(cache.evictor.evicts)
        close_session(ssn)
        return dt, evicts

    xp_s, xp_ev = preempt_session("xla_preempt")
    sp_s, sp_ev = preempt_session("preempt")
    assert xp_ev == sp_ev, f"preempt evicts diverge: {sp_ev} vs {xp_ev}"
    details["preempt_contended"] = {
        "xla_s": round(xp_s, 4),
        "serial_s": round(sp_s, 4),
        "evicts": xp_ev,
    }

    # backfill's BestEffort walk, serial vs group-dedup'd scan, same
    # config (secondary): the serial cost is a full predicate chain per
    # (task, node) pair — 2M calls at this size
    def backfill_session(action_name):
        cache = FakeCache(besteffort_mix(2000, 1000))
        ssn = open_session(cache, tiers())
        action = get_action(action_name)
        t0 = time.perf_counter()
        action.execute(ssn)
        dt = time.perf_counter() - t0
        binds = dict(cache.binder.binds)  # task -> node, the actual placements
        close_session(ssn)
        return dt, binds

    xb_s, xb_binds = backfill_session("xla_backfill")
    sb_s, sb_binds = backfill_session("backfill")
    assert xb_binds == sb_binds, "backfill placements diverge"
    details["backfill_2k_1k"] = {
        "xla_s": round(xb_s, 4),
        "serial_s": round(sb_s, 4),
        "binds": len(xb_binds),
    }

    # Cross-queue reclaim, serial vs vectorized, same config (secondary;
    # reclaim previously had only the 24-seed test sweep, no bench row):
    # one queue hogging every slot past its deserved share, a
    # higher-weight queue starved with pending gangs. Victim SET and
    # placement parity are asserted on every bench run.
    xr_s, xr_ev, xr_place = reclaim_session("xla_reclaim")
    sr_s, sr_ev, sr_place = reclaim_session("reclaim")
    assert len(xr_ev) >= 1, "reclaim row reclaimed nothing; scene is broken"
    assert xr_ev == sr_ev, (
        f"reclaim victim sets diverge: {len(sr_ev)} serial vs {len(xr_ev)} xla"
    )
    assert xr_place == sr_place, "reclaim placements diverge"
    details["reclaim_cross_queue_400"] = {
        "xla_s": round(xr_s, 4),
        "serial_s": round(sr_s, 4),
        "victims": len(xr_ev),
        "victims_equal_serial": True,
        "placements_equal_serial": True,
    }

    # Streaming mode (ISSUE 8): sustained open-loop arrivals served by
    # event-driven micro-cycles against a 100k-pod resident world —
    # single-gang p50 time-to-bind < 10ms and zero warm-micro-cycle
    # recompiles are asserted in-row, as is bind-for-bind parity with a
    # full-cycle twin.
    details["sustained_arrival_100k"] = sustained_arrival_row()

    # Failover MTTR (ISSUE 3): leader SIGKILL mid-bulk-bind -> first
    # successful standby bind. In-process simulation of the production
    # topology (the cache has no remote-store transport yet): a leader
    # with a bind-intent journal dies via a BaseException in its write
    # pool after 16 of 64 bulk store writes (neither the retry ladder
    # nor resync can catch BaseException — the write side stops exactly
    # like SIGKILL); the standby waits out the lease (crash path, 1 s
    # lease for the row), reconciles the journal, and its first
    # re-dispatched bind stops the clock. sessions>=5, p50/p90.
    details["failover_mttr"] = failover_mttr_row(sessions=5)

    # Federated kill-and-adopt MTTR (ISSUE 16): one of four leased shard
    # owners killed mid-bind_many; MTTR = kill -> first bind landing in
    # the orphaned slot after a survivor adopts it (lease wait-out +
    # journal reconciliation + backlog re-drive). p50 must sit within
    # lease TTL + renew period. sessions>=5, p50/p90.
    details["federation_kill_mttr"] = federation_kill_mttr_row(sessions=5)

    # Sharded federation scale-out (ISSUE 10): 1/2/4/8 active schedulers
    # over one store on a 50k-pod world — aggregate binds/s plus the
    # conflict/retry economics; exactly-once + union fsck asserted per N.
    details["federation_scaleout_50k"] = federation_scaleout_row()

    # Wire-transport ladder (ISSUE 17): the same scale-out shape over
    # the REAL loopback wire, v1 vs v2 per shard count — binds/s,
    # bytes/bind, backend RTT and txn coalescing depth, with the v2 >= v1
    # throughput and strictly-leaner-bytes claims asserted at N=4/8.
    # bench_diff expands these into <row>.wire_v<p>_n<N> pseudo-rows.
    details["federation_scaleout_50k"]["wire_runs"] = federation_wire_runs()

    # Admission storm (ISSUE 18): the overload drill as a headline row —
    # protected-lane p99 + goodput with admission ON, the OFF collapse
    # for contrast, and kill-cell MTTR; directional columns gated by
    # bench_diff (_STORM_LOWER/_STORM_HIGHER).
    details["admission_storm"] = admission_storm_row()

    # Headline speedup at the headline config (VERDICT r3 item 2).
    serial_50k = e50k.get("serial_s")
    vs_baseline = (
        round(serial_50k / e50k["xla_s"], 2)
        if serial_50k and e50k["xla_s"]
        else None
    )

    print(json.dumps({"details": details}), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "xla_session_seconds_50k_5k",
                "value": e50k["xla_s"],
                "unit": "s",
                "vs_baseline": vs_baseline,
                # provenance of the serial side of vs_baseline, machine-
                # readable: "measured" = this run (KBT_BENCH_FULL_SERIAL),
                # "cached" = the provenance-stamped one-time measurement
                "baseline_source": (
                    "measured" if "serial_s_note" not in e50k else "cached"
                )
                if serial_50k
                else None,
            }
        )
    )


if __name__ == "__main__":
    main()
