"""Benchmark: serial reference path vs the XLA allocate solve.

Methodology follows the reference's kubemark density tests
(test/e2e/benchmark.go:49-281) but hollow-state in-process: generate a
synthetic cluster (kube_batch_tpu.models), open a session, schedule one
full cycle, measure wall-clock. The serial python path is timed on the
1k x 100 config (it is the reference implementation, and minutes-slow
beyond that); the XLA path is timed on the 10k x 1k multi-queue config
(and 50k x 5k with BENCH_FULL=1).

Prints ONE JSON line:
  {"metric": "xla_pods_per_sec_10k_1k", "value": <pods/s>, "unit":
   "pods/s", "vs_baseline": <xla per-pod rate / serial per-pod rate>}

vs_baseline > 1 means the vectorized TPU path schedules pods faster than
the serial reference path (BASELINE.md publishes no reference numbers, so
the serial twin measured on identical hollow state is the baseline).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import kube_batch_tpu.actions  # noqa: F401
import kube_batch_tpu.plugins  # noqa: F401
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import multi_queue, preempt_mix, synthetic
from kube_batch_tpu.ops.encode import encode_session
from kube_batch_tpu.ops.kernels import solve_allocate
from kube_batch_tpu.testing import FakeCache

TIERS_YAML = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: predicates
  - name: nodeorder
"""


def tiers():
    return parse_scheduler_conf(TIERS_YAML).tiers


def time_serial(cluster) -> tuple[float, int]:
    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers())
    t0 = time.perf_counter()
    get_action("allocate").execute(ssn)
    dt = time.perf_counter() - t0
    n = len(cache.binder.binds)
    close_session(ssn)
    return dt, n


def time_xla_solve(cluster, warm: bool = True) -> tuple[float, int, float]:
    """(solve_seconds, assigned, encode_seconds). Times the pure device
    solve (the per-cycle hot loop); compile is cached across cycles at
    stable bucket sizes, so the first call is excluded when warm."""
    ssn = open_session(FakeCache(cluster), tiers())
    t0 = time.perf_counter()
    enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float32)
    t_encode = time.perf_counter() - t0
    arrays = dict(enc.arrays)
    arrays.update(
        w_least=np.float32(1), w_balanced=np.float32(1), w_aff=np.float32(1)
    )
    if warm:
        solve_allocate(arrays).n_assigned.block_until_ready()
    t0 = time.perf_counter()
    result = solve_allocate(arrays)
    n = int(result.n_assigned)
    dt = time.perf_counter() - t0
    return dt, n, t_encode


def main() -> None:
    details = {}

    serial_dt, serial_n = time_serial(synthetic(1000, 100))
    serial_rate = serial_n / serial_dt if serial_dt > 0 else 0.0
    details["serial_1k_100"] = {"s": round(serial_dt, 4), "pods": serial_n}

    xs_dt, xs_n, _ = time_xla_solve(synthetic(1000, 100))
    details["xla_1k_100"] = {"s": round(xs_dt, 4), "pods": xs_n}

    xla_dt, xla_n, enc_dt = time_xla_solve(multi_queue(10_000, 1000))
    xla_rate = xla_n / xla_dt if xla_dt > 0 else 0.0
    details["xla_10k_1k"] = {
        "s": round(xla_dt, 4),
        "pods": xla_n,
        "encode_s": round(enc_dt, 4),
    }

    if os.environ.get("BENCH_FULL"):
        f_dt, f_n, f_enc = time_xla_solve(preempt_mix(50_000, 5000))
        details["xla_50k_5k"] = {
            "s": round(f_dt, 4),
            "pods": f_n,
            "encode_s": round(f_enc, 4),
        }

    print(json.dumps({"details": details}), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "xla_pods_per_sec_10k_1k",
                "value": round(xla_rate, 1),
                "unit": "pods/s",
                "vs_baseline": round(xla_rate / serial_rate, 2) if serial_rate else None,
            }
        )
    )


if __name__ == "__main__":
    main()
