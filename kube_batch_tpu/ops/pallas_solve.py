"""Fused Pallas TPU kernel for the gang-aware allocate solve.

Same algorithm, same policy, same float32 arithmetic as the XLA
`lax.while_loop` kernel (ops/kernels.py `solve_allocate_step`) — but the
*entire* loop runs inside one Mosaic kernel with every array resident in
VMEM, so one solver iteration costs ~2-3us instead of the ~70us of
per-HLO-op dispatch the XLA while loop pays at these (tiny-tensor)
shapes. That difference is the whole ballgame: a 50k-task snapshot is
>50k dependent iterations (reference allocate.go:94-190 is an inherently
sequential greedy loop — each assignment changes the node state the next
decision reads), so the serial spine cannot be batched away without
changing policy; it can only be made cheap. This kernel makes it cheap.

Layout strategy (Mosaic supports dynamic indexing on sublane/leading
dims, NOT on the lane dim — probed, see git history):

- per-task fields fold to ``[T/128, 128]`` (row = t >> 7, lane = t & 127);
  a task access is one dynamic-sublane row load + a lane-mask reduce, and
  a result write is a row read-modify-write — both O(1) vregs;
- task resource vectors dedup into *classes* (unique (req, res, group,
  flags, ports) combinations — a 50k-pod job collapses to a handful), so
  the kernel carries a ``[T/128, 128]`` class id plus tiny
  ``[8, C/128, 128]`` class tables instead of 2x ``[8, T]`` megabytes;
- node arrays fold to ``[8, N/128, 128]`` (resource dim in sublanes);
  feasibility/score are full-array VPU ops, but the *assignment* update
  touches only the 128-lane slab holding the chosen node — a full-array
  RMW measured ~6us/iter, the slab RMW is free;
- job/queue fields fold like tasks; the per-queue "has active jobs" set
  (a scatter over jobs in the XLA kernel) is maintained *incrementally*
  as an active-job counter per queue, updated on the single job/queue
  retirement any iteration can cause;
- the (queue, job) selection block — only needed when the current job
  was retired — sits under `lax.cond` so task-pop iterations skip it.

Equivalence contract: identical op-for-op float32 formulas and identical
lexicographic tie-breaks as ops/kernels.py, pinned by the pallas ≡ XLA
property tests (interpret mode on CPU, real kernel on TPU via bench's
serial-vs-xla bind assertions). The pause/resume protocol for host-only
(pod-affinity) tasks is identical: the kernel exits with ``paused_at``
set, the action serial-steps the task and re-enters with patched state.

Out-of-envelope snapshots (resource rank > 8, > 31 distinct host ports,
a compat matrix too large for VMEM) fall back to the XLA kernel — never
to serial Python.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache

import jax
import numpy as np

from kube_batch_tpu.ops.kernels import SolveState, ieee_div as _ieee_div

R8 = 8  # padded resource rank (milli-cpu, memory, <=6 scalar resources)
LANES = 128
INT_MAX = np.iinfo(np.int32).max

# VMEM budget guard for the packed snapshot (bytes); see vmem_budget().
_DEFAULT_VMEM_BUDGET = 12 * 1024 * 1024  # conservative: v4-class 16MiB cores


def vmem_budget() -> int:
    """Per-core VMEM the solve may claim, by device generation.

    v5e/v5p/v6 cores carry 128 MiB of VMEM — measured on the bench chip
    (TPU v5 lite): a 400k-task x 40k-node snapshot (~33 MiB estimated
    resident) compiles and solves in 4 s, 16x faster than the XLA
    fallback the old 12 MiB gate forced it onto. Older/unknown cores
    keep the conservative 12 MiB; a too-generous verdict only costs a
    failed Mosaic compile, which the action's try/except downgrades to
    the XLA kernel. ``KBT_VMEM_BUDGET`` (bytes) overrides."""
    env = os.environ.get("KBT_VMEM_BUDGET")
    if env:
        try:
            return int(env)
        except ValueError:
            import logging

            logging.getLogger("kube_batch_tpu.ops.pallas_solve").warning(
                "KBT_VMEM_BUDGET=%r is not an integer byte count; "
                "using the device default",
                env,
            )
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 -- no devices: gate conservatively
        return _DEFAULT_VMEM_BUDGET
    if any(tag in kind for tag in ("v5 lite", "v5e", "v5p", "v6", "v7")):
        return 96 * 1024 * 1024
    return _DEFAULT_VMEM_BUDGET


def _rows(n: int) -> int:
    return max((n + LANES - 1) // LANES, 1)


def _fold1(x: np.ndarray, rows: int, dtype, pad=0) -> np.ndarray:
    out = np.full(rows * LANES, pad, dtype)
    out[: x.shape[0]] = x
    return out.reshape(rows, LANES)


def _fold2(x: np.ndarray, rows: int, dtype) -> np.ndarray:
    """[X, R] -> [R8, rows, 128] (resource dim to sublanes, X folded)."""
    X, R = x.shape
    out = np.zeros((R8, rows * LANES), dtype)
    out[:R, :X] = np.ascontiguousarray(x.T)
    return out.reshape(R8, rows, LANES)


def _unfold1(x, n: int):
    return np.asarray(x).reshape(-1)[:n]


def _unfold2(x, n: int, r: int):
    return np.ascontiguousarray(np.asarray(x).reshape(R8, -1).T[:n, :r])


def _ports_mask(ports_bool: np.ndarray) -> np.ndarray:
    """[X, P] bool -> int32 bitmask (caller guarantees P <= 31)."""
    P = ports_bool.shape[1]
    bits = (1 << np.arange(P, dtype=np.int64))[None, :]
    return (ports_bool.astype(np.int64) * bits).sum(axis=1).astype(np.int32)


@dataclass
class _Packed:
    """Folded static inputs + initial dynamic state + dims."""

    dims: tuple  # (Tr, Nr, Jr, Qr, Cr, GT, R, max_iter)
    statics: list  # ordered static input arrays
    tcls: np.ndarray
    n_tasks_pad: int  # lax-padded T (for parity of indices)
    n_jobs_pad: int
    n_nodes_pad: int
    n_queues_pad: int


_class_inv_slot: tuple | None = None  # (input arrays, result) single-cycle memo
_CLASS_KEYS = (
    "task_req", "task_res", "task_gid", "task_has_sc",
    "task_res_has_sc", "task_host_only", "task_ports",
)


def _class_inverse(a: dict):
    """Dedup tasks into classes by (req, res, gid, flags, ports): returns
    (tports, first_indices, inverse) as np.unique does. Shared by pack()
    and supported() so the VMEM gate sees the real class count. The last
    result is memoized, keyed on the identity of *every* input array (the
    slot holds strong refs, so `is` comparisons cannot alias freed
    buffers), so the O(T log T) dedup runs once per cycle, not once per
    caller; the memo must stay *outside* the arrays dict, which is a jit
    pytree argument."""
    global _class_inv_slot
    inputs = tuple(a[k] for k in _CLASS_KEYS)
    if _class_inv_slot is not None and all(
        x is y for x, y in zip(_class_inv_slot[0], inputs)
    ):
        return _class_inv_slot[1]
    tports = _ports_mask(np.asarray(a["task_ports"]))
    key = np.concatenate(
        [
            np.asarray(a["task_req"], np.float64),
            np.asarray(a["task_res"], np.float64),
            np.asarray(a["task_gid"], np.float64)[:, None],
            np.asarray(a["task_has_sc"], np.float64)[:, None],
            np.asarray(a["task_res_has_sc"], np.float64)[:, None],
            np.asarray(a["task_host_only"], np.float64)[:, None],
            tports.astype(np.float64)[:, None],
        ],
        axis=1,
    )
    key = np.ascontiguousarray(key)
    from kube_batch_tpu import faults as _faults
    from kube_batch_tpu.native import lib as _native

    if (
        _native is not None
        and hasattr(_native, "class_dedup")
        and not _faults.should_fire("native.class_dedup")
    ):
        # O(T) hash pass, classes in first-occurrence order (~10x the
        # void-sort below at 400k). Any consistent (first, inverse)
        # pairing is equivalent — class order carries no meaning in the
        # packed layout.
        first_b, inv_b = _native.class_dedup(key)
        first = np.frombuffer(first_b, np.int64)
        inv = np.frombuffer(inv_b, np.int32).astype(np.int64)
    else:
        void = key.view(np.dtype((np.void, key.dtype.itemsize * key.shape[1])))
        _, first, inv = np.unique(void.ravel(), return_index=True, return_inverse=True)
    _class_inv_slot = (inputs, (tports, first, inv))
    return tports, first, inv


def supported(a: dict) -> bool:
    """Envelope check for the pallas path (beyond kernel_supported).

    The VMEM estimate accounts for every buffer resident during the solve
    (round-3 advisor finding: the old estimate omitted the class tables,
    jalloc/qalloc, and the doubled state from the manual in->out copy
    that works around Mosaic's aliasing semantics): all packed statics,
    plus the dynamic state twice — once as the aliased inputs, once as
    the output copies the kernel writes at entry."""
    R = a["task_req"].shape[1]
    if R > R8:
        return False
    if a["task_ports"].shape[1] > 31:
        return False
    GT = a["compat"].shape[0]
    N = a["node_idle"].shape[0]
    T = a["task_req"].shape[0]
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]
    _, first, _ = _class_inverse(a)
    C = first.shape[0]
    T_pad, N_pad, J_pad, Q_pad, C_pad = (
        _rows(T) * LANES,
        _rows(N) * LANES,
        _rows(J) * LANES,
        _rows(Q) * LANES,
        _rows(C) * LANES,
    )
    # elements (4 bytes each), mirroring _Packed.statics exactly
    statics = (
        T_pad  # tcls
        + 2 * R8 * C_pad  # creq, cres
        + 5 * C_pad  # cgid, chs, crhs, cho, cpt
        + 2 * GT * N_pad  # cnode, affw
        + R8 * N_pad  # nalloc
        + 3 * N_pad  # nmax, nihs, nrhs
        + 6 * J_pad  # jstart/jend/jmin/jprio/jqueue/jvalid
        + 2 * R8 * Q_pad  # qdes, qdim
        + 16 + 2 * R8  # fscal, drft, drfd
        + LANES  # iscal
    )
    # dynamic state, mirroring the kernel's in/out ref lists
    state = (
        3 * T_pad  # tnode, tkind, tpos
        + 3 * R8 * N_pad  # idle, rel, used
        + 2 * N_pad  # ntasks, nports
        + 3 * J_pad  # jptr, jready, jactive
        + 2 * Q_pad  # qdropped, qcount
        + R8 * J_pad  # jalloc
        + R8 * Q_pad  # qalloc
        + Q_pad  # qahs
        + LANES  # oscal
    )
    vmem = (statics + 2 * state) * 4
    return vmem <= vmem_budget()


def block_vmem_bytes(a: dict, mesh_size: int) -> int:
    """Per-shard VMEM the *blocked* sharded solve claims (bytes).

    The blocked path (parallel/sharded_pallas.ShardedPallasSolver) keeps
    only the node-axis slab resident in the block kernel's VMEM: the
    fused feasibility+score kernel reads the local node block of the
    statics (cnode, affw, nalloc, nmax, nihs, nrhs) and the dynamic node
    state (idle, rel, used, ntasks, nports). Task/job/queue state stays
    replicated in XLA-land (HBM/registers), so — unlike the single-chip
    fused kernel, whose envelope is dominated by the task fold at large
    T — the blocked envelope scales with N / mesh_size only. That is the
    capacity story: a snapshot that overflows `vmem_budget()` on one
    chip stays on the Pallas rung when its node block divided over the
    mesh fits.
    """
    N = a["node_idle"].shape[0]
    GT = a["compat"].shape[0]
    Nr = _rows(N)
    n_loc = -(-Nr // max(mesh_size, 1)) * LANES  # folded columns per shard
    # elements (4 bytes each): cnode+affw [GT,...] statics, nalloc +
    # idle/rel/used [R8,...], nmax/nihs/nrhs/ntasks/nports flat, plus the
    # candidate/score scratch the kernel materializes (~4 flat arrays).
    elems = n_loc * (2 * GT + 4 * R8 + 5 + 4)
    return elems * 4


def mesh_supported(a: dict, mesh_size: int) -> bool:
    """Envelope check for the blocked sharded-Pallas path: same static
    limits as the single-chip kernel (resource rank, host ports), but the
    VMEM gate is per shard — `block_vmem_bytes(a, mesh_size)` against the
    device budget."""
    if a["task_req"].shape[1] > R8:
        return False
    if a["task_ports"].shape[1] > 31:
        return False
    return block_vmem_bytes(a, mesh_size) <= vmem_budget()


def fold_affinity_scores(a: dict, Nr: int) -> np.ndarray:
    """[GT, Nr, 128] combined static score term: preferred node-affinity
    plus live InterPodAffinity, each pre-weighted (the kernel multiplies
    by 1). Re-folded by PallasSolver.solve when the action refreshes
    a["pod_sc"] between pause/resume segments — a [GT, N] multiply-add,
    not a re-pack."""
    f32 = np.float32
    node_gid = np.asarray(a["node_gid"], np.int64)
    N = node_gid.shape[0]
    full = np.asarray(a["aff_sc"], f32)[:, node_gid] * f32(a["w_aff"])
    pod_sc = np.asarray(a.get("pod_sc"), f32)
    if pod_sc.ndim == 2 and pod_sc.any():
        full = full + pod_sc * f32(a["w_podaff"])
    GT = full.shape[0]
    affw = np.zeros((GT, Nr, LANES), f32)
    affw[:, : (N + LANES - 1) // LANES, :].reshape(GT, -1)[:, :N] = full
    return affw


def pack(a: dict, enable_drf: bool, enable_proportion: bool) -> _Packed:
    """Fold the encoder's SoA snapshot into the kernel's VMEM layout."""
    f32, i32 = np.float32, np.int32
    T, R = a["task_req"].shape
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]
    Tr, Nr, Jr, Qr = _rows(T), _rows(N), _rows(J), _rows(Q)

    # -- task classes: unique (req, res, gid, flags, ports) rows ----------
    tports, first, inv = _class_inverse(a)
    C = first.shape[0]
    Cr = _rows(C)
    tcls = _fold1(inv.astype(i32), Tr, i32)

    creq = _fold2(np.asarray(a["task_req"], f32)[first], Cr, f32)
    cres = _fold2(np.asarray(a["task_res"], f32)[first], Cr, f32)
    cgid = _fold1(np.asarray(a["task_gid"], i32)[first], Cr, i32)
    chs = _fold1(np.asarray(a["task_has_sc"], i32)[first], Cr, i32)
    crhs = _fold1(np.asarray(a["task_res_has_sc"], i32)[first], Cr, i32)
    cho = _fold1(np.asarray(a["task_host_only"], i32)[first], Cr, i32)
    cpt = _fold1(tports[first], Cr, i32)

    # -- node statics: compat/affinity expanded per node ------------------
    node_gid = np.asarray(a["node_gid"], np.int64)
    okv = np.asarray(a["node_ok"] & a["node_valid"])
    cnode_full = np.asarray(a["compat"])[:, node_gid] & okv[None, :]  # [GT,N]
    GT = cnode_full.shape[0]
    cnode = np.zeros((GT, Nr, LANES), i32)
    cnode[:, : (N + LANES - 1) // LANES, :].reshape(GT, -1)[:, :N] = cnode_full
    affw = fold_affinity_scores(a, Nr)

    nalloc = _fold2(np.asarray(a["node_alloc"], f32), Nr, f32)
    nmax = _fold1(np.asarray(a["node_max_tasks"], i32), Nr, i32)
    nihs = _fold1(np.asarray(a["node_idle_has_sc"], i32), Nr, i32)
    nrhs = _fold1(np.asarray(a["node_rel_has_sc"], i32), Nr, i32)

    # -- job / queue statics ----------------------------------------------
    jstart = _fold1(np.asarray(a["job_start"], i32), Jr, i32)
    jend = _fold1(np.asarray(a["job_end"], i32), Jr, i32)
    jmin = _fold1(np.asarray(a["job_min"], i32), Jr, i32)
    jprio = _fold1(np.asarray(a["job_prio"], i32), Jr, i32)
    jqueue = _fold1(np.asarray(a["job_queue"], i32), Jr, i32)
    jvalid = _fold1(np.asarray(a["job_valid"], i32), Jr, i32)
    qdes = _fold2(np.asarray(a["q_deserved"], f32), Qr, f32)
    qdim = _fold2(np.asarray(a["q_dims"], i32), Qr, f32)  # as f32 0/1

    # Pad rows (r >= R) carry req=0 and idle=0; eps must be positive there
    # so the all-dims fit check sees 0 < 0 + eps and ignores them.
    eps = np.ones(R8, f32)
    eps[:R] = np.asarray(a["eps"], f32)
    fscal = np.zeros(16, f32)
    fscal[:R8] = eps
    fscal[8] = np.float32(a["w_least"])
    fscal[9] = np.float32(a["w_balanced"])
    # The affinity weights (w_aff AND w_podaff) are baked into the affw
    # matrix at fold time (fold_affinity_scores), so the kernel's single
    # multiplier is 1 — this is what lets live InterPodAffinity scores
    # refresh between pause/resume segments without a kernel change.
    fscal[10] = np.float32(1.0)
    drft = np.zeros(R8, f32)
    drfd = np.zeros(R8, i32)
    if enable_drf:
        drft[:R] = np.asarray(a["drf_total"], f32)
        drfd[:R] = np.asarray(a["drf_dims"], i32)

    max_iter = T + J + Q + 1 + int(np.asarray(a["task_host_only"]).sum())

    statics = [
        tcls, creq, cres, cgid, chs, crhs, cho, cpt,
        cnode, affw, nalloc, nmax, nihs, nrhs,
        jstart, jend, jmin, jprio, jqueue, jvalid,
        qdes, qdim, fscal, drft, drfd,
    ]
    return _Packed(
        dims=(Tr, Nr, Jr, Qr, Cr, GT, R, max_iter),
        statics=statics,
        tcls=tcls,
        n_tasks_pad=T,
        n_jobs_pad=J,
        n_nodes_pad=N,
        n_queues_pad=Q,
    )


def _initial_state(a: dict, enable_drf: bool, enable_proportion: bool) -> SolveState:
    """Numpy twin of kernels.init_state (fresh solve)."""
    f32, i32 = np.float32, np.int32
    T, R = a["task_req"].shape
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]
    return SolveState(
        it=i32(0),
        step=i32(0),
        cur=i32(-1),
        ptr=np.asarray(a["job_start"], i32).copy(),
        assigned_node=np.full(T, -1, i32),
        assigned_kind=np.zeros(T, i32),
        assign_pos=np.full(T, -1, i32),
        idle=np.asarray(a["node_idle"], f32).copy(),
        rel=np.asarray(a["node_rel"], f32).copy(),
        used=np.asarray(a["node_used"], f32).copy(),
        ntasks=np.asarray(a["node_ntasks"], i32).copy(),
        nports=np.asarray(a["node_ports"], bool).copy(),
        ready_cnt=np.asarray(a["job_ready0"], i32).copy(),
        job_active=np.asarray(a["job_valid"], bool).copy(),
        q_dropped=np.zeros(Q, bool),
        job_alloc=(
            np.asarray(a["job_alloc0"], f32).copy()
            if enable_drf
            else np.zeros((J, R), f32)
        ),
        q_alloc=(
            np.asarray(a["q_alloc0"], f32).copy()
            if enable_proportion
            else np.zeros((Q, R), f32)
        ),
        q_alloc_has_sc=(
            np.asarray(a["q_alloc_has_sc0"], bool).copy()
            if enable_proportion
            else np.zeros(Q, bool)
        ),
        paused_at=i32(-1),
    )


@lru_cache(maxsize=64)
def _build(
    Tr: int, Nr: int, Jr: int, Qr: int, Cr: int, GT: int, R: int,
    enable_drf: bool, enable_proportion: bool, interpret: bool,
):
    """Compile (cached per shape bucket) the fused solve kernel."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    MAX_PRIORITY = 10
    import os as _os
    _DEBUG = _os.environ.get("KBT_PALLAS_DEBUG") == "1"
    T_pad, N_pad, J_pad, Q_pad = Tr * LANES, Nr * LANES, Jr * LANES, Qr * LANES
    NINF = float("-inf")  # python floats: jnp weak types, no captured consts
    PINF = float("inf")

    def kernel(
        # statics (order = _Packed.statics)
        tcls_ref, creq_ref, cres_ref, cgid_ref, chs_ref, crhs_ref, cho_ref,
        cpt_ref, cnode_ref, affw_ref, nalloc_ref, nmax_ref, nihs_ref,
        nrhs_ref, jstart_ref, jend_ref, jmin_ref, jprio_ref, jqueue_ref,
        jvalid_ref, qdes_ref, qdim_ref, fscal_ref, drft_ref, drfd_ref,
        iscal_ref,
        # state inputs (aliased to outputs)
        tnode_in, tkind_in, tpos_in, idle_in, rel_in, used_in, ntasks_in,
        nports_in, jptr_in, jready_in, jactive_in, qdropped_in, qcount_in,
        jalloc_in, qalloc_in, qahs_in,
        # outputs
        oscal_ref, tnode_ref, tkind_ref, tpos_ref, idle_ref, rel_ref,
        used_ref, ntasks_ref, nports_ref, jptr_ref, jready_ref, jactive_ref,
        qdropped_ref, qcount_ref, jalloc_ref, qalloc_ref, qahs_ref,
    ):
        # Copy the incoming state into the output refs and operate on those
        # — Mosaic does not expose aliased input values through output refs,
        # so in/out aliasing alone is not enough (measured: garbage reads).
        tnode_ref[:, :] = tnode_in[:, :]
        tkind_ref[:, :] = tkind_in[:, :]
        tpos_ref[:, :] = tpos_in[:, :]
        idle_ref[:, :, :] = idle_in[:, :, :]
        rel_ref[:, :, :] = rel_in[:, :, :]
        used_ref[:, :, :] = used_in[:, :, :]
        ntasks_ref[:, :] = ntasks_in[:, :]
        nports_ref[:, :] = nports_in[:, :]
        jptr_ref[:, :] = jptr_in[:, :]
        jready_ref[:, :] = jready_in[:, :]
        jactive_ref[:, :] = jactive_in[:, :]
        qdropped_ref[:, :] = qdropped_in[:, :]
        qcount_ref[:, :] = qcount_in[:, :]
        jalloc_ref[:, :, :] = jalloc_in[:, :, :]
        qalloc_ref[:, :, :] = qalloc_in[:, :, :]
        qahs_ref[:, :] = qahs_in[:, :]

        lane = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        lane3 = lane[None]  # [1,1,128]
        nidx = (
            lax.broadcasted_iota(jnp.int32, (Nr, LANES), 0) * LANES
            + lax.broadcasted_iota(jnp.int32, (Nr, LANES), 1)
        )
        jidx = (
            lax.broadcasted_iota(jnp.int32, (Jr, LANES), 0) * LANES
            + lax.broadcasted_iota(jnp.int32, (Jr, LANES), 1)
        )
        qidx = (
            lax.broadcasted_iota(jnp.int32, (Qr, LANES), 0) * LANES
            + lax.broadcasted_iota(jnp.int32, (Qr, LANES), 1)
        )

        # loop-invariant scalars / small vectors
        eps_v = jnp.concatenate(
            [jnp.full((1, 1), fscal_ref[i], jnp.float32) for i in range(R8)]
        )  # [R8,1]
        eps3 = eps_v[:, :, None]
        w_least = fscal_ref[8]
        w_bal = fscal_ref[9]
        w_aff = fscal_ref[10]
        max_iter = iscal_ref[5]

        def exti(ref, idx):
            r, l = idx // LANES, idx % LANES
            # dtype pinned: under jax x64 (CPU interpret tests) jnp.sum
            # would promote int32 to int64 and break the carry types
            return jnp.sum(jnp.where(lane == l, ref[pl.ds(r, 1), :], 0), dtype=jnp.int32)

        def extcol(ref3, idx, zero=0.0):
            r, l = idx // LANES, idx % LANES
            slab = ref3[:, pl.ds(r, 1), :]
            return jnp.sum(jnp.where(lane3 == l, slab, zero), axis=2)  # [R8,1]

        def extdim(ref3, idx, r):
            """Scalar of resource dim r at folded column idx. Mosaic cannot
            do i1 vector ops at [8,1], so per-dim gates are scalar-unrolled."""
            rr, l = idx // LANES, idx % LANES
            return jnp.sum(jnp.where(lane == l, ref3[r, pl.ds(rr, 1), :], 0.0))

        def rmw_set(ref, idx, val):
            r, l = idx // LANES, idx % LANES
            row = ref[pl.ds(r, 1), :]
            ref[pl.ds(r, 1), :] = jnp.where(lane == l, val, row)

        def rmw_add(ref, idx, val):
            r, l = idx // LANES, idx % LANES
            ref[pl.ds(r, 1), :] = ref[pl.ds(r, 1), :] + jnp.where(lane == l, val, 0)

        def rmw_add3(ref3, idx, col):
            r, l = idx // LANES, idx % LANES
            slab = ref3[:, pl.ds(r, 1), :]
            ref3[:, pl.ds(r, 1), :] = slab + jnp.where(
                lane3 == l, col[:, :, None], 0.0
            )

        def lex_argmin(mask, keys, idx, pad):
            m = mask
            for k in keys:
                sent = PINF if jnp.issubdtype(k.dtype, jnp.floating) else INT_MAX
                kmin = jnp.min(jnp.where(m, k, sent))
                m = m & (k == kmin)
            return jnp.min(jnp.where(m, idx, pad))

        def drf_share():
            # _share_rows over jobs: max over masked dims of alloc/total
            s = jnp.full((Jr, LANES), NINF, jnp.float32)
            for r in range(R8):
                denom = drft_ref[r]
                alloc_r = jalloc_ref[r, :, :]
                sr = jnp.where(
                    denom == 0.0,
                    # dtype-pinned 0/1 branch (trace-audit KBT-P002)
                    (alloc_r != 0.0).astype(alloc_r.dtype),
                    _ieee_div(alloc_r, jnp.where(denom == 0.0, 1.0, denom)),
                )
                s = jnp.where(drfd_ref[r] != 0, jnp.maximum(s, sr), s)
            return jnp.maximum(s, 0.0)

        def q_share():
            s = jnp.full((Qr, LANES), NINF, jnp.float32)
            for r in range(R8):
                d = qdes_ref[r, :, :]
                al = qalloc_ref[r, :, :]
                sr = jnp.where(
                    d == 0.0,
                    # dtype-pinned 0/1 branch (trace-audit KBT-P002)
                    (al != 0.0).astype(al.dtype),
                    _ieee_div(al, jnp.where(d == 0.0, 1.0, d)),
                )
                s = jnp.where(qdim_ref[r, :, :] != 0.0, jnp.maximum(s, sr), s)
            return jnp.maximum(s, 0.0)

        def select():
            """Queue + job selection (lax kernel body lines 'queue + job
            selection'); returns (qsel, drop_q, jsel, sel_ok)."""
            q_has = (qcount_ref[:, :] > 0) & (qdropped_ref[:, :] == 0)
            if enable_proportion:
                qsel = lex_argmin(q_has, [q_share(), qidx], qidx, Q_pad)
            else:
                qsel = lex_argmin(q_has, [qidx], qidx, Q_pad)
            q_any = qsel < Q_pad
            qsel_c = jnp.minimum(qsel, Q_pad - 1)

            if enable_proportion:
                # Overused gate (proportion.go:188-199 + the Go
                # nil-scalar-map branch), scalar-unrolled per dim.
                has_sc_q = exti(qahs_ref, qsel_c) != 0
                overused = jnp.bool_(True)
                for r in range(R8):
                    d_r = extdim(qdes_ref, qsel_c, r)
                    a_r = extdim(qalloc_ref, qsel_c, r)
                    m_r = extdim(qdim_ref, qsel_c, r)
                    ok_r = (d_r < a_r) | (jnp.abs(a_r - d_r) < fscal_ref[r])
                    if r >= 2:
                        ok_r = ok_r & has_sc_q
                    overused = overused & jnp.where(m_r != 0.0, ok_r, True)
            else:
                overused = jnp.bool_(False)

            jmask = (jactive_ref[:, :] != 0) & (jqueue_ref[:, :] == qsel_c)
            ready_bit = (jready_ref[:, :] >= jmin_ref[:, :]).astype(jnp.int32)
            keys = [-jprio_ref[:, :], ready_bit]
            if enable_drf:
                keys.append(drf_share())
            keys.append(jidx)
            jsel = lex_argmin(jmask, keys, jidx, J_pad)
            j_any = jsel < J_pad
            sel_ok = q_any & ~overused & j_any
            drop_q = q_any & overused
            return qsel_c, drop_q, jnp.minimum(jsel, J_pad - 1), sel_ok

        def body(carry):
            it, step, cur, paused, n_active = carry
            need_sel = cur < 0

            qsel, drop_q, jsel, sel_ok = lax.cond(
                need_sel,
                select,
                lambda: (jnp.int32(0), jnp.bool_(False), jnp.int32(0), jnp.bool_(False)),
            )
            cur = jnp.where(need_sel, jnp.where(sel_ok, jsel, -1), cur)

            qsel_cnt = exti(qcount_ref, qsel)

            @pl.when(drop_q)
            def _():
                # overused queue retires all its jobs for the cycle
                jactive_ref[:, :] = jnp.where(
                    jqueue_ref[:, :] == qsel, 0, jactive_ref[:, :]
                )
                rmw_set(qdropped_ref, qsel, 1)
                rmw_set(qcount_ref, qsel, 0)

            n_active = n_active - jnp.where(drop_q, qsel_cnt, 0)

            # -- pop the current job's next pending task (O(1) pointer) --
            cur_c = jnp.maximum(cur, 0)
            t = exti(jptr_ref, cur_c)
            if _DEBUG:
                jax.debug.print(
                    "it={} cur={} qsel={} drop_q={} sel_ok={} t={} jend={} nact={}",
                    it, cur, qsel, drop_q, sel_ok, t, exti(jend_ref, cur_c), n_active,
                )
            t_any = (cur >= 0) & (t < exti(jend_ref, cur_c))
            t = jnp.minimum(t, T_pad - 1)
            drop = (cur >= 0) & ~t_any
            cls = exti(tcls_ref, t)
            pause = t_any & (exti(cho_ref, cls) != 0)
            proc = t_any & ~pause

            # -- feasibility over the node axis (vectorized) -------------
            req = extcol(creq_ref, cls)  # [R8,1]
            res = extcol(cres_ref, cls)
            has_sc = exti(chs_ref, cls) != 0
            gid = jnp.minimum(exti(cgid_ref, cls), GT - 1)
            tports = exti(cpt_ref, cls)

            req3 = req[:, :, None]  # [R8,1,1]
            fits_idle = jnp.all(req3 < idle_ref[:, :, :] + eps3, axis=0) & ~(
                has_sc & (nihs_ref[:, :] == 0)
            )
            fits_rel = jnp.all(req3 < rel_ref[:, :, :] + eps3, axis=0) & ~(
                has_sc & (nrhs_ref[:, :] == 0)
            )
            static_ok = cnode_ref[pl.ds(gid, 1), :, :][0] != 0
            room = ntasks_ref[:, :] < nmax_ref[:, :]
            port_ok = (nports_ref[:, :] & tports) == 0
            cand = static_ok & room & port_ok & (fits_idle | fits_rel)

            # -- score + deterministic best node -------------------------
            req_cpu = used_ref[0, :, :] + res[0, 0]
            req_mem = used_ref[1, :, :] + res[1, 0]
            cap_cpu = nalloc_ref[0, :, :]
            cap_mem = nalloc_ref[1, :, :]

            def least_dim(rq, cp):
                safe = jnp.where(cp == 0.0, 1.0, cp)
                sc = jnp.floor(
                    _ieee_div((cp - rq) * MAX_PRIORITY, safe)
                ).astype(jnp.int32)
                return jnp.where((cp == 0.0) | (rq > cp), 0, sc)

            least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
            cpu_f = jnp.where(
                cap_cpu != 0.0,
                _ieee_div(req_cpu, jnp.where(cap_cpu == 0.0, 1.0, cap_cpu)),
                1.0,
            )
            mem_f = jnp.where(
                cap_mem != 0.0,
                _ieee_div(req_mem, jnp.where(cap_mem == 0.0, 1.0, cap_mem)),
                1.0,
            )
            balanced = jnp.where(
                (cpu_f >= 1.0) | (mem_f >= 1.0),
                0,
                (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(
                    jnp.int32
                ),
            )
            score = (
                least.astype(jnp.float32) * w_least
                + balanced.astype(jnp.float32) * w_bal
                + affw_ref[pl.ds(gid, 1), :, :][0] * w_aff
            )
            if _DEBUG:
                jax.debug.print(
                    "  cls={} gid={} req0={} req1={} static={} room={} port={} fi={} fr={}",
                    cls, gid, req[0, 0], req[1, 0], jnp.sum(static_ok),
                    jnp.sum(room), jnp.sum(port_ok), jnp.sum(fits_idle),
                    jnp.sum(fits_rel),
                )
            big = jnp.max(jnp.where(cand, score, NINF))
            any_cand = big > NINF
            nb = jnp.min(jnp.where(cand & (score == big), nidx, N_pad))
            nb = jnp.minimum(nb, N_pad - 1)
            abandon = proc & ~any_cand
            assign = proc & any_cand

            # fits-idle at the chosen node (scalar recompute from slab,
            # per-dim unrolled — see extdim)
            nr, nl = nb // LANES, nb % LANES
            fits_idle_nb = ~(has_sc & (exti(nihs_ref, nb) == 0))
            for r in range(R8):
                req_r = extdim(creq_ref, cls, r)
                idle_r = extdim(idle_ref, nb, r)
                fits_idle_nb = fits_idle_nb & (req_r < idle_r + fscal_ref[r])
            do_alloc = assign & fits_idle_nb

            @pl.when(assign)
            def _():
                col_alloc = jnp.where(do_alloc, res, 0.0)
                col_pipe = jnp.where(do_alloc, 0.0, res)
                lmask = lane3 == nl
                idle_ref[:, pl.ds(nr, 1), :] = idle_ref[:, pl.ds(nr, 1), :] - jnp.where(
                    lmask, col_alloc[:, :, None], 0.0
                )
                rel_ref[:, pl.ds(nr, 1), :] = rel_ref[:, pl.ds(nr, 1), :] - jnp.where(
                    lmask, col_pipe[:, :, None], 0.0
                )
                used_ref[:, pl.ds(nr, 1), :] = used_ref[:, pl.ds(nr, 1), :] + jnp.where(
                    lmask, res[:, :, None], 0.0
                )
                rmw_add(ntasks_ref, nb, 1)
                nports_ref[pl.ds(nr, 1), :] = nports_ref[pl.ds(nr, 1), :] | jnp.where(
                    lane == nl, tports, 0
                )
                rmw_set(tnode_ref, t, nb)
                rmw_set(tkind_ref, t, jnp.where(do_alloc, 1, 2))
                rmw_set(tpos_ref, t, step)
                rmw_add(jready_ref, cur_c, jnp.where(do_alloc, 1, 0))
                if enable_drf:
                    rmw_add3(jalloc_ref, cur_c, res)
                if enable_proportion:
                    qcur = exti(jqueue_ref, cur_c)
                    rmw_add3(qalloc_ref, qcur, res)
                    res_has_sc = exti(crhs_ref, cls) != 0
                    rmw_set(
                        qahs_ref,
                        qcur,
                        jnp.where(res_has_sc, 1, exti(qahs_ref, qcur)),
                    )

            @pl.when(proc)
            def _():
                rmw_add(jptr_ref, cur_c, 1)

            retire = drop | abandon

            @pl.when(retire)
            def _():
                rmw_set(jactive_ref, cur_c, 0)
                rmw_add(qcount_ref, exti(jqueue_ref, cur_c), -1)

            n_active = n_active - jnp.where(retire, 1, 0)

            # -- gang barrier / next current job -------------------------
            ready_c = exti(jready_ref, cur_c)  # post-update value
            ready_now = ready_c >= exti(jmin_ref, cur_c)
            cur_next = jnp.where(retire | (proc & ready_now), -1, cur)

            return (
                it + 1,
                step + assign.astype(jnp.int32),
                cur_next,
                jnp.where(pause, t, -1),
                n_active,
            )

        def cond(carry):
            it, step, cur, paused, n_active = carry
            return ((cur >= 0) | (n_active > 0)) & (it < max_iter) & (paused < 0)

        it, step, cur, paused, n_active = lax.while_loop(
            cond,
            body,
            (iscal_ref[0], iscal_ref[1], iscal_ref[2], jnp.int32(-1), iscal_ref[4]),
        )
        oscal_ref[0] = it
        oscal_ref[1] = step
        oscal_ref[2] = cur
        oscal_ref[3] = paused
        oscal_ref[4] = n_active

    f32, i32 = jnp.float32, jnp.int32
    state_shapes = [
        ((Tr, LANES), i32),  # tnode
        ((Tr, LANES), i32),  # tkind
        ((Tr, LANES), i32),  # tpos
        ((R8, Nr, LANES), f32),  # idle
        ((R8, Nr, LANES), f32),  # rel
        ((R8, Nr, LANES), f32),  # used
        ((Nr, LANES), i32),  # ntasks
        ((Nr, LANES), i32),  # nports
        ((Jr, LANES), i32),  # jptr
        ((Jr, LANES), i32),  # jready
        ((Jr, LANES), i32),  # jactive
        ((Qr, LANES), i32),  # qdropped
        ((Qr, LANES), i32),  # qcount
        ((R8, Jr, LANES), f32),  # jalloc
        ((R8, Qr, LANES), f32),  # qalloc
        ((Qr, LANES), i32),  # qahs
    ]
    out_shape = [jax.ShapeDtypeStruct((16,), i32)] + [
        jax.ShapeDtypeStruct(s, d) for s, d in state_shapes
    ]
    in_specs = (
        [pl.BlockSpec(memory_space=pltpu.VMEM)] * 22
        + [pl.BlockSpec(memory_space=pltpu.SMEM)] * 4  # fscal, drft, drfd, iscal
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 16
    )
    out_specs = tuple(
        [pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 16
    )
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=interpret,
    )

    def wrapped(*args):
        """Concatenate the 17 outputs into one i32 + one f32 device
        buffer: a device->host fetch costs ~100ms of round-trip latency
        through the axon tunnel, so 17 per-array fetches would dominate
        the whole solve (measured: 1.65s fixed per call). The f32 buffer
        is only materialized on pause/resume or in tests."""
        (
            oscal, tnode, tkind, tpos, idle, rel, used, ntasks, nports,
            jptr, jready, jactive, qdropped, qcount, jalloc, qalloc, qahs,
        ) = call(*args)
        icat = jnp.concatenate(
            [
                oscal, tnode.ravel(), tkind.ravel(), tpos.ravel(),
                jptr.ravel(), jready.ravel(), jactive.ravel(),
                ntasks.ravel(), nports.ravel(), qdropped.ravel(),
                qcount.ravel(), qahs.ravel(),
            ]
        )
        fcat = jnp.concatenate(
            [
                idle.ravel(), rel.ravel(), used.ravel(),
                jalloc.ravel(), qalloc.ravel(),
            ]
        )
        return icat, fcat

    return jax.jit(wrapped)


# -- blocked sharded-Pallas entry (parallel/sharded_pallas) ---------------
#
# The block step is the per-shard half of one gang iteration: the fused
# feasibility + score + block-local argmax over the shard's node block,
# in the same folded [R8, Nr_loc, 128] VMEM layout and with the same
# float32 formulas as the single-chip fused kernel above. The caller
# (ShardedPallasSolver) exchanges the returned (best score, global node
# index, fits-idle bit) triple across the mesh axis per iteration and
# applies the winning capacity update on the owning shard only.
#
# fvec layout (f32, 32): [0:8] padded task req, [8:16] padded task res,
# [16:24] padded eps (pad dims carry 1.0 so the all-dims fit check
# ignores them), [24] w_least, [25] w_balanced (affinity weights are
# baked into affw at fold time, as in the single-chip kernel).
# ivec layout (i32, 8): [0] gid (pre-clamped to GT-1), [1] task has_sc,
# [2] task port bitmask, [3] global folded index offset of this shard's
# block, [4] the "no candidate" index sentinel (global padded N).

FVEC_LEN = 32
IVEC_LEN = 8


@lru_cache(maxsize=64)
def _build_block_step(Nr_loc: int, GT: int, interpret: bool):
    """Compile (cached per local block shape) the fused block step."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    MAX_PRIORITY = 10
    NINF = float("-inf")

    def kernel(
        ivec_ref, fvec_ref,
        cnode_ref, affw_ref, nalloc_ref, nmax_ref, nihs_ref, nrhs_ref,
        idle_ref, rel_ref, used_ref, ntasks_ref, nports_ref,
        oscore_ref, oidx_ref,
    ):
        gid = ivec_ref[0]
        has_sc = ivec_ref[1] != 0
        tports = ivec_ref[2]
        off = ivec_ref[3]
        sentinel = ivec_ref[4]

        lane = lax.broadcasted_iota(jnp.int32, (1, LANES), 1)
        nidx = (
            lax.broadcasted_iota(jnp.int32, (Nr_loc, LANES), 0) * LANES
            + lax.broadcasted_iota(jnp.int32, (Nr_loc, LANES), 1)
        )

        req_v = jnp.concatenate(
            [jnp.full((1, 1), fvec_ref[i], jnp.float32) for i in range(R8)]
        )
        eps_v = jnp.concatenate(
            [jnp.full((1, 1), fvec_ref[16 + i], jnp.float32) for i in range(R8)]
        )
        req3 = req_v[:, :, None]
        eps3 = eps_v[:, :, None]

        # -- feasibility over the local node block (== single-chip kernel) --
        fits_idle = jnp.all(req3 < idle_ref[:, :, :] + eps3, axis=0) & ~(
            has_sc & (nihs_ref[:, :] == 0)
        )
        fits_rel = jnp.all(req3 < rel_ref[:, :, :] + eps3, axis=0) & ~(
            has_sc & (nrhs_ref[:, :] == 0)
        )
        static_ok = cnode_ref[pl.ds(gid, 1), :, :][0] != 0
        room = ntasks_ref[:, :] < nmax_ref[:, :]
        port_ok = (nports_ref[:, :] & tports) == 0
        cand = static_ok & room & port_ok & (fits_idle | fits_rel)

        # -- score + deterministic block-local best ------------------------
        req_cpu = used_ref[0, :, :] + fvec_ref[8]
        req_mem = used_ref[1, :, :] + fvec_ref[9]
        cap_cpu = nalloc_ref[0, :, :]
        cap_mem = nalloc_ref[1, :, :]

        def least_dim(rq, cp):
            safe = jnp.where(cp == 0.0, 1.0, cp)
            sc = jnp.floor(
                _ieee_div((cp - rq) * MAX_PRIORITY, safe)
            ).astype(jnp.int32)
            return jnp.where((cp == 0.0) | (rq > cp), 0, sc)

        least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
        cpu_f = jnp.where(
            cap_cpu != 0.0,
            _ieee_div(req_cpu, jnp.where(cap_cpu == 0.0, 1.0, cap_cpu)),
            1.0,
        )
        mem_f = jnp.where(
            cap_mem != 0.0,
            _ieee_div(req_mem, jnp.where(cap_mem == 0.0, 1.0, cap_mem)),
            1.0,
        )
        balanced = jnp.where(
            (cpu_f >= 1.0) | (mem_f >= 1.0),
            0,
            (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(
                jnp.int32
            ),
        )
        score = (
            least.astype(jnp.float32) * fvec_ref[24]
            + balanced.astype(jnp.float32) * fvec_ref[25]
            + affw_ref[pl.ds(gid, 1), :, :][0]
        )
        big = jnp.max(jnp.where(cand, score, NINF))
        any_c = big > NINF
        nb = jnp.min(jnp.where(cand & (score == big), nidx, INT_MAX))
        nb = jnp.minimum(nb, Nr_loc * LANES - 1)

        # fits-idle at the block-local best (scalar recompute per dim —
        # Mosaic cannot do i1 vector extraction at [8,1], same idiom as
        # the single-chip kernel's extdim unroll)
        def exti(ref, idx):
            r, l = idx // LANES, idx % LANES
            return jnp.sum(
                jnp.where(lane == l, ref[pl.ds(r, 1), :], 0), dtype=jnp.int32
            )

        def extdim(ref3, idx, r):
            rr, l = idx // LANES, idx % LANES
            return jnp.sum(jnp.where(lane == l, ref3[r, pl.ds(rr, 1), :], 0.0))

        fits_nb = ~(has_sc & (exti(nihs_ref, nb) == 0))
        for r in range(R8):
            fits_nb = fits_nb & (
                fvec_ref[r] < extdim(idle_ref, nb, r) + fvec_ref[16 + r]
            )

        oscore_ref[0] = jnp.where(any_c, big, NINF)
        oidx_ref[0] = jnp.where(any_c, nb + off, sentinel)
        oidx_ref[1] = (any_c & fits_nb).astype(jnp.int32)

    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((1,), jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        ),
        in_specs=(
            [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 11
        ),
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        interpret=interpret,
    )

    def step(ivec, fvec, *blocks):
        oscore, oidx = call(ivec, fvec, *blocks)
        return oscore[0], oidx[0], oidx[1]

    return step


def block_step_jnp(ivec, fvec, cnode, affw, nalloc, nmax, nihs, nrhs,
                   idle, rel, used, ntasks, nports):
    """jnp twin of the fused block step — identical folded layout and
    float32 formulas, as plain XLA ops. The fast compiled path on
    non-TPU meshes (virtual-CPU parity tests and bench rows) and the
    cross-check oracle the interpret-mode kernel is pinned against."""
    import jax.numpy as jnp
    from jax import lax

    MAX_PRIORITY = 10
    gid = ivec[0]
    has_sc = ivec[1] != 0
    tports = ivec[2]
    off = ivec[3]
    sentinel = ivec[4]
    Nr_loc = nmax.shape[0]

    req3 = fvec[:R8][:, None, None]
    eps3 = fvec[16:24][:, None, None]
    fits_idle = jnp.all(req3 < idle + eps3, axis=0) & ~(has_sc & (nihs == 0))
    fits_rel = jnp.all(req3 < rel + eps3, axis=0) & ~(has_sc & (nrhs == 0))
    static_ok = cnode[gid] != 0
    room = ntasks < nmax
    port_ok = (nports & tports) == 0
    cand = static_ok & room & port_ok & (fits_idle | fits_rel)

    req_cpu = used[0] + fvec[8]
    req_mem = used[1] + fvec[9]
    cap_cpu = nalloc[0]
    cap_mem = nalloc[1]

    def least_dim(rq, cp):
        safe = jnp.where(cp == 0.0, 1.0, cp)
        sc = jnp.floor(_ieee_div((cp - rq) * MAX_PRIORITY, safe)).astype(jnp.int32)
        return jnp.where((cp == 0.0) | (rq > cp), 0, sc)

    least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
    cpu_f = jnp.where(
        cap_cpu != 0.0,
        _ieee_div(req_cpu, jnp.where(cap_cpu == 0.0, 1.0, cap_cpu)),
        1.0,
    )
    mem_f = jnp.where(
        cap_mem != 0.0,
        _ieee_div(req_mem, jnp.where(cap_mem == 0.0, 1.0, cap_mem)),
        1.0,
    )
    balanced = jnp.where(
        (cpu_f >= 1.0) | (mem_f >= 1.0),
        0,
        (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(jnp.int32),
    )
    score = (
        least.astype(jnp.float32) * fvec[24]
        + balanced.astype(jnp.float32) * fvec[25]
        + affw[gid]
    )
    nidx = (
        lax.broadcasted_iota(jnp.int32, (Nr_loc, LANES), 0) * LANES
        + lax.broadcasted_iota(jnp.int32, (Nr_loc, LANES), 1)
    )
    NINF = jnp.float32(-jnp.inf)
    big = jnp.max(jnp.where(cand, score, NINF))
    any_c = big > NINF
    nb = jnp.min(jnp.where(cand & (score == big), nidx, INT_MAX))
    nb = jnp.minimum(nb, Nr_loc * LANES - 1)
    rr, l = nb // LANES, nb % LANES
    fits_nb = ~(has_sc & (nihs[rr, l] == 0)) & jnp.all(
        fvec[:R8] < idle[:, rr, l] + fvec[16:24]
    )
    return (
        jnp.where(any_c, big, NINF),
        jnp.where(any_c, nb + off, sentinel),
        (any_c & fits_nb).astype(jnp.int32),
    )


class PallasSolver:
    """Per-execute driver: pack once, then solve / resume.

    Speaks the same `SolveState` protocol as ops.kernels so the action's
    segmented pod-affinity hybrid works unchanged.
    """

    def __init__(
        self,
        a: dict,
        enable_drf: bool,
        enable_proportion: bool,
        interpret: bool = False,
        fetch_f32: bool = False,
    ) -> None:
        self.a = a
        self.enable_drf = enable_drf
        self.enable_proportion = enable_proportion
        self._fetch_f32 = fetch_f32  # tests compare idle/used; replay doesn't
        self.packed = pack(a, enable_drf, enable_proportion)
        self._pod_sc = a.get("pod_sc")  # identity marker for refresh
        Tr, Nr, Jr, Qr, Cr, GT, R, self.max_iter = self.packed.dims
        self.fn = _build(
            Tr, Nr, Jr, Qr, Cr, GT, R, enable_drf, enable_proportion, interpret
        )

    _AFFW_IDX = 9  # affw's position in _Packed.statics

    def trace_args(self, state: SolveState | None = None) -> tuple:
        """The concrete argument tuple ``solve`` passes to the traced
        program ``self.fn``. Public so the trace auditor
        (analysis/trace) can walk the fused kernel's jaxpr on these
        arguments' avals without executing it."""
        if state is None:
            state = _initial_state(self.a, self.enable_drf, self.enable_proportion)
        return self._program_args(state)

    def _program_args(self, state: SolveState) -> tuple:
        p = self.packed
        Tr, Nr, Jr, Qr, Cr, GT, R, max_iter = p.dims
        f32, i32 = np.float32, np.int32
        job_active = np.asarray(state.job_active, bool)
        job_queue = np.asarray(self.a["job_queue"], np.int64)
        qcount = np.bincount(
            job_queue[job_active], minlength=p.n_queues_pad
        ).astype(i32)
        n_active = int(job_active.sum())

        iscal = np.zeros(16, i32)
        iscal[0] = int(state.it)
        iscal[1] = int(state.step)
        iscal[2] = int(state.cur)
        iscal[3] = -1
        iscal[4] = n_active
        iscal[5] = max_iter

        nports_bits = _ports_mask(np.asarray(state.nports, bool))
        folded_state = [
            _fold1(np.asarray(state.assigned_node, i32), Tr, i32, pad=-1),
            _fold1(np.asarray(state.assigned_kind, i32), Tr, i32),
            _fold1(np.asarray(state.assign_pos, i32), Tr, i32, pad=-1),
            _fold2(np.asarray(state.idle, f32), Nr, f32),
            _fold2(np.asarray(state.rel, f32), Nr, f32),
            _fold2(np.asarray(state.used, f32), Nr, f32),
            _fold1(np.asarray(state.ntasks, i32), Nr, i32),
            _fold1(nports_bits, Nr, i32),
            _fold1(np.asarray(state.ptr, i32), Jr, i32),
            _fold1(np.asarray(state.ready_cnt, i32), Jr, i32),
            _fold1(job_active.astype(i32), Jr, i32),
            _fold1(np.asarray(state.q_dropped, i32), Qr, i32),
            _fold1(qcount, Qr, i32),
            _fold2(np.asarray(state.job_alloc, f32), Jr, f32),
            _fold2(np.asarray(state.q_alloc, f32), Qr, f32),
            _fold1(np.asarray(state.q_alloc_has_sc, i32), Qr, i32),
        ]
        return (*p.statics, iscal, *folded_state)

    def solve(self, state: SolveState | None = None) -> SolveState:
        p = self.packed
        Tr, Nr, Jr, Qr, Cr, GT, R, max_iter = p.dims
        if self.a.get("pod_sc") is not self._pod_sc:
            # The action recomputed live InterPodAffinity scores after a
            # host-stepped pod landed (VERDICT r3 item 7): re-fold just
            # the affinity static and resume with the fresh scores.
            self._pod_sc = self.a.get("pod_sc")
            p.statics[self._AFFW_IDX] = fold_affinity_scores(self.a, Nr)
        if state is None:
            state = _initial_state(self.a, self.enable_drf, self.enable_proportion)
        icat_d, fcat_d = self.fn(*self._program_args(state))
        icat = np.asarray(icat_d)  # ONE round-trip for everything integer

        TL, NL, JL, QL = Tr * LANES, Nr * LANES, Jr * LANES, Qr * LANES
        T, J, Q, N = p.n_tasks_pad, p.n_jobs_pad, p.n_queues_pad, p.n_nodes_pad
        pos = [0]

        def take(n):
            s = icat[pos[0] : pos[0] + n]
            pos[0] += n
            return s

        oscal = take(16)
        tnode = take(TL)[:T]
        tkind = take(TL)[:T]
        tpos = take(TL)[:T]
        jptr = take(JL)[:J]
        jready = take(JL)[:J]
        jactive = take(JL)[:J]
        ntasks = take(NL)[:N]
        nport_bits = take(NL)[:N]
        qdropped = take(QL)[:Q]
        take(QL)  # qcount (derived; recomputed at next entry)
        qahs = take(QL)[:Q]

        paused = int(oscal[3])
        if paused >= 0 or self._fetch_f32:
            # Only pause/resume (the pod-affinity hybrid) and the parity
            # tests need the float state on the host; one more round-trip.
            fcat = np.asarray(fcat_d)
            fpos = [0]

            def ftake(n):
                s = fcat[fpos[0] : fpos[0] + n]
                fpos[0] += n
                return s

            idle = _unfold2(ftake(R8 * NL).reshape(R8, Nr, LANES), N, R)
            rel = _unfold2(ftake(R8 * NL).reshape(R8, Nr, LANES), N, R)
            used = _unfold2(ftake(R8 * NL).reshape(R8, Nr, LANES), N, R)
            jalloc = _unfold2(ftake(R8 * JL).reshape(R8, Jr, LANES), J, R)
            qalloc = _unfold2(ftake(R8 * QL).reshape(R8, Qr, LANES), Q, R)
        else:
            # Unused by the replay path on a completed solve; carry the
            # entry state forward so the tuple stays well-formed.
            idle, rel, used = state.idle, state.rel, state.used
            jalloc, qalloc = state.job_alloc, state.q_alloc

        P = np.asarray(self.a["task_ports"]).shape[1]
        nports_bool = (nport_bits[:, None] & (1 << np.arange(P, dtype=np.int64))) != 0
        return SolveState(
            it=np.int32(oscal[0]),
            step=np.int32(oscal[1]),
            cur=np.int32(oscal[2]),
            ptr=jptr,
            assigned_node=tnode,
            assigned_kind=tkind,
            assign_pos=tpos,
            idle=idle,
            rel=rel,
            used=used,
            ntasks=ntasks,
            nports=nports_bool,
            ready_cnt=jready,
            job_active=jactive.astype(bool),
            q_dropped=qdropped.astype(bool),
            job_alloc=jalloc,
            q_alloc=qalloc,
            q_alloc_has_sc=qahs.astype(bool),
            paused_at=np.int32(paused),
        )
