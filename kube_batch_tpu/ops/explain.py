"""Batched unschedulability forensics over the post-solve arena tensors.

The solve kernel (ops/kernels.py) already materializes, for every task it
pops, the four feasibility planes of the serial predicate scan —

- ``static``:   label-compat gather x taints/cordon (``node_ok & compat``)
- ``room``:     pod-count headroom (``ntasks < node_max_tasks``)
- ``ports``:    dynamic host-port bitmask disjointness
- ``resources``: epsilon-tolerant fit against idle OR releasing, with the
  Go nil-scalar-map parity bits (resource_info.go:255-278)

— but discards them after the argmax. This module re-evaluates exactly
those planes *after* the solve, against the final node state, for one
representative task per still-pending gang (the first unassigned row in
pop order — the task the serial loop abandoned on), and reduces them to
the three answers an operator asks for:

(a) per-plane node elimination counts — the dense-tensor analogue of
    kube-scheduler's "0/40k nodes: 12k insufficient-cpu, 28k affinity";
(b) top-k near-miss nodes by the solver's own score with per-plane
    feasibility bits (which constraint each almost-fit node fails);
(c) leave-one-plane-out would-fit-if verdicts: does relaxing a single
    plane make at least one node feasible?

Everything is one jitted vmap over the (padded) representative rows, so
marginal cost is a few [N] reductions per pending gang per cycle. The
numpy twin (`explain_rows_np`) computes the identical numbers task by
task with correctly-rounded host arithmetic, pinning explain parity
serial = XLA = mesh the same way the solver pins placement parity.

Scores deliberately omit the InterPodAffinity term (``pod_sc``): it is
the one score input recomputed host-side per segmented step, so the
pre-solve matrix the device holds and the post-action matrix a serial
re-encode sees can legitimately differ. The static affinity term
(``aff_sc``) is per (task-group, node-group) and identical across
encodes of the same world, so it stays in the ranking.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from kube_batch_tpu.ops.kernels import MAX_PRIORITY, _le_eps, ieee_div

# Fixed plane order: elimination counts, would-fit-if verdicts and
# near-miss bit vectors are all indexed by this tuple, and the dominant
# reason tie-break is first-plane-wins over it.
PLANES = ("static", "room", "ports", "resources")

# Keys of the encode arrays the forensics kernel reads (a strict subset
# of the solver's inputs — nothing here mutates or extends the arena).
ARRAY_KEYS = (
    "task_req",
    "task_res",
    "task_gid",
    "task_has_sc",
    "task_ports",
    "node_ok",
    "node_valid",
    "node_gid",
    "node_max_tasks",
    "node_alloc",
    "node_idle_has_sc",
    "node_rel_has_sc",
    "compat",
    "aff_sc",
    "eps",
)


def pad_rows(rows: list[int], floor: int = 8) -> np.ndarray:
    """Pad a representative-row list to the next power-of-two bucket with
    -1 sentinels so the jitted program recompiles per world shape, not
    per pending-gang count (same bucketing discipline as ops/encode)."""
    n = max(len(rows), 1)
    cap = floor
    while cap < n:
        cap *= 2
    out = np.full(cap, -1, np.int32)
    out[: len(rows)] = rows
    return out


def _score_planes(a, idle, rel, used, ntasks, nports, t, xp):
    """The shared plane + score math for one representative task row.

    ``xp`` is jnp on the batched device path and np on the serial twin;
    every divide goes through ieee_div on device (correctly rounded, see
    kernels.ieee_div) and the native / operator on host, which numpy
    already rounds correctly — the same parity contract the solver's
    score path relies on."""
    fdtype = a["task_req"].dtype
    req = a["task_req"][t]
    if xp is jnp:
        fits_idle = _le_eps(req, idle, a["eps"])
        fits_rel = _le_eps(req, rel, a["eps"])
        div = ieee_div
    else:
        fits_idle = np.all(req[None, :] < idle + a["eps"][None, :], axis=1)
        fits_rel = np.all(req[None, :] < rel + a["eps"][None, :], axis=1)

        def div(x, y):
            return x / y

    has_sc = a["task_has_sc"][t]
    fits_idle = fits_idle & ~(has_sc & ~a["node_idle_has_sc"])
    fits_rel = fits_rel & ~(has_sc & ~a["node_rel_has_sc"])
    resources = fits_idle | fits_rel
    static_ok = a["node_ok"] & a["compat"][a["task_gid"][t], a["node_gid"]]
    room = ntasks < a["node_max_tasks"]
    ports = ~xp.any(a["task_ports"][t][None, :] & nports, axis=1)
    planes = xp.stack([static_ok, room, ports, resources])  # [4, N]

    # Score: the solver's LeastRequested + BalancedResourceAllocation +
    # static-affinity formula verbatim (kernels.body HOT LOOP #2), minus
    # the pod_sc term — see the module docstring.
    res = a["task_res"][t]
    req_cpu = used[:, 0] + res[0]
    req_mem = used[:, 1] + res[1]
    cap_cpu = a["node_alloc"][:, 0]
    cap_mem = a["node_alloc"][:, 1]

    def least_dim(rq, cp):
        safe = xp.where(cp == 0, 1.0, cp)
        sc = xp.floor(div((cp - rq) * MAX_PRIORITY, safe)).astype(xp.int32)
        return xp.where((cp == 0) | (rq > cp), 0, sc)

    least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
    cpu_f = xp.where(
        cap_cpu != 0, div(req_cpu, xp.where(cap_cpu == 0, 1.0, cap_cpu)), 1.0
    )
    mem_f = xp.where(
        cap_mem != 0, div(req_mem, xp.where(cap_mem == 0, 1.0, cap_mem)), 1.0
    )
    balanced = xp.where(
        (cpu_f >= 1.0) | (mem_f >= 1.0),
        0,
        (MAX_PRIORITY - xp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(xp.int32),
    )
    score = (
        least.astype(fdtype) * xp.asarray(a["w_least"], fdtype)
        + balanced.astype(fdtype) * xp.asarray(a["w_balanced"], fdtype)
        + a["aff_sc"][a["task_gid"][t], a["node_gid"]].astype(fdtype)
        * xp.asarray(a["w_aff"], fdtype)
    )
    return planes, score


@partial(jax.jit, static_argnames=("topk",))
def _explain_jit(a, idle, rel, used, ntasks, nports, rep_rows, topk):
    valid = a["node_valid"]
    T = a["task_req"].shape[0]

    def one(t):
        tc = jnp.clip(jnp.maximum(t, 0), 0, T - 1)
        planes, score = _score_planes(a, idle, rel, used, ntasks, nports, tc, jnp)
        elim = jnp.sum(valid[None, :] & ~planes, axis=1).astype(jnp.int32)
        feasible = jnp.sum(valid & jnp.all(planes, axis=0)).astype(jnp.int32)
        would = jnp.stack(
            [
                jnp.any(valid & jnp.all(planes.at[p].set(True), axis=0))
                for p in range(len(PLANES))
            ]
        )
        # Deterministic top-k: k argmax+mask rounds, first index wins
        # ties — byte-identical to the numpy twin's loop (lax.top_k's
        # tie contract is not worth pinning a parity surface to).
        ranked = jnp.where(valid, score, -jnp.inf)
        idxs = []
        vals = []
        for _ in range(topk):
            i = jnp.argmax(ranked).astype(jnp.int32)
            idxs.append(i)
            vals.append(score[i])
            ranked = ranked.at[i].set(-jnp.inf)
        nm_idx = jnp.stack(idxs)
        nm_score = jnp.stack(vals)
        nm_planes = planes[:, nm_idx].T  # [k, 4]
        return elim, feasible, would, nm_idx, nm_score, nm_planes

    return jax.vmap(one)(rep_rows)


def explain_batch(a, idle, rel, used, ntasks, nports, rep_rows, topk=3):
    """Batched device forensics over padded representative rows.

    ``a`` is the solver's arrays dict (host or device residency — any
    mix works, jit transfers what it needs); the five state tensors are
    the *final* SolveState fields. Returns host numpy arrays
    ``(elim [G,4], feasible [G], would_fit [G,4], nm_idx [G,k],
    nm_score [G,k], nm_planes [G,k,4])``; rows where ``rep_rows`` is -1
    are padding and carry garbage the caller must mask."""
    sub = {k: a[k] for k in ARRAY_KEYS}
    for w in ("w_least", "w_balanced", "w_aff"):
        sub[w] = jnp.asarray(a[w], a["task_req"].dtype)
    out = _explain_jit(
        sub,
        jnp.asarray(idle),
        jnp.asarray(rel),
        jnp.asarray(used),
        jnp.asarray(ntasks),
        jnp.asarray(nports),
        jnp.asarray(rep_rows, jnp.int32),
        topk=int(topk),
    )
    return tuple(np.asarray(x) for x in out)


# Node-side keys _score_planes reads per node — the class key for the
# compressed explain path. Two nodes with identical bytes across these
# slabs plus the five state tensors produce identical planes and scores,
# so forensics only has to evaluate one representative per class.
_CLASS_NODE_KEYS = (
    "node_alloc",
    "node_ok",
    "node_valid",
    "node_gid",
    "node_max_tasks",
    "node_idle_has_sc",
    "node_rel_has_sc",
)


@jax.jit
def _planes_scores_jit(a, idle, rel, used, ntasks, nports, rep_rows):
    """Raw (planes [G, 4, C], score [G, C]) over class-representative
    node rows — the same _score_planes ops as _explain_jit, so a class
    row produces the identical bytes its member nodes would."""
    T = a["task_req"].shape[0]

    def one(t):
        tc = jnp.clip(jnp.maximum(t, 0), 0, T - 1)
        return _score_planes(a, idle, rel, used, ntasks, nports, tc, jnp)

    return jax.vmap(one)(rep_rows)


def explain_batch_classes(a, idle, rel, used, ntasks, nports, rep_rows, topk=3):
    """Class-compressed forensics: byte-identical outputs to
    ``explain_batch``, with the per-node device evaluation folded to one
    row per node equivalence class (ops/class_solve key discipline).

    The final node state is grouped over the explain-relevant key (the
    static node slabs _score_planes reads plus the five dynamic state
    tensors); planes and scores are evaluated on class representatives
    only, then expanded on host: elimination / feasible counts by valid
    member multiplicity, would-fit-if by class validity, and the top-k
    near-miss list by replaying the node-level argmax tie contract
    (score descending, lowest node row wins ties) from the sorted
    member lists. Cost scales with class count, not node count."""
    from kube_batch_tpu.ops.class_solve import _pow2, dedup_rows

    idle = np.asarray(idle)
    rel = np.asarray(rel)
    used = np.asarray(used)
    ntasks = np.asarray(ntasks)
    nports = np.asarray(nports)
    sub = {k: np.asarray(a[k]) for k in ARRAY_KEYS}
    first, inv = dedup_rows(
        [sub[k] for k in _CLASS_NODE_KEYS] + [idle, rel, used, ntasks, nports]
    )
    C = int(first.shape[0])
    counts = np.bincount(inv, minlength=C).astype(np.int64)
    order = np.argsort(inv, kind="stable").astype(np.int64)
    off = np.zeros(C, np.int64)
    np.cumsum(counts[:-1], out=off[1:])
    rep = order[off]  # lowest member row per class (= first occurrence)

    # Pad the class axis to a power-of-two bucket (index-0 repeats) so
    # the jitted program recompiles per bucket, not per class count.
    Cp = _pow2(C)
    rep_p = np.concatenate([rep, np.zeros(Cp - C, np.int64)])
    for key in _CLASS_NODE_KEYS:
        sub[key] = sub[key][rep_p]
    for w in ("w_least", "w_balanced", "w_aff"):
        sub[w] = jnp.asarray(a[w], np.asarray(a["task_req"]).dtype)
    planes_c, score_c = _planes_scores_jit(
        sub,
        jnp.asarray(idle[rep_p]),
        jnp.asarray(rel[rep_p]),
        jnp.asarray(used[rep_p]),
        jnp.asarray(ntasks[rep_p]),
        jnp.asarray(nports[rep_p]),
        jnp.asarray(rep_rows, jnp.int32),
    )
    planes_c = np.asarray(planes_c)  # [G, 4, Cp] bool
    score_c = np.asarray(score_c)  # [G, Cp] fdtype

    valid_c = np.asarray(a["node_valid"], bool)[rep]  # class-uniform (in key)
    vcounts = np.where(valid_c, counts, 0)
    G = len(rep_rows)
    k = int(topk)
    P = len(PLANES)
    elim = np.zeros((G, P), np.int32)
    feasible = np.zeros(G, np.int32)
    would = np.zeros((G, P), bool)
    nm_idx = np.zeros((G, k), np.int32)
    nm_score = np.zeros((G, k), score_c.dtype)
    nm_planes = np.zeros((G, k, P), bool)
    vcls = np.flatnonzero(valid_c)
    for g, t in enumerate(np.asarray(rep_rows)):
        if t < 0:
            continue  # padding row: explain_batch carries garbage here too
        pl = planes_c[g][:, :C]  # [4, C]
        sc = score_c[g][:C]
        elim[g] = (vcounts[None, :] * ~pl).sum(axis=1)
        feasible[g] = int((vcounts * pl.all(axis=0)).sum())
        for p in range(P):
            relaxed = pl.copy()
            relaxed[p] = True
            would[g, p] = bool((valid_c & relaxed.all(axis=0)).any())
        # Top-k replay of the node-level argmax+mask rounds. Classes
        # sorted by (score desc, lowest member); take classes until k
        # members are covered, then extend through the boundary score
        # tie group — members of equal-score classes interleave by node
        # row, so every class tied at the cut must be materialized.
        m = 0
        if vcls.size:
            o = vcls[np.lexsort((rep[vcls], -sc[vcls]))]
            taken = 0
            i = 0
            while i < o.size and taken < k:
                taken += counts[o[i]]
                i += 1
            while i < o.size and sc[o[i]] == sc[o[i - 1]]:
                i += 1
            chosen = o[:i]
            mem_nodes = np.concatenate(
                [order[off[c] : off[c] + counts[c]] for c in chosen]
            )
            mem_cls = np.repeat(chosen, counts[chosen])
            sidx = np.lexsort((mem_nodes, -sc[mem_cls]))[:k]
            nodes, cls = mem_nodes[sidx], mem_cls[sidx]
            m = nodes.size
            nm_idx[g, :m] = nodes
            nm_score[g, :m] = sc[cls]
            nm_planes[g, :m] = pl[:, cls].T
        if m < k:
            # Node-level exhaustion contract: argmax over an all -inf
            # ranking returns row 0, so the pad entry is node 0's raw
            # score and planes, repeated.
            c0 = inv[0]
            nm_idx[g, m:] = 0
            nm_score[g, m:] = sc[c0]
            nm_planes[g, m:] = pl[:, c0]
    return elim, feasible, would, nm_idx, nm_score, nm_planes


def explain_rows_np(a, idle, rel, used, ntasks, nports, rep_rows, topk=3):
    """The serial twin: identical numbers, computed task by task with
    host numpy (the correctness-oracle side of explain parity)."""
    valid = np.asarray(a["node_valid"], bool)
    G = len(rep_rows)
    k = int(topk)
    elim = np.zeros((G, len(PLANES)), np.int32)
    feasible = np.zeros(G, np.int32)
    would = np.zeros((G, len(PLANES)), bool)
    nm_idx = np.zeros((G, k), np.int32)
    nm_score = np.zeros((G, k), np.float64)
    nm_planes = np.zeros((G, k, len(PLANES)), bool)
    for g, t in enumerate(rep_rows):
        if t < 0:
            continue
        planes, score = _score_planes(a, idle, rel, used, ntasks, nports, int(t), np)
        elim[g] = np.sum(valid[None, :] & ~planes, axis=1)
        feasible[g] = np.sum(valid & np.all(planes, axis=0))
        for p in range(len(PLANES)):
            relaxed = planes.copy()
            relaxed[p] = True
            would[g, p] = bool(np.any(valid & np.all(relaxed, axis=0)))
        ranked = np.where(valid, score, -np.inf)
        for j in range(k):
            i = int(np.argmax(ranked))
            nm_idx[g, j] = i
            nm_score[g, j] = score[i]
            nm_planes[g, j] = planes[:, i]
            ranked[i] = -np.inf
    return elim, feasible, would, nm_idx, nm_score, nm_planes
