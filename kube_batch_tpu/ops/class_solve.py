"""Node-class compressed solve (``KBT_CLASS_COMPRESS``, default off).

Production fleets have dozens-to-hundreds of distinct node *shapes* —
capacity signature, labels/taints, idle vector — so at 400k x 40k the
node axis the solver tiers scan every gang iteration is overwhelmingly
redundant. This module folds interchangeable nodes into equivalence
classes and runs feasibility + score + argmax at **class granularity**:

- The per-node class key is byte-exact over every array the kernel's
  fit/score block reads (idle, releasing, used, capacity, pod count,
  port mask, static-feasibility bits, label/affinity group id, live
  InterPodAffinity column), reusing the encode slabs directly — no
  re-derivation, so two nodes share a class iff the uncompressed kernel
  could not tell them apart.
- Dedup runs through the native ``class_dedup`` hash pass (multi-slab
  form, satellite of this PR) with a widened ``np.unique`` fallback and
  the pre-existing ``native.class_dedup`` fault point.
- The compressed kernel mirrors ``ops.kernels.solve_allocate_step``
  operation-for-operation over the class axis (shared
  ``select_queue_job``, shared ``ieee_div``/``_le_eps`` numerics), with
  a multiplicity counter per class. Selection uses
  ``_lex_argmin(cand, -score, tiebreak)`` where ``tiebreak`` is each
  class's lowest member node row — exactly the uncompressed kernel's
  ``argmax`` first-row tie-break, so placement is **bind-for-bind
  identical** by construction.
- **Dynamic splitting**: a bind changes only the chosen node, so that
  member splits off into a fresh singleton slot (statics copied, task
  deltas applied) while the parent class decrements its multiplicity
  and advances its member cursor — no per-iteration re-dedup. The slot
  axis is padded to a sticky power-of-two bucket (grow-only per action
  lifetime) so warm cycles stay at zero recompiles under churn; slot
  exhaustion pauses the kernel, the host re-buckets to the next power
  of two (bounded by the node bucket — slots can never exceed live
  nodes) and resumes mid-iteration.
- At segment boundaries (pod-affinity pause/resume, streaming
  micro-cycles absorbing peer-bind occupancy patches, the next cycle's
  encode) the table regroups from the current node-space state: split
  members whose rows re-converged **re-merge** into shared classes, and
  a node whose *static* key changed (encode-cache dirty node) is
  dropped from its class and re-keyed — both metered on
  ``class_table_splits_total`` / the solver stats.

The solver wraps whichever tier ``_make_solver`` picked and speaks
node-space ``SolveState`` at every boundary (pause/resume, result,
explain), expanding class state through the member table — per shard
when a mesh is configured (replicated class table, per-shard
membership), matching the GSPMD rung's layout. Any failure, or the
``solve.class_table`` fault point, drops the cycle to the uncompressed
tier loudly (``degraded_cycles`` + error log).

``python -m kube_batch_tpu.ops.class_solve --json`` runs the seeded
self-check: a heterogeneous node-pool world solved serial, uncompressed
and compressed (bind parity asserted), across two cycles so in-solve
splits AND cross-cycle re-merges are both exercised.
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kube_batch_tpu.ops.kernels import (
    KIND_ALLOCATED,
    KIND_PIPELINED,
    MAX_PRIORITY,
    SolveState,
    _le_eps,
    _lex_argmin,
    ieee_div,
    select_queue_job,
)

ENV = "KBT_CLASS_COMPRESS"
_ON_WORDS = ("1", "true", "on", "yes")

log = logging.getLogger("kube_batch_tpu.ops.class_solve")


def enabled() -> bool:
    return os.environ.get(ENV, "").strip().lower() in _ON_WORDS


def _pow2(n: int) -> int:
    return max(8, 1 << (max(int(n), 1) - 1).bit_length())


# -- multi-slab row dedup -----------------------------------------------------


def _as_rows(slab) -> np.ndarray:
    """Byte-exact [N, k] uint8 view of one key slab (1-D slabs become one
    column). Views require contiguity; the copy is taken at most once."""
    s = np.ascontiguousarray(slab)
    if s.ndim == 1:
        s = s.reshape(-1, 1)
    return s.view(np.uint8).reshape(s.shape[0], -1)


def dedup_rows(slabs) -> tuple[np.ndarray, np.ndarray]:
    """Dedup rows over the concatenated byte spans of ``slabs`` (each
    [N] or [N, k], equal row counts): returns (first int64 per class,
    inverse int32 per row). Native ``class_dedup`` multi-buffer hash
    pass when available (one O(N) pass, no Python-level concat), else
    the widened np.unique void-sort. Class *order* differs between the
    two paths (first-occurrence vs sorted) and carries no meaning —
    callers derive representatives from member lists, never from ids."""
    mats = [_as_rows(s) for s in slabs]
    n = mats[0].shape[0]
    if any(m.shape[0] != n for m in mats):
        raise ValueError("dedup_rows slabs disagree on row count")
    from kube_batch_tpu import faults as _faults
    from kube_batch_tpu.native import lib as _native

    if (
        _native is not None
        and hasattr(_native, "class_dedup")
        and not _faults.should_fire("native.class_dedup")
    ):
        try:
            arg = mats[0] if len(mats) == 1 else tuple(mats)
            first_b, inv_b = _native.class_dedup(arg)
            return (
                np.frombuffer(first_b, np.int64),
                np.frombuffer(inv_b, np.int32),
            )
        except TypeError:
            # older single-buffer extension: fall through to the
            # widened host path rather than failing the cycle
            log.debug("native class_dedup lacks multi-buffer keys; using np.unique")
    key = np.ascontiguousarray(np.concatenate(mats, axis=1))
    void = key.view(np.dtype((np.void, key.shape[1])))
    _, first, inv = np.unique(void.ravel(), return_index=True, return_inverse=True)
    return first.astype(np.int64), inv.astype(np.int32)


# -- cross-cycle static class table -------------------------------------------

_STATIC_KEYS = (
    "node_alloc",
    "node_ok",
    "node_valid",
    "node_max_tasks",
    "node_idle_has_sc",
    "node_rel_has_sc",
    "node_gid",
)


class ClassTable:
    """The persistent half of the compression: static per-node keys
    (capacity, feasibility bits, label/affinity group) deduped once,
    then delta-refreshed — a churned node's changed row is dropped from
    its class and re-keyed through the key dict (re-merging with any
    class already holding that key), without re-hashing the fleet.
    Class ids are stable across cycles so the dynamic regroup (which
    folds them into its key) stays incremental-friendly; the sticky
    power-of-two slot bucket lives here so warm cycles never change the
    compiled class-kernel shapes."""

    def __init__(self) -> None:
        self.key_bytes: np.ndarray | None = None  # [N, K] uint8
        self.class0_of: np.ndarray | None = None  # [N] int32 stable static ids
        self.key_to_id: dict[bytes, int] = {}
        self.sticky_cpad = 8
        self.rekeys_total = 0  # static-key churn (encode-cache dirty nodes)
        self.splits_total = 0  # in-solve bind splits
        self.remerges_total = 0
        self.rebuilds = 0
        self._prev_singleton: np.ndarray | None = None  # [N] bool at last solve end

    def _next_id(self, key: bytes) -> int:
        cid = self.key_to_id.get(key)
        if cid is None:
            cid = len(self.key_to_id)
            self.key_to_id[key] = cid
        return cid

    def refresh_static(self, arrays: dict) -> tuple[np.ndarray, int]:
        """Return ([N] stable static class ids, re-keyed row count)."""
        mats = [_as_rows(np.asarray(arrays[k])) for k in _STATIC_KEYS]
        key = np.ascontiguousarray(np.concatenate(mats, axis=1))
        if (
            self.key_bytes is None
            or self.key_bytes.shape != key.shape
            or self.class0_of is None
        ):
            # cold (or re-bucketed fleet): one dedup pass, ids minted in
            # class order so a later warm refresh maps changed rows only
            first, inv = dedup_rows([key])
            ids = np.fromiter(
                (self._next_id(key[r].tobytes()) for r in first),
                np.int32,
                count=len(first),
            )
            self.class0_of = ids[inv]
            self.key_bytes = key
            self.rebuilds += 1
            self._prev_singleton = None
            return self.class0_of, 0
        changed = np.nonzero(np.any(self.key_bytes != key, axis=1))[0]
        if changed.size:
            out = self.class0_of.copy()
            for r in changed:
                out[r] = self._next_id(key[r].tobytes())
            self.class0_of = out
            self.key_bytes = key
            self.rekeys_total += int(changed.size)
        return self.class0_of, int(changed.size)

    def note_end(self, slot_of_end: np.ndarray) -> None:
        counts = np.bincount(slot_of_end, minlength=int(slot_of_end.max()) + 1)
        self._prev_singleton = counts[slot_of_end] == 1

    def note_regroup(self, slot_of: np.ndarray, counts: np.ndarray) -> int:
        """Re-merge accounting: nodes that sat in singleton slots at the
        last solve end and now share a multi-member class again."""
        if self._prev_singleton is None or self._prev_singleton.shape != slot_of.shape:
            return 0
        merged = int(np.count_nonzero(self._prev_singleton & (counts[slot_of] > 1)))
        self.remerges_total += merged
        return merged


# -- the class-granularity kernel ---------------------------------------------


class ClassSolveState(NamedTuple):
    """``SolveState`` with the node axis folded to slot granularity plus
    the split machinery (multiplicity, member cursor, free slot pointer).
    Job/queue/task fields keep their ``SolveState`` names so
    ``select_queue_job`` reads this state unchanged."""

    it: "np.ndarray"
    step: "np.ndarray"
    cur: "np.ndarray"
    ptr: "np.ndarray"
    assigned_node: "np.ndarray"
    assigned_kind: "np.ndarray"
    assign_pos: "np.ndarray"
    # slot-granular node state (mutable within a segment)
    cidle: "np.ndarray"  # [C, R]
    crel: "np.ndarray"
    cused: "np.ndarray"
    cntasks: "np.ndarray"  # [C]
    cnports: "np.ndarray"  # [C, P]
    # slot-granular statics (copied to the child on split)
    calloc: "np.ndarray"  # [C, R]
    cok: "np.ndarray"  # [C] bool (node_ok & node_valid)
    cmax_tasks: "np.ndarray"
    cidle_has_sc: "np.ndarray"
    crel_has_sc: "np.ndarray"
    cgid: "np.ndarray"
    cpod_sc: "np.ndarray"  # [GT, C] live InterPodAffinity columns
    # split machinery
    cmult: "np.ndarray"  # [C] members remaining (0 = dead slot)
    ctie: "np.ndarray"  # [C] lowest member node row (the tie-break key)
    cpos: "np.ndarray"  # [C] absolute cursor into members_sorted
    free_ptr: "np.ndarray"  # first free slot
    overflow: "np.ndarray"  # bool: slot bucket exhausted, host must re-bucket
    seg_it: "np.ndarray"  # iterations burned in this segment (re-pack cap)
    # job/queue state, verbatim SolveState layout
    ready_cnt: "np.ndarray"
    job_active: "np.ndarray"
    q_dropped: "np.ndarray"
    job_alloc: "np.ndarray"
    q_alloc: "np.ndarray"
    q_alloc_has_sc: "np.ndarray"
    paused_at: "np.ndarray"


def _fit_score_block(
    cidle, crel, cused, cntasks, cnports, calloc, cok, cmax_tasks,
    cidle_has_sc, crel_has_sc, cgid, cpod_col,
    req, res, tports, t_has_sc, eps, compat_t, aff_t,
    w_least, w_balanced, w_aff, w_podaff, fdtype,
):
    """The per-iteration fit+score block over (a block of) the slot
    axis — the exact ops of the uncompressed kernel's HOT LOOP #1/#2
    (``ops.kernels.solve_allocate_step``), shared by the flat XLA twin
    and the blocked mesh rung so the two cannot drift numerically."""
    fits_idle = _le_eps(req, cidle, eps) & ~(t_has_sc & ~cidle_has_sc)
    fits_rel = _le_eps(req, crel, eps) & ~(t_has_sc & ~crel_has_sc)
    static_ok = cok & compat_t[cgid]
    room = cntasks < cmax_tasks
    port_ok = ~jnp.any(tports[None, :] & cnports, axis=1)

    req_cpu = cused[:, 0] + res[0]
    req_mem = cused[:, 1] + res[1]
    cap_cpu = calloc[:, 0]
    cap_mem = calloc[:, 1]

    def least_dim(rq, cp):
        safe = jnp.where(cp == 0, 1.0, cp)
        sc = jnp.floor(ieee_div((cp - rq) * MAX_PRIORITY, safe)).astype(jnp.int32)
        return jnp.where((cp == 0) | (rq > cp), 0, sc)

    least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
    cpu_f = jnp.where(
        cap_cpu != 0, ieee_div(req_cpu, jnp.where(cap_cpu == 0, 1.0, cap_cpu)), 1.0
    )
    mem_f = jnp.where(
        cap_mem != 0, ieee_div(req_mem, jnp.where(cap_mem == 0, 1.0, cap_mem)), 1.0
    )
    balanced = jnp.where(
        (cpu_f >= 1.0) | (mem_f >= 1.0),
        0,
        (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(jnp.int32),
    )
    score = (
        least.astype(fdtype) * w_least
        + balanced.astype(fdtype) * w_balanced
        + aff_t[cgid] * w_aff
        + cpod_col * w_podaff
    )
    return fits_idle, fits_rel, static_ok & room & port_ok, score


@partial(
    jax.jit,
    static_argnames=("enable_drf", "enable_proportion", "blocks", "seg_budget"),
)
def _class_step(
    ca: dict,
    state: ClassSolveState,
    enable_drf: bool,
    enable_proportion: bool,
    blocks: int,
    seg_budget: int,
) -> ClassSolveState:
    """One kernel segment at class granularity: runs until every job is
    retired, a host-only task pauses it, the slot bucket overflows, or
    ``seg_budget`` iterations elapse. The budget bounds split-driven
    fragmentation: each bind to a fresh node splits a singleton, so a
    long segment degenerates toward node granularity — capping the
    segment forces a host re-pack that re-merges equivalent occupied
    nodes and keeps the slot axis small for the whole solve. The budget
    is ``cpad // 2 <= cpad - C`` free slots, so in-segment overflow
    cannot fire (the re-bucket path stays as a backstop). Mirrors
    ``solve_allocate_step`` body-for-body; the only structural
    additions are the multiplicity/tie-break selection and the
    split-on-assign scatter."""
    T = ca["task_req"].shape[0]
    J = ca["job_min"].shape[0]
    Q = ca["queue_rank"].shape[0]
    C = state.cmult.shape[0]
    N = ca["members_sorted"].shape[0]

    task_req = ca["task_req"]
    task_res = ca["task_res"]
    task_gid = ca["task_gid"]
    task_has_sc = ca["task_has_sc"]
    task_res_has_sc = ca["task_res_has_sc"]
    task_ports = ca["task_ports"]
    task_host_only = ca["task_host_only"]
    compat = ca["compat"]
    aff_sc = ca["aff_sc"]
    members_sorted = ca["members_sorted"]
    job_end = ca["job_end"]
    job_min = ca["job_min"]
    job_queue = ca["job_queue"]
    eps = ca["eps"]
    fdtype = task_req.dtype
    w_least = jnp.asarray(ca["w_least"], fdtype)
    w_balanced = jnp.asarray(ca["w_balanced"], fdtype)
    w_aff = jnp.asarray(ca["w_aff"], fdtype)
    w_podaff = jnp.asarray(ca["w_podaff"], fdtype)

    max_iter = jnp.int32(T + J + Q + 1) + jnp.sum(task_host_only).astype(jnp.int32)

    state = state._replace(
        paused_at=jnp.int32(-1),
        overflow=jnp.asarray(False),
        seg_it=jnp.int32(0),
    )

    def cond(s: ClassSolveState):
        return (
            ((s.cur >= 0) | jnp.any(s.job_active))
            & (s.it < max_iter)
            & (s.seg_it < seg_budget)
            & (s.paused_at < 0)
            & ~s.overflow
        )

    def body(s: ClassSolveState) -> ClassSolveState:
        need_sel = s.cur < 0
        qsel, q_any, overused, jsel, j_any = select_queue_job(
            ca, s, enable_drf, enable_proportion
        )
        drop_q = need_sel & q_any & overused
        sel_ok = q_any & ~overused & j_any
        cur = jnp.where(need_sel, jnp.where(sel_ok, jsel, -1), s.cur)

        job_active = jnp.where(
            drop_q, s.job_active & (job_queue != qsel), s.job_active
        )
        q_dropped = s.q_dropped.at[qsel].set(drop_q | s.q_dropped[qsel])

        cur_c = jnp.maximum(cur, 0)
        t = s.ptr[cur_c]
        t_any = (cur >= 0) & (t < job_end[cur_c])
        t = jnp.minimum(t, T - 1)
        drop = (cur >= 0) & ~t_any
        pause = t_any & task_host_only[t]
        proc = t_any & ~pause

        # -- fit + score over the slot axis (flat, or blocked for the
        # mesh-Pallas rung: identical elementwise ops per block) ------------
        req = task_req[t]
        res = task_res[t]
        tports = task_ports[t]
        t_has = task_has_sc[t]
        compat_t = compat[task_gid[t]]
        aff_t = aff_sc[task_gid[t]]
        cpod_col = s.cpod_sc[task_gid[t]]
        if blocks > 1:
            cb_n = C // blocks

            def blk(ci, cr, cu, cn, cp, al, ok, mx, ih, rh, gd, pc):
                return _fit_score_block(
                    ci, cr, cu, cn, cp, al, ok, mx, ih, rh, gd, pc,
                    req, res, tports, t_has, eps, compat_t, aff_t,
                    w_least, w_balanced, w_aff, w_podaff, fdtype,
                )

            fi, fr, so, sc = jax.vmap(blk)(
                s.cidle.reshape(blocks, cb_n, -1),
                s.crel.reshape(blocks, cb_n, -1),
                s.cused.reshape(blocks, cb_n, -1),
                s.cntasks.reshape(blocks, cb_n),
                s.cnports.reshape(blocks, cb_n, -1),
                s.calloc.reshape(blocks, cb_n, -1),
                s.cok.reshape(blocks, cb_n),
                s.cmax_tasks.reshape(blocks, cb_n),
                s.cidle_has_sc.reshape(blocks, cb_n),
                s.crel_has_sc.reshape(blocks, cb_n),
                s.cgid.reshape(blocks, cb_n),
                cpod_col.reshape(blocks, cb_n),
            )
            fits_idle = fi.reshape(C)
            fits_rel = fr.reshape(C)
            hard_ok = so.reshape(C)
            score = sc.reshape(C)
        else:
            fits_idle, fits_rel, hard_ok, score = _fit_score_block(
                s.cidle, s.crel, s.cused, s.cntasks, s.cnports,
                s.calloc, s.cok, s.cmax_tasks, s.cidle_has_sc,
                s.crel_has_sc, s.cgid, cpod_col,
                req, res, tports, t_has, eps, compat_t, aff_t,
                w_least, w_balanced, w_aff, w_podaff, fdtype,
            )
        cand = (s.cmult > 0) & hard_ok & (fits_idle | fits_rel)
        any_cand = jnp.any(cand)
        abandon = proc & ~any_cand

        # -- selection: max score, then lowest member node row — exactly the
        # uncompressed argmax's first-row tie-break, because every member of
        # a slot shares the score and ctie is the slot's lowest row ---------
        cb, _ = _lex_argmin(cand, -score, s.ctie)
        cb = cb.astype(jnp.int32)

        # -- split-on-assign ------------------------------------------------
        ns_raw = proc & any_cand & (s.cmult[cb] > 1)
        ovf = ns_raw & (s.free_ptr >= C)
        proc = proc & ~ovf
        assign = proc & any_cand
        ns = assign & (s.cmult[cb] > 1)

        do_alloc = assign & fits_idle[cb]
        do_pipe = assign & ~fits_idle[cb]
        nb_node = s.ctie[cb]  # the concrete node this assignment consumes

        f = jnp.minimum(s.free_ptr, C - 1)
        zero_row = jnp.zeros_like(res)
        new_idle = s.cidle[cb] + jnp.where(do_alloc, -res, zero_row)
        new_rel = s.crel[cb] + jnp.where(do_pipe, -res, zero_row)
        new_used = s.cused[cb] + jnp.where(assign, res, zero_row)
        new_ntasks = s.cntasks[cb] + jnp.where(assign, 1, 0)
        new_ports = s.cnports[cb] | (tports & assign)

        inplace = assign & ~ns  # mult==1: the slot IS the node

        def upd(arr, new_row):
            arr = arr.at[cb].set(jnp.where(inplace, new_row, arr[cb]))
            return arr.at[f].set(jnp.where(ns, new_row, arr[f]))

        cidle = upd(s.cidle, new_idle)
        crel = upd(s.crel, new_rel)
        cused = upd(s.cused, new_used)
        cntasks = upd(s.cntasks, new_ntasks)
        cnports = upd(s.cnports, new_ports)
        # child inherits the parent's statics
        calloc = s.calloc.at[f].set(jnp.where(ns, s.calloc[cb], s.calloc[f]))
        cok = s.cok.at[f].set(jnp.where(ns, s.cok[cb], s.cok[f]))
        cmax_tasks = s.cmax_tasks.at[f].set(
            jnp.where(ns, s.cmax_tasks[cb], s.cmax_tasks[f])
        )
        cidle_has_sc = s.cidle_has_sc.at[f].set(
            jnp.where(ns, s.cidle_has_sc[cb], s.cidle_has_sc[f])
        )
        crel_has_sc = s.crel_has_sc.at[f].set(
            jnp.where(ns, s.crel_has_sc[cb], s.crel_has_sc[f])
        )
        cgid = s.cgid.at[f].set(jnp.where(ns, s.cgid[cb], s.cgid[f]))
        cpod_sc = s.cpod_sc.at[:, f].set(
            jnp.where(ns, s.cpod_sc[:, cb], s.cpod_sc[:, f])
        )
        # the consumed member becomes the child's sole member; the parent
        # advances its cursor to the next-lowest remaining member
        cmult = s.cmult.at[cb].add(jnp.where(ns, -1, 0))
        cmult = cmult.at[f].set(jnp.where(ns, 1, cmult[f]))
        next_tie = members_sorted[jnp.minimum(s.cpos[cb] + 1, N - 1)]
        ctie = s.ctie.at[cb].set(jnp.where(ns, next_tie, s.ctie[cb]))
        ctie = ctie.at[f].set(jnp.where(ns, nb_node, ctie[f]))
        cpos = s.cpos.at[cb].add(jnp.where(ns, 1, 0))
        free_ptr = s.free_ptr + ns.astype(jnp.int32)

        # -- bookkeeping, verbatim from the uncompressed kernel -------------
        ready_cnt = s.ready_cnt.at[cur_c].add(jnp.where(do_alloc, 1, 0))
        ptr = s.ptr.at[cur_c].add(jnp.where(proc, 1, 0))
        assigned_node = s.assigned_node.at[t].set(
            jnp.where(assign, nb_node, s.assigned_node[t])
        )
        kind = jnp.where(
            do_alloc, KIND_ALLOCATED, jnp.where(do_pipe, KIND_PIPELINED, 0)
        )
        assigned_kind = s.assigned_kind.at[t].set(
            jnp.where(assign, kind, s.assigned_kind[t])
        )
        assign_pos = s.assign_pos.at[t].set(
            jnp.where(assign, s.step, s.assign_pos[t])
        )

        add_row = jnp.where(assign, task_res[t], zero_row)
        job_alloc = s.job_alloc.at[cur_c].add(add_row) if enable_drf else s.job_alloc
        if enable_proportion:
            qcur = job_queue[cur_c]
            q_alloc = s.q_alloc.at[qcur].add(add_row)
            q_alloc_has_sc = s.q_alloc_has_sc.at[qcur].set(
                s.q_alloc_has_sc[qcur] | (assign & task_res_has_sc[t])
            )
        else:
            q_alloc = s.q_alloc
            q_alloc_has_sc = s.q_alloc_has_sc

        job_active = job_active.at[cur_c].set(
            jnp.where(drop | abandon, False, job_active[cur_c])
        )
        ready_now = ready_cnt[cur_c] >= job_min[cur_c]
        cur_next = jnp.where(drop | abandon | (proc & ready_now), -1, cur)

        return ClassSolveState(
            it=s.it + jnp.where(ovf, 0, 1),
            step=s.step + assign.astype(jnp.int32),
            cur=jnp.where(ovf, s.cur, cur_next),
            ptr=ptr,
            assigned_node=assigned_node,
            assigned_kind=assigned_kind,
            assign_pos=assign_pos,
            cidle=cidle,
            crel=crel,
            cused=cused,
            cntasks=cntasks,
            cnports=cnports,
            calloc=calloc,
            cok=cok,
            cmax_tasks=cmax_tasks,
            cidle_has_sc=cidle_has_sc,
            crel_has_sc=crel_has_sc,
            cgid=cgid,
            cpod_sc=cpod_sc,
            cmult=cmult,
            ctie=ctie,
            cpos=cpos,
            free_ptr=free_ptr,
            overflow=ovf,
            seg_it=s.seg_it + jnp.where(ovf, 0, 1),
            ready_cnt=ready_cnt,
            job_active=jnp.where(ovf, s.job_active, job_active),
            q_dropped=jnp.where(ovf, s.q_dropped, q_dropped),
            job_alloc=job_alloc,
            q_alloc=q_alloc,
            q_alloc_has_sc=q_alloc_has_sc,
            paused_at=jnp.where(pause, t, jnp.int32(-1)),
        )

    return lax.while_loop(cond, body, state)


# -- the wrapping solver ------------------------------------------------------

_DYNAMIC_SLABS = ("idle", "rel", "used", "ntasks", "nports")

_CA_KEYS = (
    "task_req", "task_res", "task_gid", "task_has_sc", "task_res_has_sc",
    "task_ports", "task_host_only", "job_end", "job_min", "job_queue",
    "job_prio", "job_rank", "queue_rank", "q_deserved", "q_dims",
    "drf_total", "drf_dims", "compat", "aff_sc", "eps",
    "w_least", "w_balanced", "w_aff", "w_podaff",
)


class ClassCompressedSolver:
    """Drop-in ``solve_fn`` layer: takes and returns node-space
    ``SolveState`` (numpy leaves), compressing on entry and expanding on
    exit, so the action's pause loop, ``_host_step``, ``result_of`` and
    explain all run unchanged. Regrouping happens only at segment
    boundaries; within a segment the kernel splits incrementally."""

    def __init__(
        self, table: ClassTable, arrays: dict, enable_drf: bool,
        enable_proportion: bool, dtype, mesh=None, arena=None,
    ) -> None:
        self.table = table
        self.arrays = arrays
        self.enable_drf = bool(enable_drf)
        self.enable_proportion = bool(enable_proportion)
        self.dtype = dtype
        self.mesh = mesh
        self.arena = arena
        self.blocks = 1
        self.rung = "xla"
        if mesh is not None:
            mmode = os.environ.get("KBT_MESH_PALLAS", "auto").strip().lower() or "auto"
            if mmode not in ("0", "off"):
                # the blocked rung: the fit/score block runs per class
                # block (the jnp twin of the mesh-Pallas formulation)
                self.blocks = int(mesh.devices.size)
                self.rung = "mesh_pallas"
            else:
                self.rung = "sharded_xla"
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # replicated class table over the mesh: the slot axis is
            # tiny, so every device carries the full table and the
            # node-sharded structures stay host-side (per-shard member
            # lists drive the expansion below)
            self._sharding = NamedSharding(mesh, PartitionSpec())
        # per-solve stats (the bench/metrics surface)
        self.class_count = 0
        self.classes_valid = 0
        self.compression_ratio = 0.0
        self.splits = 0
        self.remerges = 0
        self.rekeys = 0
        self.segments = 0
        self.group_s = 0.0
        self.kernel_s = 0.0
        self.c_pad = 0
        self.shard_members: list[np.ndarray] | None = None
        self._slot_of: np.ndarray | None = None
        self._entry_C = 0

    # -- node-space <-> class-space ---------------------------------------

    def _init_node_state(self):
        """Numpy twin of ``kernels.init_state`` (the fresh-solve entry)."""
        a = self.arrays
        T = a["task_req"].shape[0]
        J = np.asarray(a["job_min"]).shape[0]
        Q = np.asarray(a["queue_rank"]).shape[0]
        R = a["task_req"].shape[1]
        fdtype = np.asarray(a["task_req"]).dtype
        return SolveState(
            it=np.int32(0),
            step=np.int32(0),
            cur=np.int32(-1),
            ptr=np.asarray(a["job_start"], np.int32).copy(),
            assigned_node=np.full(T, -1, np.int32),
            assigned_kind=np.zeros(T, np.int32),
            assign_pos=np.full(T, -1, np.int32),
            idle=np.asarray(a["node_idle"]).copy(),
            rel=np.asarray(a["node_rel"]).copy(),
            used=np.asarray(a["node_used"]).copy(),
            ntasks=np.asarray(a["node_ntasks"]).copy(),
            nports=np.asarray(a["node_ports"]).copy(),
            ready_cnt=np.asarray(a["job_ready0"], np.int32).copy(),
            job_active=np.asarray(a["job_valid"], bool).copy(),
            q_dropped=np.zeros(Q, bool),
            job_alloc=(
                np.asarray(a["job_alloc0"]).copy()
                if self.enable_drf
                else np.zeros((J, R), fdtype)
            ),
            q_alloc=(
                np.asarray(a["q_alloc0"]).copy()
                if self.enable_proportion
                else np.zeros((Q, R), fdtype)
            ),
            q_alloc_has_sc=(
                np.asarray(a["q_alloc_has_sc0"], bool).copy()
                if self.enable_proportion
                else np.zeros(Q, bool)
            ),
            paused_at=np.int32(-1),
        )

    def _pack(self, st) -> ClassSolveState:
        """Regroup the current node-space state into slots and build the
        kernel state. Runs at segment boundaries only."""
        a = self.arrays
        t0 = time.perf_counter()
        class0_of, rekeys = self.table.refresh_static(a)
        self.rekeys += rekeys
        idle = np.asarray(st.idle)
        rel = np.asarray(st.rel)
        used = np.asarray(st.used)
        ntasks = np.asarray(st.ntasks)
        nports = np.asarray(st.nports)
        pod_sc = np.asarray(a["pod_sc"])
        N = idle.shape[0]
        first, inv = dedup_rows(
            [
                class0_of.astype(np.int32),
                idle, rel, used,
                ntasks.astype(np.int32),
                nports,
                np.ascontiguousarray(pod_sc.T),
            ]
        )
        C = int(len(first))
        slot_of = inv.astype(np.int32)
        counts = np.bincount(slot_of, minlength=C)
        self.remerges += self.table.note_regroup(slot_of, counts)
        order = np.argsort(slot_of, kind="stable").astype(np.int32)
        off = np.zeros(C, counts.dtype)
        np.cumsum(counts[:-1], out=off[1:])
        rep = order[off]  # lowest member row per slot (stable sort)

        cpad = min(
            _pow2(max(2 * C, C + 64, self.table.sticky_cpad)), _pow2(N)
        )
        # N-scaled floor: the segment budget is cpad // 2, so a large
        # fleet gets long-enough segments that the host re-pack between
        # them stays a rounding error. Capped at 1024: past that the
        # slot-axis cost per iteration outweighs the amortized re-pack
        # (measured on the 1-core CPU host — the re-pack is ~9 ms at
        # 40k nodes, the kernel pays ~0.03 us per slot row per step)
        cpad = max(cpad, _pow2(C), min(_pow2(N) // 16, 1024))
        self.table.sticky_cpad = max(self.table.sticky_cpad, cpad)
        cpad = self.table.sticky_cpad
        if self.segments == 0:
            self.class_count = C
            valid = np.asarray(a["node_valid"], bool)
            self.classes_valid = int(valid[rep].sum())
            n_valid = int(valid.sum())
            self.compression_ratio = (
                float(n_valid) / float(self.classes_valid)
                if self.classes_valid
                else 1.0
            )
        self.c_pad = int(cpad)
        self._slot_of = slot_of
        self._entry_C = C
        if self.mesh is not None:
            # per-shard membership: contiguous node-axis chunks, the same
            # layout the GSPMD rung shards its node arrays by
            shards = int(self.mesh.devices.size)
            bounds = np.linspace(0, N, shards + 1).astype(np.int64)
            self.shard_members = [
                np.arange(bounds[i], bounds[i + 1], dtype=np.int64)
                for i in range(shards)
            ]

        def pad1(x, fill=0):
            out = np.full((cpad,) + x.shape[1:], fill, x.dtype)
            out[:C] = x
            return out

        fdtype = idle.dtype
        node_ok = np.asarray(a["node_ok"], bool) & np.asarray(a["node_valid"], bool)
        imax = np.iinfo(np.int32).max
        cs = ClassSolveState(
            it=np.int32(st.it),
            step=np.int32(st.step),
            cur=np.int32(st.cur),
            ptr=np.asarray(st.ptr, np.int32),
            assigned_node=np.asarray(st.assigned_node, np.int32),
            assigned_kind=np.asarray(st.assigned_kind, np.int32),
            assign_pos=np.asarray(st.assign_pos, np.int32),
            cidle=pad1(idle[rep]),
            crel=pad1(rel[rep]),
            cused=pad1(used[rep]),
            cntasks=pad1(ntasks[rep]),
            cnports=pad1(nports[rep]),
            calloc=pad1(np.asarray(a["node_alloc"])[rep]),
            cok=pad1(node_ok[rep]),
            cmax_tasks=pad1(np.asarray(a["node_max_tasks"])[rep]),
            cidle_has_sc=pad1(np.asarray(a["node_idle_has_sc"], bool)[rep]),
            crel_has_sc=pad1(np.asarray(a["node_rel_has_sc"], bool)[rep]),
            cgid=pad1(np.asarray(a["node_gid"], np.int32)[rep]),
            cpod_sc=np.ascontiguousarray(
                np.pad(pod_sc[:, rep], ((0, 0), (0, cpad - C))).astype(fdtype)
            ),
            cmult=pad1(counts.astype(np.int32)),
            ctie=pad1(rep.astype(np.int32), fill=imax),
            cpos=pad1(off.astype(np.int32)),
            free_ptr=np.int32(C),
            overflow=np.asarray(False),
            seg_it=np.int32(0),
            ready_cnt=np.asarray(st.ready_cnt, np.int32),
            job_active=np.asarray(st.job_active, bool),
            q_dropped=np.asarray(st.q_dropped, bool),
            job_alloc=np.asarray(st.job_alloc),
            q_alloc=np.asarray(st.q_alloc),
            q_alloc_has_sc=np.asarray(st.q_alloc_has_sc, bool),
            paused_at=np.int32(st.paused_at),
        )
        self._members_sorted = order
        self.group_s += time.perf_counter() - t0
        return cs

    def _ca(self) -> dict:
        a = self.arrays
        ca = {k: a[k] for k in _CA_KEYS}
        members = self._members_sorted
        if self.arena is not None:
            try:
                members = self.arena.upload("class_members", members, mesh=self.mesh)
            except Exception:  # noqa: BLE001 - arena loss must not fail the solve
                log.exception("class member slab upload failed; passing host array")
        ca["members_sorted"] = members
        return ca

    def _rebucket(self, cs: ClassSolveState) -> ClassSolveState:
        """Slot bucket exhausted mid-solve: grow to the next power of two
        (bounded by the node bucket — slots can never outnumber nodes)
        and resume. A recompile at the new shape is expected and cold;
        the sticky bucket keeps later cycles at the grown size."""
        old = int(cs.cmult.shape[0])
        N = int(self._slot_of.shape[0])
        new = min(_pow2(old * 2), _pow2(N))
        if new <= old:
            raise RuntimeError(
                f"class slot bucket cannot grow past {old} (nodes={N})"
            )
        self.table.sticky_cpad = max(self.table.sticky_cpad, new)
        log.warning(
            "class slot bucket overflow: re-bucketing %d -> %d slots", old, new
        )

        def grow(x, fill=0):
            x = np.asarray(x)
            out = np.full((new,) + x.shape[1:], fill, x.dtype)
            out[:old] = x
            return out

        imax = np.iinfo(np.int32).max
        return cs._replace(
            cidle=grow(cs.cidle),
            crel=grow(cs.crel),
            cused=grow(cs.cused),
            cntasks=grow(cs.cntasks),
            cnports=grow(cs.cnports),
            calloc=grow(cs.calloc),
            cok=grow(cs.cok),
            cmax_tasks=grow(cs.cmax_tasks),
            cidle_has_sc=grow(cs.cidle_has_sc),
            crel_has_sc=grow(cs.crel_has_sc),
            cgid=grow(cs.cgid),
            cpod_sc=np.ascontiguousarray(
                np.pad(np.asarray(cs.cpod_sc), ((0, 0), (0, new - old)))
            ),
            cmult=grow(cs.cmult),
            ctie=grow(cs.ctie, fill=imax),
            cpos=grow(cs.cpos),
            overflow=np.asarray(False),
        )

    def _expand(self, cs: ClassSolveState):
        """Class state back to a node-space ``SolveState`` view: every
        node reads its slot's row (children first override their split
        origin). With a mesh the gather runs per member shard — the
        node-space view is assembled shard by shard, the class table
        itself staying replicated."""
        slot_of = self._slot_of.copy()
        fp = int(cs.free_ptr)
        if fp > self._entry_C:
            child = np.arange(self._entry_C, fp)
            slot_of[np.asarray(cs.ctie)[child]] = child
        self.splits += fp - self._entry_C
        self.table.splits_total += fp - self._entry_C
        self.table.note_end(slot_of)

        def gather(arr):
            arr = np.asarray(arr)
            if self.shard_members is None:
                return arr[slot_of].copy()
            return np.concatenate(
                [arr[slot_of[m]] for m in self.shard_members], axis=0
            )

        return SolveState(
            it=np.int32(cs.it),
            step=np.int32(cs.step),
            cur=np.int32(cs.cur),
            ptr=np.asarray(cs.ptr, np.int32).copy(),
            assigned_node=np.asarray(cs.assigned_node, np.int32).copy(),
            assigned_kind=np.asarray(cs.assigned_kind, np.int32).copy(),
            assign_pos=np.asarray(cs.assign_pos, np.int32).copy(),
            idle=gather(cs.cidle),
            rel=gather(cs.crel),
            used=gather(cs.cused),
            ntasks=gather(cs.cntasks),
            nports=gather(cs.cnports),
            ready_cnt=np.asarray(cs.ready_cnt, np.int32).copy(),
            job_active=np.asarray(cs.job_active, bool).copy(),
            q_dropped=np.asarray(cs.q_dropped, bool).copy(),
            job_alloc=np.asarray(cs.job_alloc).copy(),
            q_alloc=np.asarray(cs.q_alloc).copy(),
            q_alloc_has_sc=np.asarray(cs.q_alloc_has_sc, bool).copy(),
            paused_at=np.int32(cs.paused_at),
        )

    # -- the solve_fn surface ----------------------------------------------

    def solve(self, st):
        if st is None:
            st = self._init_node_state()
        a = self.arrays
        max_iter = (
            int(a["task_req"].shape[0])
            + int(a["job_min"].shape[0])
            + int(a["queue_rank"].shape[0])
            + 1
            + int(np.asarray(a["task_host_only"]).sum())
        )
        while True:
            cs = self._pack(st)
            ca = self._ca()
            seg_budget = max(int(cs.cmult.shape[0]) // 2, 1)
            if self._sharding is not None:
                # replicated class table + replicated (task/job) inputs:
                # the slot axis is small, so every device carries the
                # full table
                ca = jax.device_put(ca, self._sharding)
                cs = jax.device_put(cs, self._sharding)
            self.segments += 1
            t0 = time.perf_counter()
            while True:
                out = _class_step(
                    ca, cs, self.enable_drf, self.enable_proportion,
                    self.blocks, seg_budget,
                )
                out = jax.tree_util.tree_map(np.asarray, out)
                if bool(out.overflow):
                    self.kernel_s += time.perf_counter() - t0
                    cs = self._rebucket(out)
                    seg_budget = max(int(cs.cmult.shape[0]) // 2, 1)
                    if self._sharding is not None:
                        cs = jax.device_put(cs, self._sharding)
                    t0 = time.perf_counter()
                    continue
                break
            self.kernel_s += time.perf_counter() - t0
            st = self._expand(out)
            if (
                int(out.paused_at) >= 0
                or int(out.it) >= max_iter
                or (int(out.cur) < 0 and not bool(np.any(out.job_active)))
            ):
                return st
            # segment budget exhausted mid-solve: loop back through
            # ``_pack`` so equivalent occupied nodes re-merge — the
            # split machinery fragments within a segment, the re-pack
            # collapses the fragments, and the slot axis stays small
            # for the whole solve instead of degenerating toward node
            # granularity

    def stats(self) -> dict:
        return {
            "class_count": int(self.class_count),
            "classes_valid": int(self.classes_valid),
            "compression_ratio": round(float(self.compression_ratio), 4),
            "splits": int(self.splits),
            "remerges": int(self.remerges),
            "rekeys": int(self.rekeys),
            "segments": int(self.segments),
            "c_pad": int(self.c_pad),
            "group_s": round(self.group_s, 6),
            "kernel_s": round(self.kernel_s, 6),
            "rung": self.rung,
        }


def wrap_solver(
    action, inner, arrays: dict, enable_drf: bool, enable_proportion: bool,
    dtype, mesh=None,
):
    """Wrap a tier's ``solve_fn`` with the class-compressed layer. Any
    failure — including the ``solve.class_table`` fault point standing
    in for a poisoned/stale table — degrades the call to the wrapped
    uncompressed tier loudly: the cycle completes, parity holds (the
    solver is functional on its input state), and the degrade is
    metered."""
    from kube_batch_tpu import faults, metrics

    table = getattr(action, "_class_table", None)
    if table is None:
        table = ClassTable()
        action._class_table = table
    solver = ClassCompressedSolver(
        table, arrays, enable_drf, enable_proportion, dtype, mesh=mesh,
        arena=getattr(action, "_arena", None),
    )

    def solve_fn(st):
        try:
            if faults.should_fire("solve.class_table"):
                raise faults.FaultInjected("solve.class_table")
            out = solver.solve(st)
        except Exception:
            log.exception(
                "class-compressed solve failed; degrading to the "
                "uncompressed %s tier for this segment",
                "mesh" if mesh is not None else "single-chip",
            )
            metrics.register_degraded_cycle("class_solve", "class_table")
            action.last_class_stats = None
            return inner(st)
        action.last_solver_tier = "class_" + solver.rung
        stats = solver.stats()
        action.last_class_stats = stats
        metrics.set_class_solve_classes(stats["class_count"])
        metrics.set_class_solve_compression_ratio(stats["compression_ratio"])
        delta = (stats["splits"] + stats["rekeys"]) - getattr(
            solver, "_metered_splits", 0
        )
        if delta > 0:
            metrics.register_class_table_splits(delta)
        solver._metered_splits = stats["splits"] + stats["rekeys"]
        return out

    return solve_fn


# -- seeded self-check --------------------------------------------------------

_SMOKE_TIERS = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""


def _smoke_world(bound=None, arrivals=0, seed=7):
    """Heterogeneous node pools with heavy intra-pool duplication: four
    pool shapes x 18 identical nodes, a few pre-occupied residents (so
    classes are plural from the start), selector-confined and free gang
    jobs. ``bound`` (pod name -> node name) materializes a previous
    cycle's placements as running residents; ``arrivals`` appends fresh
    gangs so the next cycle has work — together they exercise
    split-then-re-merge across cycles."""
    import random

    from kube_batch_tpu.apis.types import PodPhase
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    rng = random.Random(seed)
    pools = {
        "small": build_resource_list(cpu=8, memory="16Gi", pods=32),
        "medium": build_resource_list(cpu=16, memory="32Gi", pods=64),
        "large": build_resource_list(cpu=32, memory="65536Mi", pods=110),
        "tainted": build_resource_list(cpu=16, memory="32Gi", pods=64),
    }
    nodes = []
    for pool, alloc in pools.items():
        for i in range(18):
            nodes.append(
                build_node(
                    f"{pool}-{i:03d}", dict(alloc), labels={"pool": pool}
                )
            )
    bound = dict(bound or {})
    pods, pgs = [], []
    for j in range(12):
        name = f"gang-{j:03d}"
        members = rng.choice([3, 4, 6])
        pgs.append(build_pod_group(name, min_member=members))
        pool = rng.choice([None, "small", "medium", "large"])
        cpu = rng.choice(["500m", "1", "2"])
        for m in range(members):
            pod = build_pod(
                name=f"{name}-t{m}",
                group_name=name,
                req=build_resource_list(cpu=cpu, memory="1Gi"),
                node_selector={"pool": pool} if pool else None,
            )
            host = bound.pop(f"default/{name}-t{m}", None)
            if host is not None:
                pod.node_name = host
                pod.phase = PodPhase.RUNNING
            pods.append(pod)
    for j in range(arrivals):
        name = f"arrival-{j:03d}"
        pgs.append(build_pod_group(name, min_member=2))
        for m in range(2):
            pods.append(
                build_pod(
                    name=f"{name}-t{m}",
                    group_name=name,
                    req=build_resource_list(cpu="1", memory="2Gi"),
                )
            )
    # residents diversify the initial classes inside one pool
    for i in range(4):
        pods.append(
            build_pod(
                name=f"resident-{i}",
                node_name=f"medium-{i:03d}",
                phase=PodPhase.RUNNING,
                req=build_resource_list(cpu=2, memory="4Gi"),
            )
        )
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


def _smoke_run(action, cluster):
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, open_session
    from kube_batch_tpu.testing import FakeCache

    tiers = parse_scheduler_conf(_SMOKE_TIERS).tiers
    cache = FakeCache(cluster)
    ssn = open_session(cache, tiers)
    try:
        action.execute(ssn)
    finally:
        close_session(ssn)
    return dict(cache.binder.binds)


def smoke() -> dict:
    """Seeded self-check (verify gate ``class_solve_smoke`` + image
    build): heterogeneous-pool world solved serial / uncompressed /
    compressed with bind parity, across two cycles so in-solve splits
    and re-merges (at the segment re-packs and across cycles) both
    demonstrably fire."""
    from kube_batch_tpu.actions.allocate import AllocateAction
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    saved = {}
    for env, value in (("KBT_MIN_DEVICE_PAIRS", "0"), (ENV, "0")):
        saved[env] = os.environ.get(env)
        os.environ[env] = value
    try:
        serial_binds = _smoke_run(AllocateAction(), _smoke_world())
        plain = XlaAllocateAction()
        plain_binds = _smoke_run(plain, _smoke_world())
        os.environ[ENV] = "1"
        comp = XlaAllocateAction()
        comp_binds = _smoke_run(comp, _smoke_world())
        stats1 = dict(comp.last_class_stats or {})
        tier1 = comp.last_solver_tier

        # cycle 2: cycle-1 placements become running residents, fresh
        # gangs arrive; identical nodes that split in cycle 1 re-merge
        world2 = lambda: _smoke_world(bound=comp_binds, arrivals=6)  # noqa: E731
        comp_binds2 = _smoke_run(comp, world2())
        stats2 = dict(comp.last_class_stats or {})
        os.environ[ENV] = "0"
        plain_binds2 = _smoke_run(plain, world2())
    finally:
        for env, value in saved.items():
            if value is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = value

    n_nodes = 4 * 18
    parity1 = serial_binds == plain_binds == comp_binds
    parity2 = plain_binds2 == comp_binds2
    # re-merges fire at the segment re-packs inside a solve (bound-alike
    # nodes collapse back together) and/or across cycles — either is the
    # mechanism working
    remerges = stats1.get("remerges", 0) + stats2.get("remerges", 0)
    ok = bool(
        parity1
        and parity2
        and tier1.startswith("class_")
        and stats1.get("class_count", n_nodes) < n_nodes
        and stats1.get("splits", 0) > 0
        and remerges > 0
    )
    return {
        "ok": ok,
        "binds": len(comp_binds),
        "binds_cycle2": len(comp_binds2),
        "parity_cycle1": parity1,
        "parity_cycle2": parity2,
        "tier": tier1,
        "class_count": stats1.get("class_count"),
        "compression_ratio": stats1.get("compression_ratio"),
        "splits": stats1.get("splits"),
        "remerges": remerges,
        "remerges_cycle2": stats2.get("remerges"),
        "cycle2": stats2,
    }


def main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="class-compressed solve smoke: heterogeneous pools, "
        "serial/uncompressed/compressed bind parity, split + re-merge"
    )
    parser.add_argument("--json", action="store_true", help="print the result as JSON")
    args = parser.parse_args(argv)
    result = smoke()
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"class_solve smoke: {status} ({result['binds']} binds, "
            f"classes={result['class_count']}, "
            f"ratio={result['compression_ratio']}, "
            f"splits={result['splits']}, "
            f"remerges={result['remerges']})"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose jitted kernel and table singletons would
    # otherwise be different objects than the ones the action imports
    from kube_batch_tpu.ops.class_solve import main as _canonical_main

    raise SystemExit(_canonical_main())
