"""Jitted gang-aware allocation solve (the vectorized allocate action).

One `lax.while_loop` iteration = one step of the serial allocate loop
(reference actions/allocate/allocate.go:94-190): select the active queue
(static creation/uid rank, session_plugins.go:280-305), select the next
job from it (priority desc -> gang non-ready-first -> creation/uid,
priority.go:61-77 + gang.go:96-118 + session fallback), pop its next
pending task (priority desc -> creation/uid), and assign it to the best
feasible node — except that the per-task predicate scan (HOT LOOP #1,
scheduler_helper.go:34-57) and the scoring scan (HOT LOOP #2,
scheduler_helper.go:60-109) are single vectorized ops over the whole node
axis instead of a 16-goroutine fan-out:

- feasibility: epsilon-tolerant resource fit against idle OR releasing
  (allocate.go:78-92 + resource_info.go:255-278, including the Go
  nil-scalar-map parity flags), precomputed label-compat gather
  (selector/taints/cordon), pod-count room, dynamic host-port bitmask;
- score: LeastRequested + BalancedResourceAllocation integer formulas
  plus the precomputed preferred-node-affinity term (nodeorder.go:109-222),
  argmax with first-node tie-break (= deterministic SelectBestNode);
- assignment: fits-idle -> allocate (consume idle, ready_count++), else
  -> pipeline onto releasing (node_info.go:108-136 accounting), with the
  gang barrier — a job reaching min_available is re-queued so other jobs
  get their turn, exactly like the serial heap re-push (allocate.go:182-185).

Each iteration retires one task or one job, so the loop runs at most
T + J + 1 iterations; every iteration is O(T + J + N*R) of pure vector
work (VPU-friendly compares/selects; the N*R fit/score block is the MXU/
VPU payload). All shapes are static (encode.py pads to buckets).

The kernel is policy-exact for conf `priority, gang, predicates,
nodeorder` (minus pairwise pod-affinity, which stays host-side — see
encode.host_only). drf / proportion session-event bookkeeping folds into
the loop state in a later revision (SURVEY.md section 7 hard part (d)).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

MAX_PRIORITY = 10  # schedulerapi.MaxPriority (nodeorder.py)

KIND_NONE = 0
KIND_ALLOCATED = 1
KIND_PIPELINED = 2


class SolveResult(NamedTuple):
    assigned_node: jax.Array  # [T] int32, node row or -1
    assigned_kind: jax.Array  # [T] int32, KIND_*
    assign_pos: jax.Array  # [T] int32, order of the assignment event, or -1
    ready_cnt: jax.Array  # [J] int32, final ready_task_num per job
    n_assigned: jax.Array  # int32


def _lex_argmin(mask, *keys):
    """Index of the mask=True element minimizing keys lexicographically;
    first index wins ties (ties cannot survive a unique final key).
    Returns (index, any) — index is garbage when any is False."""
    m = mask
    for k in keys:
        kmin = jnp.min(jnp.where(m, k, jnp.iinfo(k.dtype).max))
        m = m & (k == kmin)
    return jnp.argmax(m), jnp.any(mask)


def _le_eps(req, pool, eps):
    """Vectorized Resource.less_equal over the node axis
    (resource_info.go:255-278): per-dimension l < r + eps."""
    return jnp.all(req[None, :] < pool + eps[None, :], axis=1)


def solve_allocate_step(a: dict) -> SolveResult:
    """The full allocate solve; call through `solve_allocate` (jitted)."""
    T = a["task_req"].shape[0]
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]

    task_req = a["task_req"]
    task_res = a["task_res"]
    task_job = a["task_job"]
    task_rank = a["task_rank"]
    task_gid = a["task_gid"]
    task_has_sc = a["task_has_sc"]
    task_ports = a["task_ports"]
    node_alloc = a["node_alloc"]
    node_ok = a["node_ok"] & a["node_valid"]
    node_max_tasks = a["node_max_tasks"]
    node_idle_has_sc = a["node_idle_has_sc"]
    node_rel_has_sc = a["node_rel_has_sc"]
    node_gid = a["node_gid"]
    compat = a["compat"]
    aff_sc = a["aff_sc"]
    job_min = a["job_min"]
    job_prio = a["job_prio"]
    job_rank = a["job_rank"]
    job_queue = a["job_queue"]
    queue_rank = a["queue_rank"]
    eps = a["eps"]
    fdtype = task_req.dtype
    w_least = jnp.asarray(a["w_least"], fdtype)
    w_balanced = jnp.asarray(a["w_balanced"], fdtype)
    w_aff = jnp.asarray(a["w_aff"], fdtype)

    max_iter = jnp.int32(T + J + 1)

    state = dict(
        it=jnp.int32(0),
        step=jnp.int32(0),
        cur=jnp.int32(-1),
        remaining=a["task_valid"],
        assigned_node=jnp.full(T, -1, jnp.int32),
        assigned_kind=jnp.zeros(T, jnp.int32),
        assign_pos=jnp.full(T, -1, jnp.int32),
        idle=a["node_idle"],
        rel=a["node_rel"],
        used=a["node_used"],
        ntasks=a["node_ntasks"],
        nports=a["node_ports"],
        ready_cnt=a["job_ready0"],
        job_active=a["job_valid"],
    )

    def cond(s):
        return ((s["cur"] >= 0) | jnp.any(s["job_active"])) & (s["it"] < max_iter)

    def body(s):
        # -- queue + job selection (only bites when no current job) ---------
        q_has = (
            jnp.zeros(Q, jnp.int32)
            .at[job_queue]
            .max(s["job_active"].astype(jnp.int32))
        )
        qsel, _ = _lex_argmin(q_has > 0, queue_rank)
        ready_bit = (s["ready_cnt"] >= job_min).astype(jnp.int32)
        jmask = s["job_active"] & (job_queue == qsel)
        jsel, j_any = _lex_argmin(jmask, -job_prio, ready_bit, job_rank)
        cur = jnp.where(
            s["cur"] < 0, jnp.where(j_any, jsel.astype(jnp.int32), -1), s["cur"]
        )
        cur_c = jnp.maximum(cur, 0)

        # -- pop the job's next pending task --------------------------------
        tmask = s["remaining"] & (task_job == cur) & (cur >= 0)
        t, t_any = _lex_argmin(tmask, task_rank)
        drop = (cur >= 0) & ~t_any  # tasks exhausted -> job leaves the heap
        proc = (cur >= 0) & t_any

        # -- feasibility over the node axis (HOT LOOP #1, vectorized) -------
        req = task_req[t]
        fits_idle = _le_eps(req, s["idle"], eps) & ~(
            task_has_sc[t] & ~node_idle_has_sc
        )
        fits_rel = _le_eps(req, s["rel"], eps) & ~(
            task_has_sc[t] & ~node_rel_has_sc
        )
        static_ok = node_ok & compat[task_gid[t], node_gid]
        room = s["ntasks"] < node_max_tasks
        port_ok = ~jnp.any(task_ports[t][None, :] & s["nports"], axis=1)
        cand = static_ok & room & port_ok & (fits_idle | fits_rel)
        any_cand = jnp.any(cand)
        abandon = proc & ~any_cand  # serial `break` without re-push

        # -- score (HOT LOOP #2, vectorized) + deterministic best node ------
        res = task_res[t]
        req_cpu = s["used"][:, 0] + res[0]
        req_mem = s["used"][:, 1] + res[1]
        cap_cpu = node_alloc[:, 0]
        cap_mem = node_alloc[:, 1]

        def least_dim(rq, cp):
            safe = jnp.where(cp == 0, 1.0, cp)
            sc = jnp.floor((cp - rq) * MAX_PRIORITY / safe).astype(jnp.int32)
            return jnp.where((cp == 0) | (rq > cp), 0, sc)

        least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
        cpu_f = jnp.where(cap_cpu != 0, req_cpu / jnp.where(cap_cpu == 0, 1.0, cap_cpu), 1.0)
        mem_f = jnp.where(cap_mem != 0, req_mem / jnp.where(cap_mem == 0, 1.0, cap_mem), 1.0)
        balanced = jnp.where(
            (cpu_f >= 1.0) | (mem_f >= 1.0),
            0,
            (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(jnp.int32),
        )
        score = (
            least.astype(fdtype) * w_least
            + balanced.astype(fdtype) * w_balanced
            + aff_sc[task_gid[t], node_gid] * w_aff
        )
        nb = jnp.argmax(jnp.where(cand, score, -jnp.inf)).astype(jnp.int32)

        assign = proc & any_cand
        do_alloc = assign & fits_idle[nb]
        do_pipe = assign & ~fits_idle[nb]  # predicate guarantees fits_rel

        # -- apply the assignment (node_info.go:108-136 accounting) ---------
        zero_row = jnp.zeros_like(res)
        idle = s["idle"].at[nb].add(jnp.where(do_alloc, -res, zero_row))
        rel = s["rel"].at[nb].add(jnp.where(do_pipe, -res, zero_row))
        used = s["used"].at[nb].add(jnp.where(assign, res, zero_row))
        ntasks = s["ntasks"].at[nb].add(jnp.where(assign, 1, 0))
        nports = s["nports"].at[nb].set(s["nports"][nb] | (task_ports[t] & assign))
        ready_cnt = s["ready_cnt"].at[cur_c].add(jnp.where(do_alloc, 1, 0))
        remaining = s["remaining"].at[t].set(jnp.where(proc, False, s["remaining"][t]))
        assigned_node = s["assigned_node"].at[t].set(
            jnp.where(assign, nb, s["assigned_node"][t])
        )
        kind = jnp.where(do_alloc, KIND_ALLOCATED, jnp.where(do_pipe, KIND_PIPELINED, 0))
        assigned_kind = s["assigned_kind"].at[t].set(
            jnp.where(assign, kind, s["assigned_kind"][t])
        )
        assign_pos = s["assign_pos"].at[t].set(
            jnp.where(assign, s["step"], s["assign_pos"][t])
        )

        # -- gang barrier / job lifecycle (allocate.go:117-119,182-185) -----
        job_active = s["job_active"].at[cur_c].set(
            jnp.where(drop | abandon, False, s["job_active"][cur_c])
        )
        ready_now = ready_cnt[cur_c] >= job_min[cur_c]
        cur_next = jnp.where(drop | abandon | (proc & ready_now), -1, cur)

        return dict(
            it=s["it"] + 1,
            step=s["step"] + assign.astype(jnp.int32),
            cur=cur_next,
            remaining=remaining,
            assigned_node=assigned_node,
            assigned_kind=assigned_kind,
            assign_pos=assign_pos,
            idle=idle,
            rel=rel,
            used=used,
            ntasks=ntasks,
            nports=nports,
            ready_cnt=ready_cnt,
            job_active=job_active,
        )

    final = lax.while_loop(cond, body, state)
    return SolveResult(
        assigned_node=final["assigned_node"],
        assigned_kind=final["assigned_kind"],
        assign_pos=final["assign_pos"],
        ready_cnt=final["ready_cnt"],
        n_assigned=final["step"],
    )


solve_allocate = jax.jit(solve_allocate_step)
