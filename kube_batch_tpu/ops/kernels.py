"""Jitted gang-aware allocation solve (the vectorized allocate action).

One `lax.while_loop` iteration = one step of the serial allocate loop
(reference actions/allocate/allocate.go:94-190): select the active queue
(proportion share asc, then static creation/uid rank —
session_plugins.go:280-305 + proportion.go:146-159), drop it for the
cycle if overused (proportion.go:188-199; shares only grow during
allocate, so one failed check is final — exactly like the serial heap
draining the queue's remaining entries), select the next job from it
(priority desc -> gang non-ready-first -> drf share asc -> creation/uid;
priority.go:61-77 + gang.go:96-118 + drf.go:114-132 + session fallback),
take its next pending task, and assign it to the best feasible node —
except that the per-task predicate scan (HOT LOOP #1,
scheduler_helper.go:34-57) and the scoring scan (HOT LOOP #2,
scheduler_helper.go:60-109) are single vectorized ops over the whole node
axis instead of a 16-goroutine fan-out:

- feasibility: epsilon-tolerant resource fit against idle OR releasing
  (allocate.go:78-92 + resource_info.go:255-278, including the Go
  nil-scalar-map parity flags), precomputed label-compat gather
  (selector/taints/cordon), pod-count room, dynamic host-port bitmask;
- score: LeastRequested + BalancedResourceAllocation integer formulas
  plus the precomputed preferred-node-affinity term (nodeorder.go:109-222),
  argmax with first-node tie-break (= deterministic SelectBestNode);
- assignment: fits-idle -> allocate (consume idle, ready_count++), else
  -> pipeline onto releasing (node_info.go:108-136 accounting), with the
  gang barrier — a job reaching min_available is re-queued so other jobs
  get their turn, exactly like the serial heap re-push (allocate.go:182-185).

Round-3 redesign (VERDICT r2 item 1): tasks are laid out contiguously per
job by the encoder and each job keeps a next-task *pointer*, so the loop
body does **no O(T) work** — a task pop is one dynamic-slice instead of a
65k-element masked argmin. Each iteration is O(J + Q + N*R) of pure
vector work dominated by the [N,R] fit/score block (the VPU payload);
iterations are bounded by T + J + Q + 1 (one per task pop, one per job
drop, one per overused/emptied queue drop).

drf and proportion fold into the loop state (SURVEY.md section 7 hard
part (d)): per-job allocated vectors -> dominant share (drf.go:161-171),
per-queue allocated vs the statically water-filled deserved ->
queue share + the overused gate (proportion.go:101-223), updated after
every assignment exactly like the plugins' session event handlers.
They are static jit flags, so the no-drf/no-proportion program carries
no extra work.

The kernel is policy-exact for the reference's *default* conf
(util.go:31-42: priority,gang,conformance / drf,predicates,proportion,
nodeorder) minus pairwise pod-affinity, which stays host-side — see
encode.host_only and the segmented hybrid in actions/xla_allocate.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

MAX_PRIORITY = 10  # schedulerapi.MaxPriority (nodeorder.py)

KIND_NONE = 0
KIND_ALLOCATED = 1
KIND_PIPELINED = 2


class SolveResult(NamedTuple):
    assigned_node: jax.Array  # [T] int32, node row or -1
    assigned_kind: jax.Array  # [T] int32, KIND_*
    assign_pos: jax.Array  # [T] int32, order of the assignment event, or -1
    ready_cnt: jax.Array  # [J] int32, final ready_task_num per job
    n_assigned: jax.Array  # int32


class SolveState(NamedTuple):
    """Resumable mid-solve state for the segmented pod-affinity hybrid
    (actions/xla_allocate): the host serial-steps one host-only task,
    patches the node/job vectors, and re-enters the kernel."""

    it: jax.Array
    step: jax.Array
    cur: jax.Array
    ptr: jax.Array  # [J] next-task row per job
    assigned_node: jax.Array
    assigned_kind: jax.Array
    assign_pos: jax.Array
    idle: jax.Array
    rel: jax.Array
    used: jax.Array
    ntasks: jax.Array
    nports: jax.Array
    ready_cnt: jax.Array
    job_active: jax.Array
    q_dropped: jax.Array
    job_alloc: jax.Array  # [J,R] drf allocated (zeros when drf off)
    q_alloc: jax.Array  # [Q,R] proportion allocated (zeros when off)
    q_alloc_has_sc: jax.Array  # [Q] Go nil-scalar-map parity bit
    paused_at: jax.Array  # task row the solve paused on (host-only), or -1


def _lex_argmin(mask, *keys):
    """Index of the mask=True element minimizing keys lexicographically;
    first index wins ties (ties cannot survive a unique final key).
    Returns (index, any) — index is garbage when any is False."""
    m = mask
    for k in keys:
        if jnp.issubdtype(k.dtype, jnp.floating):
            sentinel = jnp.asarray(jnp.inf, k.dtype)
        else:
            sentinel = jnp.iinfo(k.dtype).max
        kmin = jnp.min(jnp.where(m, k, sentinel))
        m = m & (k == kmin)
    return jnp.argmax(m), jnp.any(mask)


def ieee_div(x, y):
    """Correctly-rounded x/y on backends whose divide is reciprocal-based
    (measured 1-2 ulp off IEEE on both the XLA CPU and TPU builds here —
    enough to flip share ties and floor((cap-req)*10/cap) boundaries vs
    the serial Python oracle, which divides correctly rounded). One
    Newton correction with a Dekker/Veltkamp two-product residual: only
    IEEE-exact mul/add/sub plus the sloppy divide on an ulp-scale
    numerator, so the correction cannot perturb an already-correct
    quotient."""
    q = x / y
    split = jnp.asarray(
        134217729.0 if jnp.asarray(q).dtype == jnp.float64 else 4097.0,
        jnp.asarray(q).dtype,
    )  # 2^27+1 / 2^12+1: Veltkamp split constants
    c = split * q
    qh = c - (c - q)
    ql = q - qh
    d = split * y
    yh = d - (d - y)
    yl = y - yh
    p = q * y
    e = ((qh * yh - p) + qh * yl + ql * yh) + ql * yl  # q*y - p, exactly
    r = (x - p) - e  # residual x - q*y (x-p exact by Sterbenz: p ~ x)
    return q + r / y


def _le_eps(req, pool, eps):
    """Vectorized Resource.less_equal over the node axis
    (resource_info.go:255-278): per-dimension l < r + eps."""
    return jnp.all(req[None, :] < pool + eps[None, :], axis=1)


def _share_rows(alloc, denom, dims):
    """Vectorized api.helpers.share over rows: max over masked dims of
    share(alloc, denom) with 0/0 -> 0, x/0 -> 1 (helpers.go:43-60,
    drf.go:161-171, proportion.go:211-223)."""
    safe = jnp.where(denom == 0, 1.0, denom)
    # dtype-pinned 0/1 branch: a two-python-scalar where takes the default
    # float dtype, which upcasts the share matrix to f64 under x64
    # (trace-audit KBT-P002)
    zero_denom = (alloc != 0).astype(alloc.dtype)
    s = jnp.where(denom == 0, zero_denom, ieee_div(alloc, safe))
    s = jnp.where(dims, s, -jnp.inf)
    return jnp.maximum(jnp.max(s, axis=-1), 0.0)


def select_queue_job(
    a: dict, s: SolveState, enable_drf: bool, enable_proportion: bool
):
    """The replicated queue + job selection half of one loop iteration
    (proportion share asc -> overused gate -> priority/gang/drf keys),
    shared verbatim by the single-chip XLA twin and the blocked
    sharded-Pallas driver (parallel/sharded_pallas) so the two paths
    cannot drift on selection numerics. Only non-node SolveState fields
    are read, so callers may carry node state in any layout.

    Returns (qsel, q_any, overused, jsel, j_any); qsel/jsel are int32
    and garbage when the matching `any` is False.
    """
    Q = a["queue_rank"].shape[0]
    job_queue = a["job_queue"]
    eps = a["eps"]
    q_has = (
        jnp.zeros(Q, jnp.int32).at[job_queue].max(s.job_active.astype(jnp.int32))
        > 0
    ) & ~s.q_dropped
    if enable_proportion:
        q_share = _share_rows(s.q_alloc, a["q_deserved"], a["q_dims"])
        qsel, q_any = _lex_argmin(q_has, q_share, a["queue_rank"])
    else:
        qsel, q_any = _lex_argmin(q_has, a["queue_rank"])
    qsel = qsel.astype(jnp.int32)

    if enable_proportion:
        # Overused gate: deserved.LessEqual(allocated) with the Go
        # nil-scalar-map branch (proportion.go:188-199 +
        # resource_info.go:255-278).
        d_row = a["q_deserved"][qsel]
        a_row = s.q_alloc[qsel]
        dim_ok = (d_row < a_row) | (jnp.abs(a_row - d_row) < eps)
        sc_ok = jnp.concatenate(
            [
                jnp.ones(2, bool),
                jnp.full(dim_ok.shape[0] - 2, s.q_alloc_has_sc[qsel]),
            ]
        )
        dim_ok = dim_ok & sc_ok
        overused = jnp.all(jnp.where(a["q_dims"][qsel], dim_ok, True))
    else:
        overused = jnp.bool_(False)

    ready_bit = (s.ready_cnt >= a["job_min"]).astype(jnp.int32)
    jmask = s.job_active & (job_queue == qsel)
    jkeys = [-a["job_prio"], ready_bit]
    if enable_drf:
        jkeys.append(
            _share_rows(s.job_alloc, a["drf_total"][None, :], a["drf_dims"][None, :])
        )
    jkeys.append(a["job_rank"])
    jsel, j_any = _lex_argmin(jmask, *jkeys)
    return qsel, q_any, overused, jsel.astype(jnp.int32), j_any


@partial(jax.jit, static_argnames=("enable_drf", "enable_proportion"))
def init_state(a: dict, enable_drf: bool = False, enable_proportion: bool = False) -> SolveState:
    """Fresh solve state from an encoded snapshot (see ops.encode)."""
    T = a["task_req"].shape[0]
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]
    R = a["task_req"].shape[1]
    fdtype = a["task_req"].dtype
    return SolveState(
        it=jnp.int32(0),
        step=jnp.int32(0),
        cur=jnp.int32(-1),
        ptr=a["job_start"],
        assigned_node=jnp.full(T, -1, jnp.int32),
        assigned_kind=jnp.zeros(T, jnp.int32),
        assign_pos=jnp.full(T, -1, jnp.int32),
        idle=a["node_idle"],
        rel=a["node_rel"],
        used=a["node_used"],
        ntasks=a["node_ntasks"],
        nports=a["node_ports"],
        ready_cnt=a["job_ready0"],
        job_active=a["job_valid"],
        q_dropped=jnp.zeros(Q, bool),
        job_alloc=a["job_alloc0"] if enable_drf else jnp.zeros((J, R), fdtype),
        q_alloc=a["q_alloc0"] if enable_proportion else jnp.zeros((Q, R), fdtype),
        q_alloc_has_sc=a["q_alloc_has_sc0"] if enable_proportion else jnp.zeros(Q, bool),
        paused_at=jnp.int32(-1),
    )


def solve_allocate_step(
    a: dict,
    state: SolveState | None = None,
    enable_drf: bool = False,
    enable_proportion: bool = False,
) -> SolveState:
    """The full allocate solve; call through `solve_allocate` (jitted).

    Runs until every job is retired or, when the encoder flagged host-only
    tasks (`a["task_host_only"]` has any True), until such a task reaches
    the head of its job — then returns with `paused_at` set so the action
    can serial-step it and resume (`state=` carries everything forward).
    """
    T = a["task_req"].shape[0]
    J = a["job_min"].shape[0]
    Q = a["queue_rank"].shape[0]

    task_req = a["task_req"]
    task_res = a["task_res"]
    task_gid = a["task_gid"]
    task_has_sc = a["task_has_sc"]
    task_res_has_sc = a["task_res_has_sc"]
    task_ports = a["task_ports"]
    task_host_only = a["task_host_only"]
    node_alloc = a["node_alloc"]
    node_ok = a["node_ok"] & a["node_valid"]
    node_max_tasks = a["node_max_tasks"]
    node_idle_has_sc = a["node_idle_has_sc"]
    node_rel_has_sc = a["node_rel_has_sc"]
    node_gid = a["node_gid"]
    compat = a["compat"]
    aff_sc = a["aff_sc"]
    pod_sc = a["pod_sc"]  # [GT, N] InterPodAffinity (zeros when inactive)
    job_end = a["job_end"]
    job_min = a["job_min"]
    job_queue = a["job_queue"]
    eps = a["eps"]
    fdtype = task_req.dtype
    w_least = jnp.asarray(a["w_least"], fdtype)
    w_balanced = jnp.asarray(a["w_balanced"], fdtype)
    w_aff = jnp.asarray(a["w_aff"], fdtype)
    w_podaff = jnp.asarray(a["w_podaff"], fdtype)

    # One iteration per task pop, job drop, queue drop, plus one paused
    # iteration per host-only task in the segmented hybrid.
    max_iter = jnp.int32(T + J + Q + 1) + jnp.sum(task_host_only).astype(jnp.int32)

    if state is None:
        state = init_state(a, enable_drf=enable_drf, enable_proportion=enable_proportion)
    state = state._replace(paused_at=jnp.int32(-1))

    def cond(s: SolveState):
        return (
            ((s.cur >= 0) | jnp.any(s.job_active))
            & (s.it < max_iter)
            & (s.paused_at < 0)
        )

    def body(s: SolveState) -> SolveState:
        # -- queue + job selection (only bites when no current job) ---------
        need_sel = s.cur < 0
        qsel, q_any, overused, jsel, j_any = select_queue_job(
            a, s, enable_drf, enable_proportion
        )
        drop_q = need_sel & q_any & overused
        sel_ok = q_any & ~overused & j_any
        cur = jnp.where(need_sel, jnp.where(sel_ok, jsel, -1), s.cur)

        # Dropping an overused queue retires all its jobs for this cycle
        # (the serial heap drains the queue's remaining entries the same
        # way — shares only grow during allocate, so overused is final).
        job_active = jnp.where(
            drop_q, s.job_active & (job_queue != qsel), s.job_active
        )
        q_dropped = s.q_dropped.at[qsel].set(drop_q | s.q_dropped[qsel])

        # -- pop the current job's next pending task (O(1) pointer) ---------
        cur_c = jnp.maximum(cur, 0)
        t = s.ptr[cur_c]
        t_any = (cur >= 0) & (t < job_end[cur_c])
        t = jnp.minimum(t, T - 1)
        drop = (cur >= 0) & ~t_any  # tasks exhausted -> job leaves the heap
        pause = t_any & task_host_only[t]  # hybrid: host handles this task
        proc = t_any & ~pause

        # -- feasibility over the node axis (HOT LOOP #1, vectorized) -------
        req = task_req[t]
        fits_idle = _le_eps(req, s.idle, eps) & ~(task_has_sc[t] & ~node_idle_has_sc)
        fits_rel = _le_eps(req, s.rel, eps) & ~(task_has_sc[t] & ~node_rel_has_sc)
        static_ok = node_ok & compat[task_gid[t], node_gid]
        room = s.ntasks < node_max_tasks
        port_ok = ~jnp.any(task_ports[t][None, :] & s.nports, axis=1)
        cand = static_ok & room & port_ok & (fits_idle | fits_rel)
        any_cand = jnp.any(cand)
        abandon = proc & ~any_cand  # serial `break` without re-push

        # -- score (HOT LOOP #2, vectorized) + deterministic best node ------
        res = task_res[t]
        req_cpu = s.used[:, 0] + res[0]
        req_mem = s.used[:, 1] + res[1]
        cap_cpu = node_alloc[:, 0]
        cap_mem = node_alloc[:, 1]

        def least_dim(rq, cp):
            safe = jnp.where(cp == 0, 1.0, cp)
            sc = jnp.floor(ieee_div((cp - rq) * MAX_PRIORITY, safe)).astype(jnp.int32)
            return jnp.where((cp == 0) | (rq > cp), 0, sc)

        least = (least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem)) // 2
        cpu_f = jnp.where(
            cap_cpu != 0, ieee_div(req_cpu, jnp.where(cap_cpu == 0, 1.0, cap_cpu)), 1.0
        )
        mem_f = jnp.where(
            cap_mem != 0, ieee_div(req_mem, jnp.where(cap_mem == 0, 1.0, cap_mem)), 1.0
        )
        balanced = jnp.where(
            (cpu_f >= 1.0) | (mem_f >= 1.0),
            0,
            (MAX_PRIORITY - jnp.abs(cpu_f - mem_f) * MAX_PRIORITY).astype(jnp.int32),
        )
        score = (
            least.astype(fdtype) * w_least
            + balanced.astype(fdtype) * w_balanced
            + aff_sc[task_gid[t], node_gid] * w_aff
            + pod_sc[task_gid[t]] * w_podaff
        )
        nb = jnp.argmax(jnp.where(cand, score, -jnp.inf)).astype(jnp.int32)

        assign = proc & any_cand
        do_alloc = assign & fits_idle[nb]
        do_pipe = assign & ~fits_idle[nb]  # predicate guarantees fits_rel

        # -- apply the assignment (node_info.go:108-136 accounting) ---------
        zero_row = jnp.zeros_like(res)
        idle = s.idle.at[nb].add(jnp.where(do_alloc, -res, zero_row))
        rel = s.rel.at[nb].add(jnp.where(do_pipe, -res, zero_row))
        used = s.used.at[nb].add(jnp.where(assign, res, zero_row))
        ntasks = s.ntasks.at[nb].add(jnp.where(assign, 1, 0))
        nports = s.nports.at[nb].set(s.nports[nb] | (task_ports[t] & assign))
        ready_cnt = s.ready_cnt.at[cur_c].add(jnp.where(do_alloc, 1, 0))
        ptr = s.ptr.at[cur_c].add(jnp.where(proc, 1, 0))
        assigned_node = s.assigned_node.at[t].set(
            jnp.where(assign, nb, s.assigned_node[t])
        )
        kind = jnp.where(do_alloc, KIND_ALLOCATED, jnp.where(do_pipe, KIND_PIPELINED, 0))
        assigned_kind = s.assigned_kind.at[t].set(
            jnp.where(assign, kind, s.assigned_kind[t])
        )
        assign_pos = s.assign_pos.at[t].set(
            jnp.where(assign, s.step, s.assign_pos[t])
        )

        # -- drf / proportion session-event bookkeeping (drf.go:135-154,
        # proportion.go:202-223: allocated grows on allocate AND pipeline) --
        add_row = jnp.where(assign, task_res[t], zero_row)
        job_alloc = s.job_alloc.at[cur_c].add(add_row) if enable_drf else s.job_alloc
        if enable_proportion:
            qcur = job_queue[cur_c]
            q_alloc = s.q_alloc.at[qcur].add(add_row)
            q_alloc_has_sc = s.q_alloc_has_sc.at[qcur].set(
                s.q_alloc_has_sc[qcur] | (assign & task_res_has_sc[t])
            )
        else:
            q_alloc = s.q_alloc
            q_alloc_has_sc = s.q_alloc_has_sc

        # -- gang barrier / job lifecycle (allocate.go:117-119,182-185) -----
        job_active = job_active.at[cur_c].set(
            jnp.where(drop | abandon, False, job_active[cur_c])
        )
        ready_now = ready_cnt[cur_c] >= job_min[cur_c]
        cur_next = jnp.where(drop | abandon | (proc & ready_now), -1, cur)

        return SolveState(
            it=s.it + 1,
            step=s.step + assign.astype(jnp.int32),
            cur=cur_next,
            ptr=ptr,
            assigned_node=assigned_node,
            assigned_kind=assigned_kind,
            assign_pos=assign_pos,
            idle=idle,
            rel=rel,
            used=used,
            ntasks=ntasks,
            nports=nports,
            ready_cnt=ready_cnt,
            job_active=job_active,
            q_dropped=q_dropped,
            job_alloc=job_alloc,
            q_alloc=q_alloc,
            q_alloc_has_sc=q_alloc_has_sc,
            paused_at=jnp.where(pause, t, jnp.int32(-1)),
        )

    return lax.while_loop(cond, body, state)


def result_of(state: SolveState) -> SolveResult:
    return SolveResult(
        assigned_node=state.assigned_node,
        assigned_kind=state.assigned_kind,
        assign_pos=state.assign_pos,
        ready_cnt=state.ready_cnt,
        n_assigned=state.step,
    )


@partial(jax.jit, static_argnames=("enable_drf", "enable_proportion"))
def _solve_fresh(a: dict, enable_drf: bool, enable_proportion: bool) -> SolveState:
    return solve_allocate_step(
        a, None, enable_drf=enable_drf, enable_proportion=enable_proportion
    )


@partial(jax.jit, static_argnames=("enable_drf", "enable_proportion"))
def _solve_resume(
    a: dict, state: SolveState, enable_drf: bool, enable_proportion: bool
) -> SolveState:
    return solve_allocate_step(
        a, state, enable_drf=enable_drf, enable_proportion=enable_proportion
    )


def solve_allocate(
    a: dict,
    state: SolveState | None = None,
    enable_drf: bool = False,
    enable_proportion: bool = False,
) -> SolveResult:
    """One-shot jitted solve returning just the assignment result (ignores
    pause; callers with host-only tasks drive the segmented loop through
    `solve_allocate_state`)."""
    return result_of(solve_allocate_state(a, state, enable_drf, enable_proportion))


def solve_allocate_state(
    a: dict,
    state: SolveState | None = None,
    enable_drf: bool = False,
    enable_proportion: bool = False,
) -> SolveState:
    if state is None:
        return _solve_fresh(a, enable_drf, enable_proportion)
    return _solve_resume(a, state, enable_drf, enable_proportion)
