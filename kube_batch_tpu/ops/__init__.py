"""kube_batch_tpu.ops: the TPU compute path.

The reference schedules serially — per task, a 16-goroutine scan over all
nodes for predicates and priorities (reference
pkg/scheduler/util/scheduler_helper.go:34-109) inside the allocate loop
(actions/allocate/allocate.go:94-190). Here the same cycle is one XLA
program: the cluster snapshot is encoded as struct-of-arrays tensors
(`encode`), and a jitted `lax.while_loop` performs the full
queue/job/task-ordered, gang-aware assignment with every per-node scan
vectorized (`kernels`). The serial actions remain the correctness oracle;
property tests pin serial ≡ XLA assignment-for-assignment.
"""

import os as _os


def enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the solve recompiles only when a
    padding bucket changes shape, but a fresh process (server restart,
    bench run, failover standby taking over) pays each bucket's 10-30 s
    trace+compile again without one. Opt-out with KBT_JAX_CACHE=0 or
    point KBT_JAX_CACHE at a directory.

    Called by the scheduler entry points (Scheduler init, bench, the
    graft entry) — deliberately NOT at import, so an embedding
    application that configures jax itself keeps full control no matter
    the import order; it defers to any cache dir already set."""
    spec = _os.environ.get("KBT_JAX_CACHE", "")
    if spec == "0":
        return
    try:
        import jax

        # Respect an embedding application's own cache configuration
        # (env or explicit jax.config) — only fill the gap.
        if getattr(jax.config, "jax_compilation_cache_dir", None) or _os.environ.get(
            "JAX_COMPILATION_CACHE_DIR"
        ):
            return
        path = spec or _os.path.join(
            _os.path.expanduser("~"), ".cache", "kube-batch-tpu", "jax"
        )
        _os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # persist any compile costing >= 0.5 s (the solve's bucket
        # compiles are 10-30 s; sub-0.5s programs stay uncached — not
        # worth the disk churn) regardless of program size
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # noqa: BLE001 -- cache is an optimization only
        import logging

        logging.getLogger("kube_batch_tpu.ops").info(
            "persistent jax compilation cache unavailable", exc_info=True
        )


from kube_batch_tpu.ops.encode import EncodedSnapshot, encode_session  # noqa: E402
from kube_batch_tpu.ops.kernels import solve_allocate  # noqa: E402

__all__ = [
    "EncodedSnapshot",
    "encode_session",
    "enable_compilation_cache",
    "solve_allocate",
]
