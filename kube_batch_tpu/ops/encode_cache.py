"""Incremental cross-cycle encode cache + device-resident tensor arena.

BENCH_r05 showed ~27% of the flagship cycle's wall clock is host-side
encode/replay work recomputed from scratch every session even though
consecutive snapshots differ by a handful of pods/nodes. Production
schedulers amortize exactly this (Kant keeps cluster state resident and
updates it event-driven; "Priority Matters" measures constraint/packing
matrices as overwhelmingly stable across Kubernetes scheduling rounds).
This module makes the encode cost scale with the *delta*:

- **signature memos**: `_task_signature` / `_node_signature` results are
  memoized per pod uid / node name, validated by *object identity* of
  the underlying API object (`task.pod` / `node_info.node`). Snapshot
  clones share those objects (TaskInfo.clone / NodeInfo.clone keep the
  reference), and every store-side change replaces the object wholesale
  (the cache-mutation detector outlaws in-place mutation), so identity
  is a sound freshness check with zero recomputation.
- **pair memo**: the static (task-group x node-group) predicate verdict
  and preferred-node-affinity score are pure functions of the two
  signatures (the same property the encoder's group dedup already
  relies on); unchanged group pairs are reused verbatim, so the
  O(GT*GN) compat product is paid only for *new* pairs.
- **block caches**: the task-side products of one encode (pending
  extraction, row order, grouping, dense task arrays) are reusable
  wholesale while the session is unmutated (`Session.state_seq`) and
  the job objects are identical; the node-side statics (signatures,
  condition/pressure verdicts, max_task_num) reuse per node while its
  `Node` object is unchanged. A steady-state warm encode is therefore
  O(dirty + gather): only churned objects recompute, plus the dynamic
  residency slabs (idle/releasing/used), which must re-gather every
  cycle because binds move them.
- **dirty feed** (`note_store_event`): the scheduler cache's informer
  handlers report node/pod/podgroup/queue churn; each event bumps a
  monotonic `version`, drops the per-object memo entries, and meters
  `encode_cache_invalidations_total{reason}`. Identity validation makes
  the feed *advisory* for correctness — it exists to bound memo growth
  (deleted objects leave the memo), to make invalidation observable,
  and to stamp a store version onto cache state for debugging.
- **TensorArena**: persistent on-device buffers for the per-node
  capacity/idle slabs and the group matrices. Warm cycles upload only
  changed rows (donated-buffer in-place row scatter) instead of
  re-transferring the full tensor set; arrays the encode cache reused
  verbatim skip the upload entirely (object identity short-circuit).

``KBT_ENCODE_CACHE`` (default on; ``0`` disables) gates all of it; the
``encode.cache`` fault point poisons the cache for one encode — the
whole state is dropped and that encode runs cold, which is also the
recovery story for any suspected-stale cache. Warm output is
byte-identical to cold by construction (every reused value is the value
the cold path would recompute); `python -m kube_batch_tpu.ops.encode_cache`
is the parity smoke the verify gate runs.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from kube_batch_tpu import faults, metrics

ENV = "KBT_ENCODE_CACHE"

# Memo-size backstops: a cluster-scale snapshot holds ~400k pods / 40k
# nodes; past these the whole layer clears (cold next encode) rather
# than growing without bound on pathological churn.
_MAX_POD_ENTRIES = 2_000_000
_MAX_NODE_ENTRIES = 200_000
_MAX_PAIR_ENTRIES = 500_000


def enabled() -> bool:
    return os.environ.get(ENV, "1") != "0"


class _TaskBlock:
    """One encode's task-side products, reusable while the session is
    unmutated and the job objects are identical."""

    __slots__ = (
        "session", "state_seq", "shortlist", "queues", "dtype", "pad",
        "job_list", "job_idx", "task_list", "task_plain", "host_only",
        "job_ranges", "host_only_rows", "ref_label_keys",
        "scalar_task_names", "interesting_ports",
        # grouping per interpod flag: {bool: (task_gid, t_reps, t_rep_sigs)}
        "groupings",
        # dense array bundle keyed by (scalar_names, ports): see encode.py
        "arrays_key", "arrays",
    )


class _NodeStatic:
    __slots__ = ("node", "ok", "max_tasks", "sig", "sig_label_keys")

    def __init__(self, node) -> None:
        self.node = node
        self.ok = None
        self.max_tasks = None
        self.sig = None
        self.sig_label_keys = None


class EncodeCache:
    """Process-wide incremental encode state (see module docstring).

    Thread-safe for the dirty feed (informer handlers run in store
    writer threads); the encode-side memo methods are called from the
    single scheduling thread, matching the session's own threading
    model.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: monotonic store version; bumped by every relevant store event
        self.version = 0
        self._pod_sigs: dict[str, tuple] = {}  # uid -> (pod, sig, sig_labels)
        self._node_static: dict[str, _NodeStatic] = {}
        self._pairs: dict[tuple, tuple] = {}  # (tsig, nsig) -> (compat, aff)
        self._task_block: Optional[_TaskBlock] = None
        # per-encode stats (reset by begin_encode)
        self._hits = 0
        self._misses = 0

    # -- dirty feed (cache/watch events) ------------------------------------

    def note_store_event(self, kind: str, key: str) -> None:
        """One informer event: bump the monotonic version, drop the
        object's memo entries, meter the invalidation. ``kind`` is the
        store kind ("pods"/"nodes"/...), ``key`` the object key (pod
        uid / node name)."""
        with self._lock:
            self.version += 1
            dropped = False
            if kind == "nodes":
                dropped = self._node_static.pop(key, None) is not None
            elif kind == "pods":
                dropped = self._pod_sigs.pop(key, None) is not None
            # any churn invalidates the whole-encode task block: its
            # validity is session-identity-scoped anyway, but dropping
            # here keeps a dead session's world from being retained
            # across real store churn
            if self._task_block is not None and kind in ("pods", "podgroups", "queues"):
                self._task_block = None
                dropped = True
        if dropped:
            metrics.register_encode_cache_invalidation(kind)

    def invalidate_all(self, reason: str) -> None:
        with self._lock:
            self.version += 1
            self._pod_sigs.clear()
            self._node_static.clear()
            self._pairs.clear()
            self._task_block = None
        metrics.register_encode_cache_invalidation(reason)

    # -- per-encode lifecycle ------------------------------------------------

    def begin_encode(self) -> None:
        self._hits = 0
        self._misses = 0
        # capacity backstops (cold next encode is the worst case)
        if (
            len(self._pod_sigs) > _MAX_POD_ENTRIES
            or len(self._node_static) > _MAX_NODE_ENTRIES
            or len(self._pairs) > _MAX_PAIR_ENTRIES
        ):
            self.invalidate_all("capacity")

    def end_encode(self) -> None:
        total = self._hits + self._misses
        if self._hits:
            metrics.register_encode_cache_hits(self._hits)
        metrics.set_encode_warm_fraction(self._hits / total if total else 0.0)

    @property
    def warm_fraction(self) -> float:
        total = self._hits + self._misses
        return self._hits / total if total else 0.0

    # -- memo layers ---------------------------------------------------------

    def task_sig(self, task, with_labels: bool, sig_fn) -> tuple:
        """Memoized `_task_signature(task, with_labels)`; valid while the
        entry's Pod object IS the task's Pod object."""
        entry = self._pod_sigs.get(task.uid)
        pod = task.pod
        if entry is not None and entry[0] is pod:
            sig = entry[2 if with_labels else 1]
            if sig is not None:
                self._hits += 1
                return sig
            sig = sig_fn(task, with_labels)
            self._pod_sigs[task.uid] = (
                pod,
                sig if not with_labels else entry[1],
                sig if with_labels else entry[2],
            )
            self._misses += 1
            return sig
        sig = sig_fn(task, with_labels)
        self._pod_sigs[task.uid] = (
            pod,
            sig if not with_labels else None,
            sig if with_labels else None,
        )
        self._misses += 1
        return sig

    def node_entry(self, node_info) -> _NodeStatic:
        """The per-node static slot (sig + condition/pressure verdict +
        max_task_num), re-keyed whenever the Node object was replaced."""
        entry = self._node_static.get(node_info.name)
        if entry is None or entry.node is not node_info.node:
            entry = _NodeStatic(node_info.node)
            self._node_static[node_info.name] = entry
        return entry

    def node_sig(self, node_info, label_keys, sig_fn) -> tuple:
        entry = self.node_entry(node_info)
        if entry.sig is not None and entry.sig_label_keys == label_keys:
            self._hits += 1
            return entry.sig
        entry.sig = sig_fn(node_info, label_keys)
        entry.sig_label_keys = label_keys
        self._misses += 1
        return entry.sig

    def node_statics(self, node_info, compute) -> tuple:
        """(schedulable-verdict, max_task_num) per node, valid while the
        Node object is unchanged."""
        entry = self.node_entry(node_info)
        if entry.ok is None:
            entry.ok, entry.max_tasks = compute(node_info)
            self._misses += 1
        else:
            self._hits += 1
        return entry.ok, entry.max_tasks

    def node_row(self, node_info, label_keys, sig_fn, statics_fn) -> _NodeStatic:
        """One cache touch per node per encode: the filled static slot
        (sig + verdicts), counted as one warm unit when fully reused."""
        entry = self.node_entry(node_info)
        if entry.ok is None:
            entry.ok, entry.max_tasks = statics_fn(node_info)
        if entry.sig is None or entry.sig_label_keys != label_keys:
            entry.sig = sig_fn(node_info, label_keys)
            entry.sig_label_keys = label_keys
            self._misses += 1
        else:
            self._hits += 1
        return entry

    def pair(self, tsig, nsig, compute) -> tuple:
        """(static compat verdict, preferred-affinity score) for one
        (task-group, node-group) signature pair — pure in the sigs."""
        key = (tsig, nsig)
        got = self._pairs.get(key)
        if got is not None:
            self._hits += 1
            return got
        got = compute()
        self._pairs[key] = got
        self._misses += 1
        return got

    # -- task block ----------------------------------------------------------

    def lookup_task_block(
        self, session, shortlist, queues, dtype, pad
    ) -> Optional[_TaskBlock]:
        """The whole task side of the previous encode, valid iff the
        session object and its mutation counter match (every
        allocate/pipeline/evict and the bulk replay bump `state_seq`)
        and the job/queue objects are identical (list `==` on
        identity-compared elements — TaskInfo/JobInfo define no __eq__)."""
        tb = self._task_block
        if (
            tb is not None
            and session is not None
            and tb.session is session
            and tb.state_seq == session.state_seq
            and tb.dtype == dtype
            and tb.pad == pad
            and tb.shortlist == shortlist
            and tb.queues is queues
        ):
            self._hits += 1
            return tb
        self._misses += 1
        return None

    def store_task_block(self, session, shortlist, queues, dtype, pad, **fields) -> Optional[_TaskBlock]:
        if session is None:
            return None
        tb = _TaskBlock()
        tb.session = session
        tb.state_seq = session.state_seq
        tb.shortlist = list(shortlist)
        tb.queues = queues
        tb.dtype = dtype
        tb.pad = pad
        tb.groupings = {}
        tb.scalar_task_names = None
        tb.interesting_ports = None
        tb.arrays_key = None
        tb.arrays = None
        for k, v in fields.items():
            setattr(tb, k, v)
        self._task_block = tb
        return tb


_cache = EncodeCache()


def get() -> EncodeCache:
    return _cache


def active() -> Optional[EncodeCache]:
    """The cache for this encode, or None (disabled / poisoned).

    The ``encode.cache`` fault point models a poisoned cache: the whole
    state is dropped and the encode runs cold — the exact operator
    recovery story for a suspected-stale cache (flip ``KBT_ENCODE_CACHE``
    or restart; the next cycle rebuilds from the store)."""
    if not enabled():
        return None
    if faults.should_fire("encode.cache"):
        _cache.invalidate_all("fault")
        return None
    return _cache


# Streaming-mode listeners (kube_batch_tpu/streaming.py): each gets the
# full event `(kind, key, obj, old)` regardless of whether the encode
# cache itself is enabled — the dirty feed doubles as the scheduler's
# wake-up trigger. Listener errors are swallowed per call: an informer
# thread must never die on a trigger bug (the periodic full cycle is
# the backstop either way).
_listeners: list = []
_listeners_lock = threading.Lock()


def add_store_listener(fn) -> None:
    with _listeners_lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_store_listener(fn) -> None:
    with _listeners_lock:
        if fn in _listeners:
            _listeners.remove(fn)


def listener_count() -> int:
    """How many listeners are currently registered. A stopped streaming
    loop must leave this at its pre-attach value — a leaked listener
    keeps firing into a dead loop on every store event (KBT-C005's
    hazard class, pinned by tests/test_streaming.py)."""
    with _listeners_lock:
        return len(_listeners)


def note_store_event(kind: str, key: str, obj=None, old=None) -> None:
    """Module-level dirty-feed entry point (what cache/cache.py calls).
    ``obj`` is the post-event object (None on delete), ``old`` the
    pre-event one (None on add) — the streaming trigger patches its
    resident state from these without re-reading the store."""
    if enabled():
        _cache.note_store_event(kind, key)
    if _listeners:
        with _listeners_lock:
            listeners = list(_listeners)
        for fn in listeners:
            try:
                fn(kind, key, obj, old)
            except Exception as e:  # noqa: BLE001 - see registry comment
                from kube_batch_tpu import log

                log.errorf("store listener failed on %s/%s: %s", kind, key, e)


# -- device-resident tensor arena -------------------------------------------


class _Slot:
    __slots__ = ("host", "device", "placement")

    def __init__(self, host, device, placement) -> None:
        self.host = host
        self.device = device
        self.placement = placement


class TensorArena:
    """Persistent on-device buffers for the solve's big inputs.

    The encoder rebuilds its host arrays every cycle, but between
    consecutive cycles most *rows* are unchanged (only nodes that took
    or released pods move). The arena keeps last cycle's device buffer
    plus the host array it was uploaded from; the next upload of the
    same (name, shape, dtype):

    - reuses the buffer outright when the host array is the *same
      object* (the encode cache's warm path returns identical arrays)
      or compares equal;
    - scatters only the changed rows into the existing buffer
      (donated, so XLA updates in place) when few rows moved;
    - falls back to a full `device_put` otherwise.

    Row comparison runs on host numpy (one vectorized equality over the
    slab — memcmp speed, far below the transfer it saves). The arena is
    correct with no dirty feed at all: the comparison IS the truth.
    Host arrays handed to the arena must not be mutated afterwards (the
    encoder never does — every cycle builds fresh arrays).

    **Pipelined mode** (``KBT_PIPELINE``): the slots double-buffer.
    Each managed name keeps two (host memo, device buffer) banks and
    ``device_view`` ping-pongs the active bank per cycle, so cycle N+1's
    donated row-scatter mutates a buffer the still-running solve/dispatch
    of cycle N is *not* reading. The row delta is computed against the
    active bank's own host memo — a two-cycles-old baseline, so a warm
    upload may scatter more rows than the single-buffer path, but the
    result is byte-identical (the comparison is still the truth).
    """

    # node-axis slabs take the row-delta path; the group matrices are
    # replaced wholesale when their content changes
    ROW_DELTA = frozenset({"node_idle", "node_rel", "node_used", "node_alloc"})
    MANAGED = (
        "node_idle", "node_rel", "node_used", "node_alloc",
        "task_req", "task_res", "compat", "aff_sc", "pod_sc",
    )
    # past this fraction of changed rows a full transfer is cheaper
    # than scatter index math
    ROW_DELTA_MAX_FRACTION = 0.25

    def __init__(self) -> None:
        self._slots: dict[tuple, _Slot] = {}  # (name, bank) -> slot
        self._bank = 0
        # counters exposed for tests/metrics narration
        self.reuses = 0
        self.row_updates = 0
        self.full_uploads = 0
        self.rows_uploaded = 0
        # high-water mark of live device bytes across all slabs+banks,
        # refreshed by device_view; the bench's HBM column
        self.hbm_watermark_bytes = 0

    @property
    def bank(self) -> int:
        return self._bank

    def _flip_bank(self) -> None:
        from kube_batch_tpu import pipeline

        self._bank = (self._bank ^ 1) if pipeline.enabled() else 0

    def _placement_key(self, mesh, name: str):
        if mesh is None:
            return None
        return (tuple(mesh.devices.flat), name)

    def _sharding(self, mesh, name: str):
        if mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kube_batch_tpu.parallel.sharded import AXIS_NAME, NODE_AXIS_ARRAYS

        if name in NODE_AXIS_ARRAYS:
            spec = P(AXIS_NAME)
        elif name == "pod_sc":
            spec = P(None, AXIS_NAME)
        else:
            spec = P()
        return NamedSharding(mesh, spec)

    def _put(self, host, mesh, name):
        import jax

        sharding = self._sharding(mesh, name)
        if sharding is None:
            return jax.device_put(host)
        return jax.device_put(host, sharding)

    def device_view(self, arrays: dict, mesh=None) -> dict:
        """`arrays` with the managed slabs replaced by device handles;
        everything else passes through for jit's own transfer (scalars
        and the small int/bool vectors are not worth residency)."""
        out = dict(arrays)
        self._flip_bank()
        for name in self.MANAGED:
            host = arrays.get(name)
            if host is None:
                continue
            out[name] = self.upload(name, host, mesh=mesh)
        self._account_hbm()
        return out

    def hbm_bytes_by_slab(self) -> dict[str, int]:
        """Live device bytes per managed slab, summed over banks (in
        pipelined mode both double-buffers are resident, so both
        count)."""
        out: dict[str, int] = {}
        for (name, _bank), slot in self._slots.items():
            nbytes = getattr(slot.device, "nbytes", None)
            if nbytes is None:
                continue
            out[name] = out.get(name, 0) + int(nbytes)
        return out

    def _account_hbm(self) -> None:
        total = 0
        for slab, nbytes in self.hbm_bytes_by_slab().items():
            metrics.set_arena_hbm_bytes(slab, nbytes)
            total += nbytes
        if total > self.hbm_watermark_bytes:
            self.hbm_watermark_bytes = total
        metrics.set_arena_hbm_watermark(self.hbm_watermark_bytes)

    def refresh(self, views: list, name: str, host, mesh=None) -> None:
        """Re-upload one array (the action's pod_sc refresh between
        pause/resume segments) into every live device view."""
        dev = self.upload(name, host, mesh=mesh)
        for v in views:
            v[name] = dev

    def upload(self, name: str, host, mesh=None):
        host = np.asarray(host)
        slot = self._slots.get((name, self._bank))
        placement = self._placement_key(mesh, name)
        if (
            slot is not None
            and slot.placement == placement
            and slot.host.shape == host.shape
            and slot.host.dtype == host.dtype
        ):
            if slot.host is host:
                self.reuses += 1
                return slot.device
            if name in self.ROW_DELTA and host.ndim >= 1 and mesh is None:
                neq = slot.host != host
                changed = (
                    np.nonzero(neq.any(axis=tuple(range(1, host.ndim))))[0]
                    if host.ndim > 1
                    else np.nonzero(neq)[0]
                )
                if changed.size == 0:
                    slot.host = host
                    self.reuses += 1
                    return slot.device
                if changed.size <= self.ROW_DELTA_MAX_FRACTION * host.shape[0]:
                    slot.device = _row_scatter(slot.device, changed, host)
                    slot.host = host
                    self.row_updates += 1
                    self.rows_uploaded += int(changed.size)
                    return slot.device
            elif np.array_equal(slot.host, host):
                slot.host = host
                self.reuses += 1
                return slot.device
        dev = self._put(host, mesh, name)
        self._slots[(name, self._bank)] = _Slot(host, dev, placement)
        self.full_uploads += 1
        return dev

    def clear(self) -> None:
        self._slots.clear()
        self._bank = 0
        self.hbm_watermark_bytes = 0


def _row_scatter(device_buf, rows: np.ndarray, new_host: np.ndarray):
    """buf.at[rows].set(new rows) with the old buffer donated (in-place
    on device). The row count pads to a power-of-two bucket — the pad
    entries re-scatter the first changed row with its own new value, a
    deterministic no-op — so jit retraces per bucket, not per churn
    count."""
    n = int(rows.size)
    bucket = 1 << max(n - 1, 0).bit_length() if n > 1 else 1
    idx = np.full(bucket, rows[0], dtype=np.int64)
    idx[:n] = rows
    vals = new_host[idx]
    return _scatter_jit()(device_buf, idx, vals)


_scatter_fn = None


def _scatter_jit():
    """One donated row-scatter program (jit caches per shape/dtype
    signature internally)."""
    global _scatter_fn
    if _scatter_fn is None:
        import jax

        _scatter_fn = jax.jit(lambda b, i, v: b.at[i].set(v), donate_argnums=(0,))
    return _scatter_fn


# -- parity smoke (the verify gate's encode-cache check) ---------------------


def smoke() -> int:
    """Cold-vs-warm parity on a seeded snapshot: a warm encode (and a
    1%-node-churn encode) must be byte-identical to a fresh cold encode.
    Returns 0 when clean; prints one line per failure."""
    from kube_batch_tpu import actions, plugins  # noqa: F401  (registries)
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, open_session
    from kube_batch_tpu.models import multi_queue
    from kube_batch_tpu.ops.encode import encode_session
    from kube_batch_tpu.testing import FakeCache, build_node, build_resource_list

    conf = parse_scheduler_conf(
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "  - name: conformance\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
        "  - name: nodeorder\n"
    )

    def encode(ssn):
        return encode_session(
            ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64,
            drf=ssn.plugins.get("drf"), proportion=ssn.plugins.get("proportion"),
            session=ssn,
        )

    def diff(a, b, what: str) -> list[str]:
        bad = []
        if set(a.arrays) != set(b.arrays):
            bad.append(f"{what}: array key sets differ")
            return bad
        for k in a.arrays:
            x, y = np.asarray(a.arrays[k]), np.asarray(b.arrays[k])
            if x.shape != y.shape or x.dtype != y.dtype or not np.array_equal(x, y):
                bad.append(f"{what}: arrays[{k!r}] diverges")
        return bad

    rc = 0
    ec = get()
    cache = FakeCache(multi_queue(600, 96))
    ssn = open_session(cache, conf.tiers)
    ec.invalidate_all("smoke")
    cold = encode(ssn)
    warm = encode(ssn)
    problems = diff(cold, warm, "warm-vs-cold")
    if get().warm_fraction <= 0.5:
        problems.append(
            f"warm encode reused only {get().warm_fraction:.0%} of units"
        )
    # 1% node churn: replace one node object (a label flip), re-encode,
    # compare against a fully cold encode of the same world
    churned = sorted(ssn.nodes)[0]
    ni = ssn.nodes[churned]
    ni.set_node(
        build_node(
            churned,
            build_resource_list(cpu=8, memory="16Gi", pods=110),
            labels={"smoke/churned": "1"},
        )
    )
    churn = encode(ssn)
    ec.invalidate_all("smoke")
    cold2 = encode(ssn)
    problems += diff(cold2, churn, "churn-vs-cold")
    close_session(ssn)
    for p in problems:
        print(f"encode-cache smoke: {p}")
        rc = 1
    if rc == 0:
        print("encode-cache smoke: ok (warm + 1%-churn encodes byte-identical to cold)")
    return rc


def smoke_pipeline() -> int:
    """Pipelined-vs-synchronous parity smoke (``--pipeline``, the verify
    gate's second encode-cache check): one seeded world scheduled twice
    — ``KBT_PIPELINE`` off, then on — must bind pod-for-pod identically,
    with the pipelined run's dispatch actually deferred through the
    fence and the arena ping-ponging its device banks across cycles."""
    from kube_batch_tpu import actions, pipeline, plugins  # noqa: F401  (registries)
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, get_action, open_session
    from kube_batch_tpu.models import multi_queue
    from kube_batch_tpu.testing import FakeCache

    conf = parse_scheduler_conf(
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "  - name: conformance\n"
        "- plugins:\n"
        "  - name: predicates\n"
        "  - name: nodeorder\n"
    )
    action = get_action("xla_allocate")

    def run(pipelined: bool):
        save = os.environ.get(pipeline.ENV)
        os.environ[pipeline.ENV] = "1" if pipelined else "0"
        pipeline.reset()
        get().invalidate_all("smoke")
        try:
            cache = FakeCache(multi_queue(600, 96))
            banks, deferred = [], []
            cycle1_binds = None
            for _ in range(2):  # two cycles: the bank must ping-pong
                ssn = open_session(cache, conf.tiers)
                action.execute(ssn)
                banks.append(action._arena.bank)
                deferred.append(getattr(ssn, "deferred_dispatch", None) is not None)
                close_session(ssn)  # joins the deferred dispatch first
                if cycle1_binds is None:
                    cycle1_binds = dict(cache.binder.binds)
            return cycle1_binds, banks, deferred
        finally:
            if save is None:
                os.environ.pop(pipeline.ENV, None)
            else:
                os.environ[pipeline.ENV] = save
            pipeline.reset()

    problems = []
    sync_binds, sync_banks, sync_deferred = run(False)
    pipe_binds, pipe_banks, pipe_deferred = run(True)
    if not sync_binds:
        problems.append("synchronous run bound nothing")
    if any(sync_deferred):
        problems.append("synchronous run unexpectedly deferred its dispatch")
    if not all(pipe_deferred):
        problems.append("pipelined run never deferred its dispatch")
    if len(set(pipe_banks)) != 2:
        problems.append(
            f"arena banks did not ping-pong across pipelined cycles: {pipe_banks}"
        )
    if len(set(sync_banks)) != 1:
        problems.append(f"synchronous run flipped arena banks: {sync_banks}")
    if pipe_binds != sync_binds:
        diff = {
            k: (sync_binds.get(k), pipe_binds.get(k))
            for k in set(sync_binds) | set(pipe_binds)
            if sync_binds.get(k) != pipe_binds.get(k)
        }
        problems.append(f"pipelined binds diverge from synchronous: {diff}")
    if pipeline.fence.degraded_reason is not None:
        problems.append(f"pipeline degraded during smoke: {pipeline.fence.degraded_reason}")
    rc = 0
    for p in problems:
        print(f"pipeline smoke: {p}")
        rc = 1
    if rc == 0:
        print(
            "pipeline smoke: ok (pipelined cycle bind-for-bind identical to "
            f"synchronous, dispatch deferred, arena banks {pipe_banks})"
        )
    return rc


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level singleton would otherwise be
    # a different object than the one encode_session uses
    import sys as _sys

    if "--pipeline" in _sys.argv[1:]:
        from kube_batch_tpu.ops.encode_cache import smoke_pipeline as _canonical

        raise SystemExit(_canonical())
    from kube_batch_tpu.ops.encode_cache import smoke as _canonical_smoke

    raise SystemExit(_canonical_smoke())
