"""Snapshot -> struct-of-arrays tensor encoder (the TPU path's front end).

Encodes the session view (jobs/nodes/queues, reference
pkg/scheduler/api/cluster_info.go:22-26) into dense, padded, fixed-width
arrays that `kernels.solve_allocate` consumes in one jitted program:

- resource rows follow the `Resource.to_vector` contract
  ``[milli_cpu, memory, *scalar_slots]`` with the per-slot epsilon vector
  (api/resource_info.py);
- tasks are laid out **contiguously per job** in serial pop order
  (priority desc -> creation -> uid within the job;
  session_plugins.go:329-341), jobs in serial fallback order
  (creation -> uid), so the kernel pops a job's next task with one
  pointer increment instead of an O(T) masked argmin (`job_start` /
  `job_end` delimit each job's rows);
- the label-world predicates (node selector, required node affinity,
  taints/tolerations, cordon) and the preferred-node-affinity score are
  **deduplicated into (task-group x node-group) matrices**: tasks sharing
  a pod spec signature and nodes sharing a label/taint signature hit the
  same pure check functions (plugins/predicates.py, plugins/nodeorder.py)
  exactly once per group pair, then broadcast by integer gather on device.
  A 10k-task job is one group, so encoding is O(T + N + GT*GN), not
  O(T*N). Node signatures keep only the label keys actually referenced by
  pending tasks' selectors/affinity terms — a cluster whose nodes all
  carry unique labels (kubernetes.io/hostname) still collapses to a
  handful of groups (round-2 advisor finding);
- host ports become a small boolean incidence over the distinct ports
  pending tasks actually use, so conflicts with both residents and
  newly-assigned tasks are dynamic bitmask tests in the kernel;
- drf / proportion session state is lifted straight from the plugin
  instances (per-job allocated vectors + cluster totals, per-queue
  allocated / water-filled deserved + the Go nil-scalar-map parity bits)
  so the kernel's in-loop share updates start bit-identical to the serial
  plugins' event-handler state (drf.go:60-83, proportion.go:58-144);
- everything is padded to stable buckets — power-of-two for tasks/jobs/
  queues, multiples of 128 for the node axis (static shapes for XLA,
  SURVEY.md section 7 hard part (e)) with validity masks.

Tasks using required pod (anti-)affinity are flagged ``host_only``: that
predicate is pairwise-dynamic over resident pods (reference
predicates.go:187-199). The kernel pauses when such a task reaches the
head of its job and the action serial-steps it (segmented hybrid,
actions/xla_allocate).

Dtype: float64 arrays make the XLA path bit-identical to the serial
float64 Python path (the equivalence property tests run this way on CPU);
the TPU bench path uses float32, which is exact for milli-CPU-granular
cpu and MiB-granular memory (values stay on a 2^20-multiple grid well
inside the 24-bit mantissa).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from kube_batch_tpu.native import lib as _native

from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.plugins.predicates import (
    check_node_condition,
    check_node_selector,
    check_node_unschedulable,
    check_pressure,
    check_taints,
)


_warned_native_fallback: set[str] = set()


def _log_native_fallback(fn: str) -> None:
    """A native extractor failing is a defect signal (the slow path is
    correct, so it must not be silent) — log it once per function."""
    if fn not in _warned_native_fallback:
        _warned_native_fallback.add(fn)
        import logging

        logging.getLogger("kube_batch_tpu.ops.encode").warning(
            "native %s failed; using the numpy encode path", fn, exc_info=True
        )


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two bucket >= max(n, 1) so XLA recompiles only on
    bucket crossings, not on every pod/node churn."""
    size = max(n, 1, minimum)
    return 1 << (size - 1).bit_length()


def _node_bucket(n: int) -> int:
    """Node-axis bucket: next multiple of 128 (one TPU lane row).

    The node axis is the kernel's per-iteration payload — every loop
    step evaluates feasibility + scores over all N_pad lanes — so
    power-of-two padding is real wasted VPU work (5k nodes -> 8192 pad
    = +64%). Nodes churn rarely (tasks churn every cycle; they keep the
    coarse pow2 buckets), so 128-granular buckets recompile only when
    the fleet itself crosses a lane row, and any power-of-two mesh size
    up to 128 still divides the bucket for the GSPMD path."""
    return max((n + 127) // 128 * 128, 128)


_PLAIN_SIG = ((), "None", (), ())


def group_by_signature(items, sig_fn):
    """Dedup `items` by signature: returns (gids int32[len(items)],
    reps) with group ids in first-occurrence order — the (task-group ×
    node-group) machinery shared by the encoder and the vectorized
    backfill scan."""
    groups: dict = {}
    gids = np.zeros(len(items), np.int32)
    reps: list = []
    for i, item in enumerate(items):
        sig = sig_fn(item)
        gid = groups.get(sig)
        if gid is None:
            gid = groups[sig] = len(reps)
            reps.append(item)
        gids[i] = gid
    return gids, reps


def build_static_compat(t_reps, n_reps, aff_sc=None):
    """[GT, GN] static predicate verdicts per (task-group, node-group)
    pair — `static_pod_node_compat` over the reps; a node group without
    a Node object rejects everything (predicates.py). When `aff_sc` is
    given, the preferred-node-affinity score is filled in the same
    sweep (the encoder's fused form)."""
    from kube_batch_tpu.plugins.nodeorder import node_affinity_score

    compat = np.zeros((max(len(t_reps), 1), max(len(n_reps), 1)), bool)
    for gi, trep in enumerate(t_reps):
        for gj, nrep in enumerate(n_reps):
            if nrep.node is None:
                continue
            compat[gi, gj] = static_pod_node_compat(trep.pod, nrep.node)
            if aff_sc is not None:
                aff_sc[gi, gj] = node_affinity_score(trep, nrep)
    return compat


def static_pod_node_compat(pod, node) -> bool:
    """The task-static × node-static predicate subset — cordon, node
    selector/required node affinity, taints (predicates.py) — shared by
    the encoder's (task-group × node-group) compat matrix and the
    vectorized backfill scan, so a predicate-chain change lands in one
    place. The node-dynamic checks (condition/pressure/pod count/ports)
    and the pairwise pod-affinity check stay with their callers."""
    return (
        check_node_unschedulable(pod, node)
        and check_node_selector(pod, node)
        and check_taints(pod, node)
    )


def _task_signature(task: TaskInfo, with_labels: bool = False) -> tuple:
    """Dedup key for the (task-group x node-group) predicate matrices.
    ``with_labels`` extends the key with the pod's own labels — needed
    when any pod in the snapshot carries pod-affinity terms, because the
    symmetric InterPodAffinity score reads the *incoming* pod's labels
    (plugins/nodeorder.py interpod_affinity_scores)."""
    pod = task.pod
    if (
        not pod.node_selector
        and pod.affinity is None
        and not pod.tolerations
        and not (with_labels and pod.metadata.labels)
    ):
        return _PLAIN_SIG  # fast path: the overwhelmingly common pod shape
    return (
        tuple(sorted(pod.node_selector.items())),
        repr(pod.affinity),
        tuple(sorted(repr(t) for t in pod.tolerations)),
        tuple(sorted(pod.metadata.labels.items())) if with_labels else (),
    )


def _node_signature(node: NodeInfo, label_keys: frozenset[str]) -> tuple:
    n = node.node
    if n is None:
        return (None,)
    return (
        tuple(sorted((k, v) for k, v in n.labels.items() if k in label_keys)),
        tuple(sorted(repr(t) for t in n.taints)),
        bool(n.unschedulable),
    )


_EMPTY_PORTS: frozenset[int] = frozenset()


def _task_ports(task: TaskInfo) -> frozenset[int]:
    cs = task.pod.containers
    if len(cs) == 1 and not cs[0].ports:
        return _EMPTY_PORTS  # fast path: single portless container
    return frozenset(p for c in cs for p in c.ports)


@dataclass
class EncodedSnapshot:
    """The dense snapshot + the host-side metadata needed to decode the
    kernel's assignment back into session mutations."""

    scalar_names: tuple[str, ...]
    tasks: list[TaskInfo]  # row order (contiguous per job)
    jobs: list[JobInfo]  # row order
    queues: list[QueueInfo]  # row order
    node_names: list[str]  # row order (sorted, = utils.get_node_list order)
    n_tasks: int
    n_nodes: int
    n_jobs: int
    n_queues: int
    host_only: list[TaskInfo] = field(default_factory=list)
    arrays: dict = field(default_factory=dict)
    # pod-affinity terms present somewhere in the snapshot: interpod
    # scores are live (arrays["pod_sc"] nonzero-able, refreshed by the
    # action after each host-stepped placement)
    interpod_active: bool = False
    task_reps: list[TaskInfo] = field(default_factory=list)  # group reps

    @property
    def has_host_only(self) -> bool:
        return bool(self.host_only)


def compute_pod_sc(
    task_reps: Sequence[TaskInfo],
    nodes: dict[str, NodeInfo],
    node_names: Sequence[str],
    n_pad: int,
    dtype,
) -> np.ndarray:
    """[GT, N] InterPodAffinity score matrix — one normalized 0..10 row
    per task group against the *current* residents. Exact for every task
    whose group rep shares its labels + affinity spec (the group
    signature guarantees that when interpod is active)."""
    from kube_batch_tpu.plugins.nodeorder import interpod_affinity_scores

    out = np.zeros((max(len(task_reps), 1), n_pad), dtype)
    for gi, rep in enumerate(task_reps):
        scores = interpod_affinity_scores(rep, nodes)
        out[gi, : len(node_names)] = [scores[name] for name in node_names]
    return out


def _collect_task_scalar_names(tasks: Sequence[TaskInfo]) -> frozenset[str]:
    names: set[str] = set()
    for t in tasks:
        # guard: the overwhelmingly common scalar-less resource avoids
        # a set.update call per task (2 x 50k calls on the 50k path)
        if t.resreq.scalars:
            names.update(t.resreq.scalars)
        if t.init_resreq.scalars:
            names.update(t.init_resreq.scalars)
    return frozenset(names)


def _collect_node_scalar_names(nodes: Sequence[NodeInfo]) -> set[str]:
    names: set[str] = set()
    for n in nodes:
        if n.idle.scalars:
            names.update(n.idle.scalars)
        if n.releasing.scalars:
            names.update(n.releasing.scalars)
        if n.allocatable.scalars:
            names.update(n.allocatable.scalars)
        if n.used.scalars:
            names.update(n.used.scalars)
    return names


def _collect_scalar_names(
    tasks: Sequence[TaskInfo], nodes: Sequence[NodeInfo]
) -> tuple[str, ...]:
    return tuple(
        sorted(_collect_task_scalar_names(tasks) | _collect_node_scalar_names(nodes))
    )


def _node_static_values(n: NodeInfo) -> tuple[bool, int]:
    """(schedulable-verdict, max_task_num) — the per-node fields that are
    pure in the Node object (condition/pressure read node.conditions,
    max_task_num the allocatable pod count), i.e. identity-cacheable."""
    return (
        n.node is not None
        and check_node_condition(n.node)
        and check_pressure(n.node),
        n.allocatable.max_task_num,
    )


def _pair_values(trep: TaskInfo, nrep: NodeInfo) -> tuple[bool, float]:
    """One (task-group, node-group) cell of the static products — the
    pair-memo compute twin of `build_static_compat`'s fused sweep. Pure
    in the two group signatures (the same property the group dedup
    itself relies on), which is what makes cross-cycle reuse sound."""
    if nrep.node is None:
        return False, 0.0
    from kube_batch_tpu.plugins.nodeorder import node_affinity_score

    return (
        static_pod_node_compat(trep.pod, nrep.node),
        node_affinity_score(trep, nrep),
    )


def _dims_mask(res: Resource, scalar_names: Sequence[str]) -> list[bool]:
    """Which vector slots `res.resource_names()` would iterate: cpu and
    memory always, scalar slots only when the key is present in the
    scalar map (share()/LessEqual walk map keys — Go nil/absent-key
    semantics, resource_info.go:255-278, helpers.go:43-60)."""
    return [True, True, *(n in res.scalars for n in scalar_names)]


def _build_task_side(shortlist):
    """The encode's task side: per-job pending extraction + pop-order
    sort + plain-task classification (one native pass when available —
    "plain" = no selector/affinity/tolerations/volumes/ports, so every
    later per-task pass can skip the row), row layout, host-only
    routing, and referenced-label-key collection. Split out so the
    encode cache can reuse the whole product for an unmutated session."""
    collected = None
    if _native is not None:
        from kube_batch_tpu.api.resource_info import (
            MIN_MEMORY,
            MIN_MILLI_CPU,
            MIN_MILLI_SCALAR,
        )

        try:
            collected = _native.collect_pending(
                shortlist,
                TaskStatus.PENDING,
                float(MIN_MILLI_CPU),
                float(MIN_MEMORY),
                float(MIN_MILLI_SCALAR),
            )
        except Exception:  # noqa: BLE001 -- fall back to the Python pass
            _log_native_fallback("collect_pending")

    job_list: list[JobInfo] = []
    job_pending: dict[str, tuple[list[TaskInfo], Optional[bytes]]] = {}
    if collected is not None:
        for job, (pending, flags) in zip(shortlist, collected):
            if pending:
                job_list.append(job)
                job_pending[job.uid] = (pending, flags)
    else:
        for job in shortlist:
            pending = [
                t
                for t in job.task_status_index.get(TaskStatus.PENDING, {}).values()
                if not t.resreq.is_empty()
            ]
            if pending:
                # Within-job pop order = priority desc, creation, uid
                # (priority plugin task_order_fn + session fallback,
                # session_plugins.go:329-341). The native pass pre-sorts.
                pending.sort(
                    key=lambda t: (
                        -t.priority,
                        t.pod.metadata.creation_timestamp,
                        t.uid,
                    )
                )
                job_list.append(job)
                job_pending[job.uid] = (pending, None)
    # Stable row order = the serial job heap's fallback order (creation,
    # uid). Dynamic ordering (priority/ready/drf share) is decided by the
    # kernel's selection keys, with this row order as the final key.
    job_list.sort(key=lambda j: (j.creation_timestamp, j.uid))
    job_idx = {j.uid: i for i, j in enumerate(job_list)}

    task_list: list[TaskInfo] = []
    task_plain = bytearray()  # parallel row flags (native-classified)
    host_only: list[TaskInfo] = []
    job_ranges: list[tuple[int, int]] = []
    host_only_rows: list[int] = []
    # Label keys the pending tasks' selectors / node-affinity terms can
    # actually read, collected inline (one pass instead of a separate
    # _referenced_label_keys sweep). Node signatures project labels onto
    # this set so per-node unique labels (hostname et al) do not defeat
    # node-group deduplication (ADVICE r2: encode.py finding).
    ref_label_keys: set[str] = set()
    for job in job_list:
        pending, flags = job_pending[job.uid]
        start = len(task_list)
        if flags is not None and flags.count(0) == 0:
            # whole job plain: no selector/affinity/volume/port rows
            task_list.extend(pending)
            task_plain.extend(flags)
            job_ranges.append((start, len(task_list)))
            continue
        for off, t in enumerate(pending):
            if flags is not None and flags[off]:
                task_plain.append(1)
                task_list.append(t)
                continue
            task_plain.append(0)
            pod = t.pod
            if pod.node_selector:
                ref_label_keys.update(pod.node_selector)
            aff = pod.affinity
            if aff is not None:
                for term in aff.node_affinity_required:
                    ref_label_keys.add(term.key)
                for _, term in aff.node_affinity_preferred:
                    ref_label_keys.add(term.key)
            if aff is not None and aff.has_pod_affinity_terms():
                # required terms gate feasibility pairwise; preferred terms
                # change *other* tasks' scores once this pod lands (the
                # symmetric InterPodAffinity half) — both must be stepped
                # host-side against the live session
                host_only.append(t)
                host_only_rows.append(len(task_list))
            elif pod.volumes:
                # claims need the volume binder's assume step (PV
                # topology, capacity, class matching) against live PVC/PV
                # state — serial-stepped host-side like the reference's
                # AssumePodVolumes inside ssn.Allocate (session.go:241-260)
                host_only.append(t)
                host_only_rows.append(len(task_list))
            task_list.append(t)
        job_ranges.append((start, len(task_list)))
    return (
        job_list, job_idx, task_list, task_plain, host_only,
        job_ranges, host_only_rows, ref_label_keys,
    )


def encode_session(
    jobs: dict[str, JobInfo],
    nodes: dict[str, NodeInfo],
    queues: dict[str, QueueInfo],
    dtype=np.float64,
    pad: bool = True,
    drf=None,
    proportion=None,
    session=None,
    resident_interpod=None,
) -> EncodedSnapshot:
    """Build the SoA snapshot for one allocate solve.

    Job/task eligibility mirrors the serial allocate action exactly
    (reference allocate.go:48-70,120-125): Pending-phase PodGroups wait
    for enqueue, jobs of unknown queues are skipped, BestEffort
    (empty-resreq) tasks are backfill's business.

    ``drf`` / ``proportion`` are the session's live plugin instances (or
    None when the conf does not enable them); their open-session state is
    copied verbatim so kernel share arithmetic starts from the exact
    serial floats.

    ``session`` (optional) scopes the cross-cycle encode cache's
    whole-block reuse (ops/encode_cache.py, ``KBT_ENCODE_CACHE``): with
    it, an encode of an unmutated session (``state_seq`` unchanged)
    reuses the previous encode's task-side products wholesale, and any
    encode reuses per-object signatures / group-pair products validated
    by API-object identity. Warm output is byte-identical to cold by
    construction — every reused value is the value this function would
    recompute.

    ``resident_interpod`` (optional) short-circuits the O(resident-pods)
    affinity sweep over every node's task map: a streaming micro-cycle
    (kube_batch_tpu.streaming) passes the last full cycle's
    ``interpod_active`` verdict for the resident side, and only the
    micro-session's own pending/host-only tasks are swept. Passing True
    when no resident pod has affinity terms costs score work but never
    correctness; the reverse is prevented by the caller (external
    bound-pod churn invalidates the resident base entirely).
    """
    from kube_batch_tpu.ops import encode_cache as _encode_cache

    ec = _encode_cache.active()
    if ec is not None:
        ec.begin_encode()

    node_list = [nodes[name] for name in sorted(nodes)]
    queue_list = sorted(
        queues.values(), key=lambda q: (q.queue.metadata.creation_timestamp, q.uid)
    )
    queue_idx = {q.name: i for i, q in enumerate(queue_list)}

    shortlist: list[JobInfo] = []
    for job in jobs.values():
        if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
            continue
        if job.queue not in queues:
            continue
        shortlist.append(job)

    # Cross-cycle task-block reuse: the whole task side of the previous
    # encode is valid while the session is unmutated (state_seq) and the
    # job/queue objects are identical — the steady-state warm cycle.
    tb = (
        ec.lookup_task_block(session, shortlist, queues, dtype, pad)
        if ec is not None
        else None
    )
    if tb is not None:
        job_list = tb.job_list
        job_idx = tb.job_idx
        task_list = tb.task_list
        task_plain = tb.task_plain
        host_only = tb.host_only
        job_ranges = tb.job_ranges
        host_only_rows = tb.host_only_rows
        ref_label_keys = tb.ref_label_keys
    else:
        (
            job_list, job_idx, task_list, task_plain, host_only,
            job_ranges, host_only_rows, ref_label_keys,
        ) = _build_task_side(shortlist)
        if ec is not None:
            tb = ec.store_task_block(
                session, shortlist, queues, dtype, pad,
                job_list=job_list, job_idx=job_idx, task_list=task_list,
                task_plain=task_plain, host_only=host_only,
                job_ranges=job_ranges, host_only_rows=host_only_rows,
                ref_label_keys=ref_label_keys,
            )

    # InterPodAffinity activation: any pod-affinity terms anywhere (pending
    # or resident) make nodeorder's interpod score nonzero-able; the score
    # is per *node* (it reads each node's residents), so it rides its own
    # [GT, N] matrix rather than the node-group-level aff_sc. Volume-only
    # host_only tasks do NOT activate it — claims change no scores.
    interpod_active = any(
        t.pod.affinity is not None and t.pod.affinity.has_pod_affinity_terms()
        for t in host_only
    ) or (
        bool(resident_interpod)
        if resident_interpod is not None
        else any(
            rt.pod.affinity is not None and rt.pod.affinity.has_pod_affinity_terms()
            for n in node_list
            for rt in n.tasks.values()
        )
    )

    if tb is not None and tb.scalar_task_names is not None:
        t_scalars = tb.scalar_task_names
    else:
        t_scalars = _collect_task_scalar_names(task_list)
        if tb is not None:
            tb.scalar_task_names = t_scalars
    scalar_names = tuple(sorted(t_scalars | _collect_node_scalar_names(node_list)))
    R = 2 + len(scalar_names)
    t_n, n_n, j_n, q_n = len(task_list), len(node_list), len(job_list), len(queue_list)
    T = _bucket(t_n) if pad else max(t_n, 1)
    N = _node_bucket(n_n) if pad else max(n_n, 1)
    J = _bucket(j_n, 4) if pad else max(j_n, 1)
    Q = _bucket(q_n, 2) if pad else max(q_n, 1)

    # -- ports ---------------------------------------------------------------
    # plain rows have no ports by classification, so only the non-plain
    # rows can contribute (flag shortcuts apply whenever the native
    # collect pass classified; otherwise every row is scanned)
    if tb is not None and tb.interesting_ports is not None:
        interesting_ports = tb.interesting_ports
    else:
        interesting_ports = sorted(
            {
                p
                for i, t in enumerate(task_list)
                if not task_plain[i]
                for p in _task_ports(t)
            }
        )
        if tb is not None:
            tb.interesting_ports = interesting_ports
    port_idx = {p: i for i, p in enumerate(interesting_ports)}
    P = max(len(interesting_ports), 1)

    # -- predicate / affinity groups ----------------------------------------
    label_keys = frozenset(ref_label_keys)
    grouping = tb.groupings.get(interpod_active) if tb is not None else None
    if grouping is not None:
        task_gid, t_reps, t_rep_sigs = grouping
    else:
        t_groups: dict[tuple, int] = {}
        task_gid = np.zeros(T, np.int32)
        t_reps: list[TaskInfo] = []
        t_rep_sigs: list[tuple] = []
        if interpod_active:
            # signatures read pod labels: no plain-row shortcut (a plain pod
            # with labels is a distinct group under InterPodAffinity)
            for i, t in enumerate(task_list):
                sig = (
                    ec.task_sig(t, True, _task_signature)
                    if ec is not None
                    else _task_signature(t, with_labels=True)
                )
                if sig not in t_groups:
                    t_groups[sig] = len(t_reps)
                    t_reps.append(t)
                    t_rep_sigs.append(sig)
                task_gid[i] = t_groups[sig]
        else:
            for i, t in enumerate(task_list):
                if task_plain[i]:
                    sig = _PLAIN_SIG
                elif ec is not None:
                    sig = ec.task_sig(t, False, _task_signature)
                else:
                    sig = _task_signature(t)
                if sig not in t_groups:
                    t_groups[sig] = len(t_reps)
                    t_reps.append(t)
                    t_rep_sigs.append(sig)
                task_gid[i] = t_groups[sig]
        if tb is not None:
            tb.groupings[interpod_active] = (task_gid, t_reps, t_rep_sigs)
    ec_node_entries = None
    if ec is not None:
        # per-node memo (identity-validated: signature + static
        # verdicts in one touch) + first-occurrence regroup — the
        # regroup is O(N) dict ops; only churned nodes recompute
        ec_node_entries = [
            ec.node_row(n, label_keys, _node_signature, _node_static_values)
            for n in node_list
        ]
        n_groups: dict[tuple, int] = {}
        node_gids = np.zeros(len(node_list), np.int32)
        n_reps = []
        n_rep_sigs = []
        for i, e in enumerate(ec_node_entries):
            sig = e.sig
            gid = n_groups.get(sig)
            if gid is None:
                gid = n_groups[sig] = len(n_reps)
                n_reps.append(node_list[i])
                n_rep_sigs.append(sig)
            node_gids[i] = gid
    else:
        node_gids, n_reps = group_by_signature(
            node_list, lambda n: _node_signature(n, label_keys)
        )
    node_gid = np.zeros(N, np.int32)
    node_gid[: len(node_gids)] = node_gids
    GT, GN = max(len(t_reps), 1), max(len(n_reps), 1)
    aff_sc = np.zeros((GT, GN), dtype)
    if ec is not None:
        # (task-group x node-group) products via the cross-cycle pair
        # memo: unchanged pairs are reused verbatim, new pairs compute
        # exactly what build_static_compat would
        compat = np.zeros((GT, GN), bool)
        for gi, trep in enumerate(t_reps):
            tsig = t_rep_sigs[gi]
            for gj, nrep in enumerate(n_reps):
                c, s = ec.pair(
                    tsig,
                    n_rep_sigs[gj],
                    lambda trep=trep, nrep=nrep: _pair_values(trep, nrep),
                )
                compat[gi, gj] = c
                aff_sc[gi, gj] = s
    else:
        compat = build_static_compat(t_reps, n_reps, aff_sc=aff_sc)

    # -- task arrays (bulk-filled: one ndarray conversion, not 50k row
    #    assignments — encode_s is on the session critical path; on a
    #    warm task-block the whole dense bundle is reused verbatim —
    #    its inputs are exactly the block's identity-validated tasks) --
    arrays_key = (scalar_names, tuple(interesting_ports))
    cached = (
        tb.arrays
        if tb is not None and tb.arrays is not None and tb.arrays_key == arrays_key
        else None
    )
    if cached is not None:
        (
            task_req, task_res, task_job, task_has_sc, task_res_has_sc,
            task_host_only, task_ports, task_created,
            job_start, job_end, job_min, job_ready0, job_prio, job_rank,
            job_queue, job_valid,
        ) = cached
    else:
        task_req = np.zeros((T, R), dtype)
        task_res = np.zeros((T, R), dtype)
        task_job = np.zeros(T, np.int32)
        task_has_sc = np.zeros(T, bool)
        task_res_has_sc = np.zeros(T, bool)
        task_host_only = np.zeros(T, bool)
        task_ports = np.zeros((T, P), bool)
        filled = False
        if t_n and not scalar_names and _native is not None:
            # native single pass: req/res cpu+mem columns, job row index,
            # scalar-presence flags (kube_batch_tpu/native extract_task_columns)
            try:
                _native.extract_task_columns(
                    task_list, job_idx, task_req, task_res,
                    task_job, task_has_sc, task_res_has_sc,
                )
                filled = True
            except Exception:  # noqa: BLE001 -- fall back to the numpy passes
                _log_native_fallback("extract_task_columns")
        if t_n and not filled:
            if scalar_names:
                task_req[:t_n] = np.asarray(
                    [t.init_resreq.to_vector(scalar_names) for t in task_list], dtype
                )
                task_res[:t_n] = np.asarray(
                    [t.resreq.to_vector(scalar_names) for t in task_list], dtype
                )
            else:
                # column-wise fromiter: one C loop per column, no 50k tuple
                # objects + list->ndarray conversion on the critical path
                task_req[:t_n, 0] = np.fromiter(
                    (t.init_resreq.milli_cpu for t in task_list), dtype, count=t_n
                )
                task_req[:t_n, 1] = np.fromiter(
                    (t.init_resreq.memory for t in task_list), dtype, count=t_n
                )
                task_res[:t_n, 0] = np.fromiter(
                    (t.resreq.milli_cpu for t in task_list), dtype, count=t_n
                )
                task_res[:t_n, 1] = np.fromiter(
                    (t.resreq.memory for t in task_list), dtype, count=t_n
                )
            task_job[:t_n] = np.fromiter(
                (job_idx[t.job] for t in task_list), np.int32, count=t_n
            )
            task_has_sc[:t_n] = np.fromiter(
                (bool(t.init_resreq.scalars) for t in task_list), bool, count=t_n
            )
            task_res_has_sc[:t_n] = np.fromiter(
                (bool(t.resreq.scalars) for t in task_list), bool, count=t_n
            )
        if t_n:
            if interesting_ports:
                for i, t in enumerate(task_list):
                    if task_plain[i]:
                        continue
                    for p in _task_ports(t):
                        task_ports[i, port_idx[p]] = True
        task_host_only[host_only_rows] = True
        # per-row pod creation timestamp: the replay's dispatch-latency
        # metric gathers from this instead of a per-task Python pass
        task_created = np.zeros(T)
        if t_n:
            task_created[:t_n] = np.fromiter(
                (t.pod.metadata.creation_timestamp for t in task_list),
                np.float64, count=t_n,
            )

        # -- job arrays (cached with the task bundle: inputs are the
        #    block's job_list/job_ranges + queue order + state_seq) ----
        job_start = np.zeros(J, np.int32)
        job_end = np.zeros(J, np.int32)
        job_min = np.zeros(J, np.int32)
        job_ready0 = np.zeros(J, np.int32)
        job_prio = np.zeros(J, np.int32)
        job_rank = np.zeros(J, np.int32)
        job_queue = np.zeros(J, np.int32)
        job_valid = np.zeros(J, bool)
        for i, j in enumerate(job_list):
            job_start[i], job_end[i] = job_ranges[i]
            job_min[i] = j.min_available
            job_ready0[i] = j.ready_task_num()
            job_prio[i] = j.priority
            job_rank[i] = i  # job_list pre-sorted by (creation, uid)
            job_queue[i] = queue_idx[j.queue]
            job_valid[i] = True
        if tb is not None:
            tb.arrays_key = arrays_key
            tb.arrays = (
                task_req, task_res, task_job, task_has_sc, task_res_has_sc,
                task_host_only, task_ports, task_created,
                job_start, job_end, job_min, job_ready0, job_prio, job_rank,
                job_queue, job_valid,
            )

    # -- node arrays ---------------------------------------------------------
    node_idle = np.zeros((N, R), dtype)
    node_rel = np.zeros((N, R), dtype)
    node_used = np.zeros((N, R), dtype)
    node_alloc = np.zeros((N, R), dtype)
    node_ok = np.zeros(N, bool)
    node_valid = np.zeros(N, bool)
    node_max_tasks = np.zeros(N, np.int32)
    node_ntasks = np.zeros(N, np.int32)
    node_idle_has_sc = np.zeros(N, bool)
    node_rel_has_sc = np.zeros(N, bool)
    node_ports = np.zeros((N, P), bool)
    node_vecs_filled = False
    if n_n and not scalar_names and _native is not None:
        # native pass over the 4 per-node resource vectors (cpu+mem)
        stacked = np.zeros((4, N, R), dtype)
        try:
            _native.extract_node_columns(
                node_list, ("idle", "releasing", "used", "allocatable"), stacked
            )
            node_idle, node_rel, node_used, node_alloc = (
                np.ascontiguousarray(stacked[0]),
                np.ascontiguousarray(stacked[1]),
                np.ascontiguousarray(stacked[2]),
                np.ascontiguousarray(stacked[3]),
            )
            node_vecs_filled = True
        except Exception:  # noqa: BLE001 -- fall back to to_vector rows
            _log_native_fallback("extract_node_columns")
    if not node_vecs_filled:
        for i, n in enumerate(node_list):
            node_idle[i] = n.idle.to_vector(scalar_names)
            node_rel[i] = n.releasing.to_vector(scalar_names)
            node_used[i] = n.used.to_vector(scalar_names)
            node_alloc[i] = n.allocatable.to_vector(scalar_names)
    # node statics (condition/pressure verdict, max_task_num) reuse per
    # Node-object identity; the dynamic residency columns (ntasks,
    # has_sc, ports) re-gather every cycle because binds move them
    if ec_node_entries is not None and n_n:
        for i, e in enumerate(ec_node_entries):
            node_ok[i] = e.ok
            node_max_tasks[i] = e.max_tasks
    elif n_n:
        node_ok[:n_n] = np.fromiter(
            (_node_static_values(n)[0] for n in node_list), bool, count=n_n
        )
        node_max_tasks[:n_n] = np.fromiter(
            (n.allocatable.max_task_num for n in node_list), np.int32, count=n_n
        )
    if n_n:
        node_valid[:n_n] = True
        node_ntasks[:n_n] = np.fromiter(
            (len(n.tasks) for n in node_list), np.int32, count=n_n
        )
        node_idle_has_sc[:n_n] = np.fromiter(
            (bool(n.idle.scalars) for n in node_list), bool, count=n_n
        )
        node_rel_has_sc[:n_n] = np.fromiter(
            (bool(n.releasing.scalars) for n in node_list), bool, count=n_n
        )
    if interesting_ports:
        # only pending tasks' ports matter; with none in play the whole
        # resident sweep is skippable (port_idx gates every write anyway)
        for i, n in enumerate(node_list):
            for task in n.tasks.values():
                for p in _task_ports(task):
                    if p in port_idx:
                        node_ports[i, port_idx[p]] = True

    queue_rank = np.arange(Q, dtype=np.int32)  # queue_list pre-sorted

    # -- drf / proportion session state (plugin-exact floats) ---------------
    job_alloc0 = np.zeros((J, R), dtype)
    drf_total = np.zeros(R, dtype)
    drf_dims = np.zeros(R, bool)
    if drf is not None:
        drf_total[:] = drf.total_resource.to_vector(scalar_names)
        drf_dims[:] = _dims_mask(drf.total_resource, scalar_names)
        for i, j in enumerate(job_list):
            attr = drf.job_attrs.get(j.uid)
            if attr is not None:
                job_alloc0[i] = attr.allocated.to_vector(scalar_names)

    q_alloc0 = np.zeros((Q, R), dtype)
    q_deserved = np.zeros((Q, R), dtype)
    q_dims = np.zeros((Q, R), bool)
    q_alloc_has_sc0 = np.zeros(Q, bool)
    if proportion is not None:
        for i, q in enumerate(queue_list):
            attr = proportion.queue_attrs.get(q.name)
            if attr is None:
                continue  # queue with no jobs: never selected by the kernel
            q_alloc0[i] = attr.allocated.to_vector(scalar_names)
            q_deserved[i] = attr.deserved.to_vector(scalar_names)
            q_dims[i] = _dims_mask(attr.deserved, scalar_names)
            q_alloc_has_sc0[i] = bool(attr.allocated.scalars)

    eps = np.asarray(Resource.vector_epsilons(scalar_names), dtype)

    if interpod_active:
        pod_sc = compute_pod_sc(t_reps, nodes, [n.name for n in node_list], N, dtype)
    else:
        pod_sc = np.zeros((GT, N), dtype)

    if ec is not None:
        ec.end_encode()

    return EncodedSnapshot(
        scalar_names=scalar_names,
        tasks=task_list,
        jobs=job_list,
        queues=queue_list,
        node_names=[n.name for n in node_list],
        n_tasks=t_n,
        n_nodes=n_n,
        n_jobs=j_n,
        n_queues=q_n,
        host_only=host_only,
        interpod_active=interpod_active,
        task_reps=t_reps,
        arrays=dict(
            task_req=task_req,
            task_res=task_res,
            task_created=task_created,
            task_job=task_job,
            task_gid=task_gid,
            task_has_sc=task_has_sc,
            task_res_has_sc=task_res_has_sc,
            task_host_only=task_host_only,
            task_ports=task_ports,
            node_idle=node_idle,
            node_rel=node_rel,
            node_used=node_used,
            node_alloc=node_alloc,
            node_ok=node_ok,
            node_valid=node_valid,
            node_max_tasks=node_max_tasks,
            node_ntasks=node_ntasks,
            node_idle_has_sc=node_idle_has_sc,
            node_rel_has_sc=node_rel_has_sc,
            node_gid=node_gid,
            node_ports=node_ports,
            compat=compat,
            aff_sc=aff_sc,
            pod_sc=pod_sc,
            job_start=job_start,
            job_end=job_end,
            job_min=job_min,
            job_ready0=job_ready0,
            job_prio=job_prio,
            job_rank=job_rank,
            job_queue=job_queue,
            job_valid=job_valid,
            queue_rank=queue_rank,
            job_alloc0=job_alloc0,
            drf_total=drf_total,
            drf_dims=drf_dims,
            q_alloc0=q_alloc0,
            q_deserved=q_deserved,
            q_dims=q_dims,
            q_alloc_has_sc0=q_alloc_has_sc0,
            eps=eps,
        ),
    )
