"""In-process scheduling metrics (reference pkg/scheduler/metrics/metrics.go:38-121).

The reference registers Prometheus collectors under subsystem "volcano":
e2e/action/plugin/task latency histograms, schedule attempts, preemption
victims/attempts, unschedulable task/job gauges, job retries. This module
keeps the same metric set in-process (no client library dependency) and
renders Prometheus text exposition for the server's /metrics endpoint.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Iterable, Optional

# Buckets: 5ms * 2^k for e2e (metrics.go:41-44), 5us * 2^k for the rest
# (metrics.go:49-72). Values recorded in seconds.
E2E_BUCKETS = tuple(0.005 * 2**k for k in range(12))
FINE_BUCKETS = tuple(5e-6 * 2**k for k in range(18))

# OpenMetrics exemplars: when armed, observations that pass an
# ``exemplar=`` trace id keep the latest one per label set and the
# exposition appends ``# {trace_id="..."} value`` to the matching
# bucket/sample line — a p99 outlier on /metrics then links straight to
# its flight-recorder trace. Off by default: exemplar storage is the
# only cost, and the golden exposition stays byte-stable.
EXEMPLARS_ENV = "KBT_METRICS_EXEMPLARS"
_EXEMPLAR_OFF = ("", "0", "false", "off", "no")


def exemplars_enabled() -> bool:
    return os.environ.get(EXEMPLARS_ENV, "").strip().lower() not in _EXEMPLAR_OFF


class Histogram:
    """Labeled histogram vector (one bucket series per label set, like the
    reference's prometheus HistogramVec)."""

    def __init__(self, name: str, help_text: str, buckets: Iterable[float]) -> None:
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        # label tuple -> [counts per bucket + overflow, sum, total]
        self._series: dict[tuple, list] = {}
        # label tuple -> (trace_id, value) — latest exemplar per series
        self._exemplars: dict[tuple, tuple[str, float]] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[dict[str, str]]) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def observe(
        self,
        value: float,
        labels: Optional[dict[str, str]] = None,
        exemplar: str | None = None,
    ) -> None:
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._series[key] = series
            counts, _, _ = series
            series[1] += value
            series[2] += 1
            if exemplar and exemplars_enabled():
                self._exemplars[key] = (exemplar, value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def observe_many(self, values, labels: Optional[dict[str, str]] = None) -> None:
        """Batch observe: one lock acquisition for a whole list of values —
        identical bucket counts/sum/total to calling observe per value.
        ndarray input takes a vectorized path (searchsorted + bincount);
        a 100k-bind gang dispatch feeds its whole latency vector here."""
        import numpy as _np

        if isinstance(values, _np.ndarray):
            if values.size == 0:
                return
            buckets = self.buckets
            nb = len(buckets)
            # bisect_left == searchsorted side='left': first bucket with
            # v <= bound (bucket bounds are inclusive upper edges)
            idx = _np.searchsorted(_np.asarray(buckets), values, side="left")
            add = _np.bincount(_np.minimum(idx, nb), minlength=nb + 1)
            key = self._key(labels)
            with self._lock:
                series = self._series.get(key)
                if series is None:
                    series = [[0] * (nb + 1), 0.0, 0]
                    self._series[key] = series
                counts = series[0]
                for i, c in enumerate(add.tolist()):
                    counts[i] += c
                series[1] += float(values.sum())
                series[2] += int(values.size)
            return
        values = list(values)
        if not values:
            return
        from bisect import bisect_left

        buckets = self.buckets
        nb = len(buckets)
        key = self._key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = [[0] * (nb + 1), 0.0, 0]
                self._series[key] = series
            counts = series[0]
            for v in values:
                i = bisect_left(buckets, v)  # first bucket with v <= bound
                counts[i if i < nb else nb] += 1
            series[1] += sum(values)
            series[2] += len(values)

    def snapshot(self, labels: Optional[dict[str, str]] = None) -> dict:
        """Cumulative bucket counts for one label set (default: the sum
        over all label sets)."""
        with self._lock:
            if labels is None:
                merged = [0] * (len(self.buckets) + 1)
                total_sum, total = 0.0, 0
                for counts, s, n in self._series.values():
                    for i, c in enumerate(counts):
                        merged[i] += c
                    total_sum += s
                    total += n
            else:
                counts, total_sum, total = self._series.get(
                    self._key(labels), [[0] * (len(self.buckets) + 1), 0.0, 0]
                )
                merged = list(counts)
            cumulative = []
            running = 0
            for c in merged[:-1]:
                running += c
                cumulative.append(running)
            return {
                "buckets": dict(zip(self.buckets, cumulative)),
                "sum": total_sum,
                "count": total,
            }

    def label_sets(self) -> list[tuple]:
        with self._lock:
            return list(self._series)

    def quantile(self, q: float, labels: Optional[dict[str, str]] = None) -> float:
        """Approximate quantile from bucket boundaries (reference extracts
        p50/p90/p99 the same way in test/e2e/metric_util.go:45-68)."""
        snap = self.snapshot(labels)
        if snap["count"] == 0:
            return 0.0
        target = math.ceil(q * snap["count"])
        for boundary, cum in snap["buckets"].items():
            if cum >= target:
                return boundary
        return float("inf")


class Counter:
    def __init__(self, name: str, help_text: str) -> None:
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = {}
        self._exemplars: dict[tuple, tuple[str, float]] = {}
        self._lock = threading.Lock()

    def inc(
        self,
        labels: Optional[dict[str, str]] = None,
        by: float = 1.0,
        exemplar: str | None = None,
    ) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by
            if exemplar and exemplars_enabled():
                self._exemplars[key] = (exemplar, by)

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> dict[tuple, float]:
        """All label sets with their values (the fleet scrape unit)."""
        with self._lock:
            return dict(self._values)


class Gauge:
    def __init__(self, name: str, help_text: str) -> None:
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, labels: Optional[dict[str, str]] = None) -> None:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            self._values[key] = value

    def value(self, labels: Optional[dict[str, str]] = None) -> float:
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._values)

    def drop_labels(self, **match: str) -> int:
        """Remove every label set matching all given label=value pairs
        (SLO queue eviction must drop the gauge series too, or the
        cardinality bound would leak through the exposition)."""
        with self._lock:
            dead = [
                k for k in self._values
                if all(dict(k).get(a) == b for a, b in match.items())
            ]
            for k in dead:
                del self._values[k]
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


_SUBSYSTEM = "kube_batch_tpu"

e2e_scheduling_latency = Histogram(
    f"{_SUBSYSTEM}_e2e_scheduling_latency", "E2E scheduling latency in seconds", E2E_BUCKETS
)
plugin_scheduling_latency = Histogram(
    f"{_SUBSYSTEM}_plugin_scheduling_latency", "Plugin scheduling latency in seconds", FINE_BUCKETS
)
action_scheduling_latency = Histogram(
    f"{_SUBSYSTEM}_action_scheduling_latency", "Action scheduling latency in seconds", FINE_BUCKETS
)
task_scheduling_latency = Histogram(
    f"{_SUBSYSTEM}_task_scheduling_latency", "Task scheduling latency in seconds", FINE_BUCKETS
)
schedule_attempts = Counter(
    f"{_SUBSYSTEM}_schedule_attempts_total",
    "Number of attempts to schedule pods, by result",
)
preemption_victims = Counter(
    f"{_SUBSYSTEM}_total_preemption_victims", "Number of selected preemption victims"
)
preemption_attempts = Counter(
    f"{_SUBSYSTEM}_total_preemption_attempts", "Total preemption attempts in the cluster"
)
unschedule_task_count = Gauge(
    f"{_SUBSYSTEM}_unschedule_task_count", "Number of tasks could not be scheduled"
)
unschedule_job_count = Gauge(
    f"{_SUBSYSTEM}_unschedule_job_count", "Number of jobs could not be scheduled"
)
job_retry_counts = Counter(f"{_SUBSYSTEM}_job_retry_counts", "Number of retry counts for one job")

# -- fault injection + degradation ladder (kube_batch_tpu.faults) ----------
fault_injections = Counter(
    f"{_SUBSYSTEM}_fault_injections_total", "Injected faults fired, by point"
)
breaker_transitions = Counter(
    f"{_SUBSYSTEM}_breaker_transitions_total",
    "Degradation-ladder circuit-breaker transitions, by tier and edge",
)
breaker_state = Gauge(
    f"{_SUBSYSTEM}_breaker_state",
    "Circuit-breaker state per solver tier (0=closed, 1=half_open, 2=open)",
)
degraded_cycles = Counter(
    f"{_SUBSYSTEM}_degraded_cycles_total",
    "Scheduling cycles that ran below their preferred solver tier, by reason",
)
write_retries = Counter(
    f"{_SUBSYSTEM}_write_retries_total",
    "Write-side retries (with jitter) before the errTasks resync path, by op",
)
cache_mutation_violations = Counter(
    f"{_SUBSYSTEM}_cache_mutation_violations_total",
    "In-place mutations of shared cached cluster objects detected, by kind",
)

# -- crash-consistent failover (kube_batch_tpu.recovery) --------------------
journal_records = Counter(
    f"{_SUBSYSTEM}_journal_records_total",
    "Write-intent journal records appended, by state (intent/confirm/append_failed)",
)
reconcile_ops = Counter(
    f"{_SUBSYSTEM}_reconcile_ops_total",
    "Takeover reconciliation outcomes, by op "
    "(confirmed/redispatched/conflict/rolled_back/aborted)",
)
cycle_overruns = Counter(
    f"{_SUBSYSTEM}_cycle_overruns_total",
    "Scheduling cycles past their deadline budget, by kind (soft/hard)",
)
resync_dropped = Counter(
    f"{_SUBSYSTEM}_resync_dropped_total",
    "errTasks resync entries dropped terminally after exhausting their retry budget",
)
stale_cycles_skipped = Counter(
    f"{_SUBSYSTEM}_stale_cycles_skipped_total",
    "Scheduling cycles refused because the snapshot exceeded the staleness threshold",
)
watch_snapshot_age = Gauge(
    f"{_SUBSYSTEM}_watch_snapshot_age_seconds",
    "Seconds since the watch-fed mirror was last known current (oldest kind)",
)
watch_relists = Counter(
    f"{_SUBSYSTEM}_watch_relists_total",
    "Full re-lists performed by watch clients after 410-Gone, by kind",
)

# -- incremental encode cache (kube_batch_tpu.ops.encode_cache) --------------
encode_cache_hits = Counter(
    f"{_SUBSYSTEM}_encode_cache_hits_total",
    "Encode-cache units (signatures, group pairs, blocks) reused verbatim",
)
encode_cache_invalidations = Counter(
    f"{_SUBSYSTEM}_encode_cache_invalidations_total",
    "Encode-cache invalidations, by reason (store kind / fault / capacity)",
)
encode_warm_fraction = Gauge(
    f"{_SUBSYSTEM}_encode_warm_fraction",
    "Fraction of the last encode's units served from the cross-cycle cache "
    "(0 = fully cold)",
)

# -- streaming scheduler (kube_batch_tpu.streaming) --------------------------
time_to_bind = Histogram(
    f"{_SUBSYSTEM}_time_to_bind_seconds",
    "Arrival-event to bind-ack latency per pod in seconds",
    E2E_BUCKETS,
)
micro_cycles = Counter(
    f"{_SUBSYSTEM}_micro_cycles_total",
    "Streaming micro-cycles run, by outcome "
    "(ok/empty/aborted/fault/stale/degraded)",
)
streaming_backlog = Gauge(
    f"{_SUBSYSTEM}_streaming_backlog_pods",
    "Pods arrived but not yet bound that streaming mode is tracking",
)

# -- sharded federation (kube_batch_tpu.federation, cache conditional writes) -
federation_conflicts = Counter(
    f"{_SUBSYSTEM}_federation_conflicts_total",
    "Optimistic-concurrency dispatch outcomes, by outcome "
    "(clean/won/retried/lost)",
)
federation_node_conflicts = Counter(
    f"{_SUBSYSTEM}_federation_node_conflicts_total",
    "Optimistic-concurrency bind conflicts attributed to the contended "
    "node, by node (the fleet heatmap's delta source)",
)
bind_retries = Counter(
    f"{_SUBSYSTEM}_bind_retries_total",
    "Gang bind transactions re-sent with a refreshed snapshot version "
    "after a store conflict",
)
store_backend_rtt = Histogram(
    f"{_SUBSYSTEM}_store_backend_rtt_seconds",
    "Store-backend round-trip latency per request in seconds, by op",
    FINE_BUCKETS,
)

# -- wire protocol v2 (cache/backend.py pooled transport) --------------------
# Power-of-two batch-size buckets: txn batches are small integers, not
# latencies, so the 5us-anchored FINE_BUCKETS would collapse them all
# into +Inf.
BATCH_BUCKETS = tuple(2.0**k for k in range(12))
store_backend_bytes = Counter(
    f"{_SUBSYSTEM}_store_backend_bytes_total",
    "Store-backend protocol bytes moved, by direction (tx/rx) and "
    "negotiated codec (json/binary)",
)
store_backend_txn_batch = Histogram(
    f"{_SUBSYSTEM}_store_backend_txn_batch_size",
    "Conditional-write transactions coalesced per /backend/v1/txn "
    "round trip",
    BATCH_BUCKETS,
)
backend_pool_in_use = Gauge(
    f"{_SUBSYSTEM}_backend_pool_in_use",
    "Persistent store-backend connections currently checked out of the "
    "keep-alive pool (KBT_BACKEND_POOL bounds the pool)",
)
watch_longpoll_wakeups = Counter(
    f"{_SUBSYSTEM}_watch_longpoll_wakeups_total",
    "Long-poll watch returns on the v2 combined endpoint, by cause "
    "(events/timeout)",
)

# -- leased shard slots (kube_batch_tpu.federation ShardSlotManager) ---------
# Dynamic shard ownership: each of the N shard slots is a store lease;
# a scheduler holds its primary slot, adopts orphaned ones, and hands
# slots off for planned moves/rebalancing.
shard_slots_owned = Gauge(
    f"{_SUBSYSTEM}_shard_slots_owned",
    "Shard slots this scheduler currently holds the lease for "
    "(1 = just the primary; more = adopted orphans)",
)
shard_slot_owned = Gauge(
    f"{_SUBSYSTEM}_shard_slot_owned",
    "Per-slot ownership flag for this scheduler (labels: slot; 1 = this "
    "process holds the slot's lease, 0 = it does not)",
)
shard_adoptions = Counter(
    f"{_SUBSYSTEM}_shard_adoptions_total",
    "Orphaned shard-slot adoption attempts, by outcome "
    "(adopted/failed/lost_race/flap_suppressed)",
)
shard_handoffs = Counter(
    f"{_SUBSYSTEM}_shard_handoffs_total",
    "Graceful shard-slot handoffs (planned moves / conflict rebalance), "
    "by outcome (completed/aborted)",
)
shard_takeover_seconds = Histogram(
    f"{_SUBSYSTEM}_shard_takeover_seconds",
    "Measured takeover time per adopted slot: lease acquire through "
    "journal reconciliation and backlog re-ingest, in seconds",
    E2E_BUCKETS,
)

# -- unschedulability forensics (kube_batch_tpu.obs.explain) -----------------
unschedulable_total = Counter(
    f"{_SUBSYSTEM}_unschedulable_total",
    "Gangs left unschedulable by an allocate cycle, by dominant reason "
    "(static/room/ports/resources/starved)",
)
would_fit_if_total = Counter(
    f"{_SUBSYSTEM}_would_fit_if_total",
    "Single-plane relaxations that would make an unschedulable gang "
    "feasible, by plane",
)

# -- pipelined cycles (kube_batch_tpu.pipeline, KBT_PIPELINE) ----------------
pipeline_overlap_fraction = Gauge(
    f"{_SUBSYSTEM}_pipeline_overlap_fraction",
    "Fraction of the last deferred dispatch that overlapped the next "
    "cycle's work (1.0 = fence never waited on, 0.0 = fully serialized)",
)
exchange_batched_iters_total = Counter(
    f"{_SUBSYSTEM}_exchange_batched_iters_total",
    "Gang iterations committed straight from a K-deep batched mesh "
    "exchange instead of a per-iteration all-gather",
)
pipeline_fence_wait_seconds = Histogram(
    f"{_SUBSYSTEM}_pipeline_fence_wait_seconds",
    "Time a cycle waited on the previous cycle's dispatch fence before "
    "taking its snapshot",
    FINE_BUCKETS,
)

# -- per-queue SLO windows (kube_batch_tpu.obs SLOAccountant) ----------------
# Sliding-window quantiles, refreshed by obs.slo.publish() at scrape
# time — unlike the cumulative histograms above, these answer "is queue
# Q meeting its SLO right now".
slo_time_to_bind = Gauge(
    f"{_SUBSYSTEM}_slo_time_to_bind_seconds",
    "Sliding-window time-to-bind quantiles per queue "
    "(labels: queue, quantile=p50/p90/p99)",
)
slo_queue_wait = Gauge(
    f"{_SUBSYSTEM}_slo_queue_wait_seconds",
    "Sliding-window pod-creation-to-dispatch wait quantiles per queue "
    "(labels: queue, quantile=p50/p90/p99)",
)
_SLO_GAUGES = {"time_to_bind": slo_time_to_bind, "queue_wait": slo_queue_wait}
slo_evicted_queues = Counter(
    f"{_SUBSYSTEM}_slo_evicted_queues_total",
    "Queues evicted from the SLO accountant's LRU cardinality bound "
    "(a tenant-name churn storm shows up here, not as unbounded labels)",
)

# -- fleet observatory (kube_batch_tpu.obs.fleet, KBT_FLEET) -----------------
# Cluster-wide rollups an aggregator computes by scraping peer shards'
# /debug/slo?raw=1 sketches and key counters, then merging — the only
# composable way to a fleet p99 (averaging per-shard percentiles is
# statistically wrong).
fleet_slo_time_to_bind = Gauge(
    f"{_SUBSYSTEM}_fleet_slo_time_to_bind_seconds",
    "Cluster-wide sliding-window time-to-bind quantiles merged from all "
    "scraped shards' sketches (labels: queue, quantile=p50/p90/p99)",
)
fleet_slo_queue_wait = Gauge(
    f"{_SUBSYSTEM}_fleet_slo_queue_wait_seconds",
    "Cluster-wide sliding-window queue-wait quantiles merged from all "
    "scraped shards' sketches (labels: queue, quantile=p50/p90/p99)",
)
_FLEET_SLO_GAUGES = {
    "time_to_bind": fleet_slo_time_to_bind,
    "queue_wait": fleet_slo_queue_wait,
}
fleet_node_conflicts = Gauge(
    f"{_SUBSYSTEM}_fleet_node_conflicts",
    "Per-node bind-conflict heatmap: top-K contended nodes by conflict "
    "delta since the previous fleet scrape, summed across shards (by node)",
)
fleet_backlog = Gauge(
    f"{_SUBSYSTEM}_fleet_backlog_pods",
    "Aggregate arrived-but-unbound backlog summed across scraped shards",
)
fleet_pods_per_second = Gauge(
    f"{_SUBSYSTEM}_fleet_pods_per_second",
    "Aggregate bind throughput across scraped shards, from bind-count "
    "deltas between fleet scrapes",
)
fleet_shards_scraped = Gauge(
    f"{_SUBSYSTEM}_fleet_shards_scraped",
    "Peer shards the fleet aggregator reached on its last scrape "
    "(a drop below the configured peer count means a dark shard)",
)
fleet_shard_up = Gauge(
    f"{_SUBSYSTEM}_fleet_shard_up",
    "Per-peer reachability on the last fleet scrape (labels: shard = "
    "peer URL; 1 = scraped, 0 = dark) — attributes a dark shard before "
    "its slot lease even expires",
)
fleet_shard_scrape_age = Gauge(
    f"{_SUBSYSTEM}_fleet_shard_last_scrape_age_seconds",
    "Seconds since the fleet aggregator last successfully scraped each "
    "peer (labels: shard = peer URL; grows without bound on a dark "
    "shard, -1 = never scraped)",
)

# -- admission control plane (kube_batch_tpu.admission, KBT_ADMISSION) -------
# Per-tenant lanes at the workload-API front door plus the backpressure
# controller that retunes them from measured fleet state. Decisions are
# counted, never silently dropped: every shed is visible here and carried
# a 429 + Retry-After on the wire.
admission_decisions = Counter(
    f"{_SUBSYSTEM}_admission_decisions_total",
    "Front-door admission decisions, by lane and outcome "
    "(admitted/shed_rate/shed_backlog/shed_brownout/shed_fault)",
)
admission_lane_backlog = Gauge(
    f"{_SUBSYSTEM}_admission_lane_backlog_pods",
    "Admitted-but-unbound pods the gate currently charges to each lane "
    "(labels: lane) — the bounded backlog that 429s when full",
)
admission_lane_rate = Gauge(
    f"{_SUBSYSTEM}_admission_lane_admit_rate",
    "Token-bucket refill rate in pods/s the controller currently grants "
    "each lane (labels: lane)",
)
admission_brownout_level = Gauge(
    f"{_SUBSYSTEM}_admission_brownout_level",
    "Current rung on the brownout ladder (0 = all lanes at configured "
    "rate; higher rungs defer lower-priority tiers first)",
)
admission_pressure = Gauge(
    f"{_SUBSYSTEM}_admission_pressure",
    "Composite overload signal the backpressure controller computed from "
    "merged fleet state (1.0 = at the configured SLO band ceiling)",
)
admission_controller_ticks = Counter(
    f"{_SUBSYSTEM}_admission_controller_ticks_total",
    "Backpressure controller evaluations, by outcome "
    "(steady/escalate/recover/fault/dark)",
)

# -- device-phase telemetry (arena HBM accounting, ops/encode_cache) ---------
arena_hbm_bytes = Gauge(
    f"{_SUBSYSTEM}_arena_hbm_bytes",
    "Device bytes currently held by the tensor arena, by slab",
)
arena_hbm_watermark = Gauge(
    f"{_SUBSYSTEM}_arena_hbm_watermark_bytes",
    "High watermark of total device bytes held by the tensor arena "
    "since process start (the bench's HBM column)",
)

# -- node-class compressed solve (ops/class_solve, KBT_CLASS_COMPRESS) -------
class_solve_classes = Gauge(
    f"{_SUBSYSTEM}_class_solve_classes",
    "Node equivalence classes at the last compressed solve's entry "
    "(the axis the solver actually scanned, padding excluded)",
)
class_solve_compression_ratio = Gauge(
    f"{_SUBSYSTEM}_class_solve_compression_ratio",
    "Valid nodes per valid node class at the last compressed solve's "
    "entry — the node-axis shrink factor; a sustained fall toward 1.0 "
    "means the fleet's shapes have diverged and compression is buying "
    "nothing",
)
class_table_splits = Counter(
    f"{_SUBSYSTEM}_class_table_splits_total",
    "Class-table member movements: in-solve bind splits (a chosen node "
    "leaves its class as a singleton) plus static re-keys from node "
    "churn (encode-cache dirty nodes re-hashed into new classes)",
)


def update_e2e_duration(seconds: float) -> None:
    e2e_scheduling_latency.observe(seconds)


def update_plugin_duration(plugin: str, phase: str, seconds: float) -> None:
    plugin_scheduling_latency.observe(seconds, {"plugin": plugin, "OnSession": phase})


def update_action_duration(action: str, seconds: float) -> None:
    action_scheduling_latency.observe(seconds, {"action": action})


def update_task_schedule_duration(seconds: float) -> None:
    task_scheduling_latency.observe(seconds)


def update_task_schedule_durations(seconds_list) -> None:
    """Batch form of update_task_schedule_duration (bulk gang dispatch)."""
    task_scheduling_latency.observe_many(seconds_list)


def update_preemption_victims_count(count: int) -> None:
    preemption_victims.inc(by=count)


def register_preemption_attempts() -> None:
    preemption_attempts.inc()


def update_unschedule_task_count(job_name: str, count: int) -> None:
    unschedule_task_count.set(count, {"job_id": job_name})


def update_unschedule_job_count(count: int) -> None:
    unschedule_job_count.set(count)


def register_job_retries(job_name: str) -> None:
    job_retry_counts.inc({"job_id": job_name})


def register_fault_injection(point: str) -> None:
    fault_injections.inc({"point": point})


def register_breaker_transition(tier: str, frm: str, to: str) -> None:
    breaker_transitions.inc({"tier": tier, "from": frm, "to": to})


def set_breaker_state(tier: str, value: float) -> None:
    breaker_state.set(value, {"tier": tier})


def register_degraded_cycle(tier: str, reason: str) -> None:
    degraded_cycles.inc({"tier": tier, "reason": reason})


def register_write_retry(op: str) -> None:
    write_retries.inc({"op": op})


def register_cache_mutation(kind: str) -> None:
    cache_mutation_violations.inc({"kind": kind})


def register_journal_records(state: str, n: int = 1) -> None:
    journal_records.inc({"state": state}, by=n)


def register_reconcile_op(op: str, n: int = 1) -> None:
    reconcile_ops.inc({"op": op}, by=n)


def register_cycle_overrun(kind: str) -> None:
    cycle_overruns.inc({"kind": kind})


def register_resync_drop() -> None:
    resync_dropped.inc()


def register_stale_cycle_skip() -> None:
    stale_cycles_skipped.inc()


def set_watch_snapshot_age(age: float) -> None:
    # +inf (never synced) renders as 'inf' in exposition, which
    # Prometheus accepts; clamp anyway to keep dashboards sane
    watch_snapshot_age.set(min(age, 1e9))


def register_watch_relist(kind: str) -> None:
    watch_relists.inc({"kind": kind})


def register_encode_cache_hits(n: int) -> None:
    encode_cache_hits.inc(by=n)


def register_encode_cache_invalidation(reason: str, n: int = 1) -> None:
    encode_cache_invalidations.inc({"reason": reason}, by=n)


def set_encode_warm_fraction(fraction: float) -> None:
    encode_warm_fraction.set(fraction)


def observe_time_to_bind(seconds: float, exemplar: str | None = None) -> None:
    time_to_bind.observe(seconds, exemplar=exemplar)


def register_micro_cycle(outcome: str) -> None:
    micro_cycles.inc({"outcome": outcome})


def set_streaming_backlog(n: int) -> None:
    streaming_backlog.set(n)


def register_federation_conflict(outcome: str, exemplar: str | None = None) -> None:
    federation_conflicts.inc({"outcome": outcome}, exemplar=exemplar)


def register_federation_node_conflict(node: str, n: int = 1) -> None:
    federation_node_conflicts.inc({"node": node}, by=n)


def register_bind_retry() -> None:
    bind_retries.inc()


def observe_store_backend_rtt(op: str, seconds: float) -> None:
    store_backend_rtt.observe(seconds, {"op": op})


def register_store_backend_bytes(direction: str, codec: str, n: int) -> None:
    store_backend_bytes.inc({"dir": direction, "codec": codec}, by=n)


def observe_txn_batch_size(n: int) -> None:
    store_backend_txn_batch.observe(float(n))


def set_backend_pool_in_use(n: int) -> None:
    backend_pool_in_use.set(n)


def register_longpoll_wakeup(cause: str) -> None:
    watch_longpoll_wakeups.inc({"cause": cause})


def set_shard_slots_owned(n: int) -> None:
    shard_slots_owned.set(n)


def set_shard_slot_owned(slot: int, owned: bool) -> None:
    shard_slot_owned.set(1 if owned else 0, {"slot": str(slot)})


def register_shard_adoption(outcome: str) -> None:
    shard_adoptions.inc({"outcome": outcome})


def register_shard_handoff(outcome: str) -> None:
    shard_handoffs.inc({"outcome": outcome})


def observe_shard_takeover(seconds: float) -> None:
    shard_takeover_seconds.observe(seconds)


def register_unschedulable(reason: str) -> None:
    unschedulable_total.inc({"reason": reason})


def register_would_fit_if(plane: str) -> None:
    would_fit_if_total.inc({"plane": plane})


def set_slo_quantile(kind: str, queue: str, quantile: str, value: float) -> None:
    """One SLO window quantile (kind in obs.SLOAccountant.KINDS)."""
    gauge = _SLO_GAUGES.get(kind)
    if gauge is not None:
        gauge.set(value, {"queue": queue, "quantile": quantile})


def register_slo_evicted_queue() -> None:
    slo_evicted_queues.inc()


def drop_slo_queue(queue: str) -> None:
    """Remove an evicted queue's label sets from both slo gauges."""
    for gauge in _SLO_GAUGES.values():
        gauge.drop_labels(queue=queue)


def set_fleet_slo_quantile(kind: str, queue: str, quantile: str, value: float) -> None:
    gauge = _FLEET_SLO_GAUGES.get(kind)
    if gauge is not None:
        gauge.set(value, {"queue": queue, "quantile": quantile})


def set_fleet_node_heatmap(deltas: dict[str, float]) -> None:
    """Replace the per-node conflict heatmap wholesale (top-K only —
    stale nodes must drop out, not linger at their old value)."""
    fleet_node_conflicts.clear()
    for node, value in deltas.items():
        fleet_node_conflicts.set(value, {"node": node})


def set_fleet_backlog(n: float) -> None:
    fleet_backlog.set(n)


def set_fleet_pods_per_second(value: float) -> None:
    fleet_pods_per_second.set(value)


def set_fleet_shards_scraped(n: int) -> None:
    fleet_shards_scraped.set(n)


def set_fleet_shard_up(shard: str, up: bool) -> None:
    fleet_shard_up.set(1 if up else 0, {"shard": shard})


def set_fleet_shard_scrape_age(shard: str, age_s: float) -> None:
    fleet_shard_scrape_age.set(age_s, {"shard": shard})


def register_admission_decision(lane: str, outcome: str) -> None:
    admission_decisions.inc({"lane": lane, "outcome": outcome})


def set_admission_lane_backlog(lane: str, n: int) -> None:
    admission_lane_backlog.set(n, {"lane": lane})


def set_admission_lane_rate(lane: str, rate: float) -> None:
    admission_lane_rate.set(rate, {"lane": lane})


def set_admission_brownout_level(level: int) -> None:
    admission_brownout_level.set(level)


def set_admission_pressure(value: float) -> None:
    admission_pressure.set(value)


def register_admission_controller_tick(outcome: str) -> None:
    admission_controller_ticks.inc({"outcome": outcome})


def set_arena_hbm_bytes(slab: str, nbytes: float) -> None:
    arena_hbm_bytes.set(nbytes, {"slab": slab})


def set_arena_hbm_watermark(nbytes: float) -> None:
    arena_hbm_watermark.set(nbytes)


def set_class_solve_classes(n: int) -> None:
    class_solve_classes.set(n)


def set_class_solve_compression_ratio(ratio: float) -> None:
    class_solve_compression_ratio.set(ratio)


def register_class_table_splits(n: int) -> None:
    class_table_splits.inc(by=n)


def set_pipeline_overlap_fraction(fraction: float) -> None:
    pipeline_overlap_fraction.set(fraction)


def register_exchange_batched_iters(n: int) -> None:
    exchange_batched_iters_total.inc(by=n)


def observe_pipeline_fence_wait(seconds: float) -> None:
    pipeline_fence_wait_seconds.observe(seconds)


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double quote
    and newline must be escaped inside the quoted value (exposition
    format spec) — a queue named ``a"b`` or a fault reason with a
    newline must not corrupt the scrape."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _exemplar_of(metric, key) -> tuple[str, float] | None:
    """OpenMetrics exemplar annotation for one series as
    ``(suffix, value)``, or None. Only rendered while
    KBT_METRICS_EXEMPLARS is on (storage is gated the same way, so the
    golden exposition never sees a stale one)."""
    if not exemplars_enabled():
        return None
    with metric._lock:
        ex = metric._exemplars.get(key)
    if ex is None:
        return None
    trace_id, value = ex
    return (f' # {{trace_id="{_escape_label_value(trace_id)}"}} {value}', value)


def _render_family(metric) -> list[str]:
    lines = [f"# HELP {metric.name} {metric.help}"]
    if isinstance(metric, Histogram):
        lines.append(f"# TYPE {metric.name} histogram")
        label_sets = metric.label_sets() or [()]
        for key in label_sets:
            labels = dict(key)
            snap = metric.snapshot(labels if key else None)
            prefix = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key)
            sep = "," if prefix else ""
            ex = _exemplar_of(metric, key)
            ex_suffix, ex_value = ex if ex else ("", None)
            for boundary, cum in snap["buckets"].items():
                mark = ex_suffix if ex_value is not None and ex_value <= boundary else ""
                if mark:
                    ex_value = None  # exemplar rides its lowest containing bucket
                lines.append(
                    f'{metric.name}_bucket{{{prefix}{sep}le="{boundary}"}} {cum}{mark}'
                )
            mark = ex_suffix if ex_value is not None else ""
            lines.append(
                f'{metric.name}_bucket{{{prefix}{sep}le="+Inf"}} {snap["count"]}{mark}'
            )
            suffix = f"{{{prefix}}}" if prefix else ""
            lines.append(f"{metric.name}_sum{suffix} {snap['sum']}")
            lines.append(f"{metric.name}_count{suffix} {snap['count']}")
    else:
        kind = "counter" if isinstance(metric, Counter) else "gauge"
        lines.append(f"# TYPE {metric.name} {kind}")
        items = metric.samples()
        if not items:
            lines.append(f"{metric.name} 0")
        for key, value in items.items():
            ex = ""
            if kind == "counter":
                found = _exemplar_of(metric, key)
                ex = found[0] if found else ""
            if key:
                label_str = ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in key
                )
                lines.append(f"{metric.name}{{{label_str}}} {value}{ex}")
            else:
                lines.append(f"{metric.name} {value}{ex}")
    return lines


def render_prometheus_text() -> str:
    """Prometheus text exposition for all registered metrics."""
    families = [
        e2e_scheduling_latency,
        plugin_scheduling_latency,
        action_scheduling_latency,
        task_scheduling_latency,
        schedule_attempts,
        preemption_victims,
        preemption_attempts,
        unschedule_task_count,
        unschedule_job_count,
        job_retry_counts,
        fault_injections,
        breaker_transitions,
        breaker_state,
        degraded_cycles,
        write_retries,
        cache_mutation_violations,
        journal_records,
        reconcile_ops,
        cycle_overruns,
        resync_dropped,
        stale_cycles_skipped,
        watch_snapshot_age,
        watch_relists,
        encode_cache_hits,
        encode_cache_invalidations,
        encode_warm_fraction,
        time_to_bind,
        micro_cycles,
        streaming_backlog,
        federation_conflicts,
        federation_node_conflicts,
        bind_retries,
        store_backend_rtt,
        store_backend_bytes,
        store_backend_txn_batch,
        backend_pool_in_use,
        watch_longpoll_wakeups,
        shard_slots_owned,
        shard_slot_owned,
        shard_adoptions,
        shard_handoffs,
        shard_takeover_seconds,
        unschedulable_total,
        would_fit_if_total,
        pipeline_overlap_fraction,
        exchange_batched_iters_total,
        pipeline_fence_wait_seconds,
        slo_time_to_bind,
        slo_queue_wait,
        slo_evicted_queues,
        fleet_slo_time_to_bind,
        fleet_slo_queue_wait,
        fleet_node_conflicts,
        fleet_backlog,
        fleet_pods_per_second,
        fleet_shards_scraped,
        fleet_shard_up,
        fleet_shard_scrape_age,
        admission_decisions,
        admission_lane_backlog,
        admission_lane_rate,
        admission_brownout_level,
        admission_pressure,
        admission_controller_ticks,
        arena_hbm_bytes,
        arena_hbm_watermark,
        class_solve_classes,
        class_solve_compression_ratio,
        class_table_splits,
    ]
    lines: list[str] = []
    for metric in families:
        lines.extend(_render_family(metric))
    return "\n".join(lines) + "\n"
