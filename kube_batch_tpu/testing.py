"""Builders shared by unit tests, action tests, and the bench harness
(reference pkg/scheduler/api/test_utils.go and pkg/scheduler/util/test_utils.go).
"""

from __future__ import annotations

import re
import threading
from typing import Optional, Union

from kube_batch_tpu.apis.types import (
    GROUP_NAME_ANNOTATION_KEY,
    Container,
    Node,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodPhase,
    Queue,
    QueueSpec,
)
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.resource_info import Resource

_QUANTITY_RE = re.compile(r"^([0-9.]+)([a-zA-Z]*)$")

_SUFFIX = {
    "": 1.0,
    "m": 1e-3,  # milli (cpu)
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
}


def parse_quantity(q: Union[str, int, float]) -> float:
    """Parse a Kubernetes-style quantity string ("100m", "1G", "2Gi") into a
    float in base units (cores for cpu, bytes for memory)."""
    if isinstance(q, (int, float)):
        return float(q)
    m = _QUANTITY_RE.match(q.strip())
    if not m:
        raise ValueError(f"cannot parse quantity {q!r}")
    value, suffix = m.groups()
    if suffix not in _SUFFIX:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {q!r}")
    return float(value) * _SUFFIX[suffix]


def build_resource_list(
    cpu: Union[str, float] = 0,
    memory: Union[str, float] = 0,
    pods: int = 0,
    **scalars: Union[str, float],
) -> dict[str, float]:
    """Resource list dict from k8s-style quantity strings. Scalar kwargs use
    double-underscore for '/' and '.' (e.g. nvidia__com__gpu=2) or pass a
    pre-built dict via build_resource_list(**{"nvidia.com/gpu": 2})."""
    rl: dict[str, float] = {}
    if cpu:
        rl["cpu"] = parse_quantity(cpu)
    if memory:
        rl["memory"] = parse_quantity(memory)
    if pods:
        rl["pods"] = float(pods)
    for name, q in scalars.items():
        rl[name] = parse_quantity(q)
    return rl


def build_pod(
    namespace: str = "default",
    name: str = "pod",
    node_name: str = "",
    phase: PodPhase = PodPhase.PENDING,
    req: Optional[dict[str, float]] = None,
    group_name: str = "",
    labels: Optional[dict[str, str]] = None,
    priority: Optional[int] = None,
    node_selector: Optional[dict[str, str]] = None,
    scheduler_name: str = "kube-batch-tpu",
) -> Pod:
    """reference api/test_utils.go buildPod."""
    annotations = {}
    if group_name:
        annotations[GROUP_NAME_ANNOTATION_KEY] = group_name
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"{namespace}-{name}",
            labels=labels or {},
            annotations=annotations,
        ),
        phase=phase,
        containers=[Container(requests=dict(req or {}))],
        node_name=node_name,
        node_selector=node_selector or {},
        priority=priority,
        scheduler_name=scheduler_name,
    )


def build_node(
    name: str,
    alloc: Optional[dict[str, float]] = None,
    labels: Optional[dict[str, str]] = None,
    capacity: Optional[dict[str, float]] = None,
) -> Node:
    """reference api/test_utils.go buildNode."""
    alloc = dict(alloc or {})
    return Node(
        metadata=ObjectMeta(name=name, uid=name, labels=labels or {}),
        allocatable=alloc,
        capacity=dict(capacity) if capacity is not None else dict(alloc),
    )


def build_pod_group(
    name: str,
    namespace: str = "default",
    queue: str = "default",
    min_member: int = 1,
    min_resources: Optional[dict[str, float]] = None,
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace, uid=f"pg-{namespace}-{name}"),
        spec=PodGroupSpec(min_member=min_member, queue=queue, min_resources=min_resources),
    )


def build_queue(name: str, weight: int = 1) -> Queue:
    return Queue(metadata=ObjectMeta(name=name, uid=f"q-{name}"), spec=QueueSpec(weight=weight))


def build_task(
    namespace: str = "default",
    name: str = "task",
    req: Optional[dict[str, float]] = None,
    node_name: str = "",
    phase: PodPhase = PodPhase.PENDING,
    group_name: str = "",
    priority: Optional[int] = None,
) -> TaskInfo:
    return TaskInfo(
        build_pod(
            namespace=namespace,
            name=name,
            node_name=node_name,
            phase=phase,
            req=req,
            group_name=group_name,
            priority=priority,
        )
    )


def build_resource(cpu: Union[str, float] = 0, memory: Union[str, float] = 0, **scalars) -> Resource:
    return Resource.from_resource_list(build_resource_list(cpu, memory, **scalars))


class FakeBinder:
    """Records binds instead of calling an API server; signals a condition
    per bind (reference util/test_utils.go:95-117)."""

    def __init__(self) -> None:
        self.binds: dict[str, str] = {}  # "ns/name" -> node
        self.channel: "threading.Event" = threading.Event()
        self._lock = threading.Lock()

    def bind(self, pod: Pod, hostname: str) -> None:
        with self._lock:
            self.binds[f"{pod.namespace}/{pod.name}"] = hostname
        self.channel.set()


class FakeEvictor:
    """reference util/test_utils.go:120-140."""

    def __init__(self) -> None:
        self.evicts: list[str] = []
        self.channel: "threading.Event" = threading.Event()
        self._lock = threading.Lock()

    def evict(self, pod: Pod) -> None:
        with self._lock:
            self.evicts.append(f"{pod.namespace}/{pod.name}")
        self.channel.set()


class FakeStatusUpdater:
    """no-op (reference util/test_utils.go:143-153)."""

    def update_pod_condition(self, pod: Pod, condition) -> None:
        return None

    def update_pod_group(self, pg: PodGroup) -> None:
        return None


class FakeVolumeBinder:
    """no-op (reference util/test_utils.go:156-166)."""

    def allocate_volumes(self, task, hostname: str) -> None:
        return None

    def bind_volumes(self, task) -> None:
        return None
