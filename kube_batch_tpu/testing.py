"""Builders shared by unit tests, action tests, and the bench harness
(reference pkg/scheduler/api/test_utils.go and pkg/scheduler/util/test_utils.go).
"""

from __future__ import annotations

import queue
import re
import threading
from collections import deque
from contextlib import contextmanager
from typing import Optional, Union

from kube_batch_tpu.apis.types import (
    GROUP_NAME_ANNOTATION_KEY,
    Container,
    Node,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodPhase,
    Queue,
    QueueSpec,
)
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.resource_info import Resource

_QUANTITY_RE = re.compile(r"^([0-9.]+)([a-zA-Z]*)$")


@contextmanager
def x64_enabled(enable: bool = True):
    """Temporarily pin ``jax_enable_x64`` through the supported
    ``jax.config.update`` API and restore the previous value on exit.

    The one place tests flip x64 mid-suite: `jax.experimental.enable_x64`
    is a deprecated context manager slated for removal, and raw
    env-var flips are too late once the backend initialized — this
    helper is the single sanctioned idiom (API-drift sweep, the
    `test_ieee_div` stale `jax.enable_x64` fix's follow-up)."""
    import jax

    old = bool(jax.config.jax_enable_x64)
    jax.config.update("jax_enable_x64", enable)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", old)

_SUFFIX = {
    "": 1.0,
    "m": 1e-3,  # milli (cpu)
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
}


def parse_quantity(q: Union[str, int, float]) -> float:
    """Parse a Kubernetes-style quantity string ("100m", "1G", "2Gi") into a
    float in base units (cores for cpu, bytes for memory)."""
    if isinstance(q, (int, float)):
        return float(q)
    m = _QUANTITY_RE.match(q.strip())
    if not m:
        raise ValueError(f"cannot parse quantity {q!r}")
    value, suffix = m.groups()
    if suffix not in _SUFFIX:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {q!r}")
    return float(value) * _SUFFIX[suffix]


def build_resource_list(
    cpu: Union[str, float] = 0,
    memory: Union[str, float] = 0,
    pods: int = 0,
    **scalars: Union[str, float],
) -> dict[str, float]:
    """Resource list dict from k8s-style quantity strings. Scalar kwargs
    translate double-underscores: ``nvidia__com__gpu=2`` becomes
    ``nvidia.com/gpu: 2`` (first ``__`` -> ``.``, second -> ``/``); or pass
    a pre-built dict via build_resource_list(**{"nvidia.com/gpu": 2})."""
    rl: dict[str, float] = {}
    if cpu:
        rl["cpu"] = parse_quantity(cpu)
    if memory:
        rl["memory"] = parse_quantity(memory)
    if pods:
        rl["pods"] = float(pods)
    for name, q in scalars.items():
        if "__" in name:
            # domain__suffix__resource -> domain.suffix/resource
            parts = name.split("__")
            name = ".".join(parts[:-1]) + "/" + parts[-1]
        rl[name] = parse_quantity(q)
    return rl


def build_pod(
    namespace: str = "default",
    name: str = "pod",
    node_name: str = "",
    phase: PodPhase = PodPhase.PENDING,
    req: Optional[dict[str, float]] = None,
    group_name: str = "",
    labels: Optional[dict[str, str]] = None,
    priority: Optional[int] = None,
    node_selector: Optional[dict[str, str]] = None,
    scheduler_name: str = "kube-batch-tpu",
    volumes: Optional[list[str]] = None,
) -> Pod:
    """reference api/test_utils.go buildPod."""
    annotations = {}
    if group_name:
        annotations[GROUP_NAME_ANNOTATION_KEY] = group_name
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=f"{namespace}-{name}",
            labels=labels or {},
            annotations=annotations,
        ),
        phase=phase,
        containers=[Container(requests=dict(req or {}))],
        node_name=node_name,
        node_selector=node_selector or {},
        priority=priority,
        scheduler_name=scheduler_name,
        volumes=list(volumes or []),
    )


def build_node(
    name: str,
    alloc: Optional[dict[str, float]] = None,
    labels: Optional[dict[str, str]] = None,
    capacity: Optional[dict[str, float]] = None,
) -> Node:
    """reference api/test_utils.go buildNode."""
    alloc = dict(alloc or {})
    return Node(
        metadata=ObjectMeta(name=name, uid=name, labels=labels or {}),
        allocatable=alloc,
        capacity=dict(capacity) if capacity is not None else dict(alloc),
    )


def build_pod_group(
    name: str,
    namespace: str = "default",
    queue: str = "default",
    min_member: int = 1,
    min_resources: Optional[dict[str, float]] = None,
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace, uid=f"pg-{namespace}-{name}"),
        spec=PodGroupSpec(min_member=min_member, queue=queue, min_resources=min_resources),
    )


def build_queue(name: str, weight: int = 1) -> Queue:
    return Queue(metadata=ObjectMeta(name=name, uid=f"q-{name}"), spec=QueueSpec(weight=weight))


def build_pv(
    name: str,
    capacity: Union[str, int, float] = "10Gi",
    storage_class: str = "",
    node_affinity: Optional[list] = None,
):
    from kube_batch_tpu.apis.types import PersistentVolume

    return PersistentVolume(
        metadata=ObjectMeta(name=name, uid=f"pv-{name}"),
        capacity_storage=parse_quantity(capacity),
        storage_class_name=storage_class,
        node_affinity=list(node_affinity or []),
    )


def build_pvc(
    name: str,
    namespace: str = "default",
    storage_class: str = "",
    request: Union[str, int, float] = "1Gi",
):
    from kube_batch_tpu.apis.types import PersistentVolumeClaim

    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name, namespace=namespace, uid=f"pvc-{namespace}-{name}"),
        storage_class_name=storage_class,
        request_storage=parse_quantity(request),
    )


def build_storage_class(name: str, mode: str = "Immediate"):
    from kube_batch_tpu.apis.types import StorageClass, VolumeBindingMode

    return StorageClass(
        metadata=ObjectMeta(name=name, uid=f"sc-{name}"),
        volume_binding_mode=VolumeBindingMode(mode),
    )


def build_task(
    namespace: str = "default",
    name: str = "task",
    req: Optional[dict[str, float]] = None,
    node_name: str = "",
    phase: PodPhase = PodPhase.PENDING,
    group_name: str = "",
    priority: Optional[int] = None,
) -> TaskInfo:
    return TaskInfo(
        build_pod(
            namespace=namespace,
            name=name,
            node_name=node_name,
            phase=phase,
            req=req,
            group_name=group_name,
            priority=priority,
        )
    )


def build_resource(cpu: Union[str, float] = 0, memory: Union[str, float] = 0, **scalars) -> Resource:
    return Resource.from_resource_list(build_resource_list(cpu, memory, **scalars))


class _Channel:
    """One-signal-per-bind channel (the reference's Go test channel,
    util/test_utils.go:95-117): SimpleQueue's get/get_nowait/empty
    surface plus a bulk `extend` — one lock round for a 200k-bind batch
    instead of 200k `put` calls."""

    __slots__ = ("_items", "_cond")

    def __init__(self) -> None:
        self._items: deque = deque()
        self._cond = threading.Condition()

    def put(self, item) -> None:
        with self._cond:
            self._items.append(item)
            self._cond.notify()

    def extend(self, items) -> None:
        with self._cond:
            self._items.extend(items)
            self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            if not self._cond.wait_for(lambda: self._items, timeout):
                raise queue.Empty
            return self._items.popleft()

    def get_nowait(self):
        with self._cond:
            if not self._items:
                raise queue.Empty
            return self._items.popleft()

    def empty(self) -> bool:
        with self._cond:
            return not self._items


class FakeBinder:
    """Records binds instead of calling an API server; delivers one signal
    per bind, like the reference's Go channel (util/test_utils.go:95-117) —
    a latching Event would let a test waiting for N binds pass after one."""

    def __init__(self) -> None:
        self.binds: dict[str, str] = {}  # "ns/name" -> node
        self.channel = _Channel()
        self._lock = threading.Lock()

    def bind(self, pod: Pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        with self._lock:
            self.binds[key] = hostname
        self.channel.put(key)

    def bind_many(self, pairs: list, keys: "Optional[list[str]]" = None) -> None:
        """Bulk form: one lock acquisition, same one-signal-per-bind
        channel contract. ``keys`` (parallel "ns/name" strings) skips
        200k per-pod f-string constructions when the caller already
        holds them (the replay path does)."""
        if keys is not None:
            keyed = list(zip(keys, (hostname for _, hostname in pairs)))
        else:
            keyed = [
                (f"{pod.namespace}/{pod.name}", hostname) for pod, hostname in pairs
            ]
        with self._lock:
            self.binds.update(keyed)
        self.channel.extend(k for k, _ in keyed)

    def bind_many_keyed(self, keys: list, hostnames: list) -> None:
        """Column form of bind_many: binds.update from an iterator, one
        channel extend — no intermediate pair list at all."""
        with self._lock:
            self.binds.update(zip(keys, hostnames))
        self.channel.extend(keys)


class FakeEvictor:
    """reference util/test_utils.go:120-140; one signal per evict."""

    def __init__(self) -> None:
        self.evicts: list[str] = []
        self.channel: "queue.SimpleQueue[str]" = queue.SimpleQueue()
        self._lock = threading.Lock()

    def evict(self, pod: Pod) -> None:
        key = f"{pod.namespace}/{pod.name}"
        with self._lock:
            self.evicts.append(key)
        self.channel.put(key)


class FakeStatusUpdater:
    """no-op (reference util/test_utils.go:143-153)."""

    def update_pod_condition(self, pod: Pod, condition) -> None:
        return None

    def update_pod_group(self, pg: PodGroup) -> None:
        return None


class FakeVolumeBinder:
    """no-op (reference util/test_utils.go:156-166)."""

    def allocate_volumes(self, task, hostname: str) -> None:
        return None

    def bind_volumes(self, task) -> None:
        return None


def build_cluster(
    pods: list[Pod],
    nodes: list[Node],
    pod_groups: Optional[list[PodGroup]] = None,
    queues: Optional[list[Queue]] = None,
):
    """Wire pods/nodes/podgroups/queues into a ClusterInfo the way the
    cache does (reference cache/event_handlers.go:43-88): tasks join jobs
    via the group-name annotation (pods without one get a synthetic
    single-member shadow job), bound/running tasks also land on their
    node. Jobs whose PodGroup is Pending-phase get phase Inqueue so the
    allocate action considers them (the enqueue action owns that gate in
    a full pipeline)."""
    from kube_batch_tpu.api.cluster_info import ClusterInfo
    from kube_batch_tpu.api.job_info import JobInfo, TaskInfo, get_job_id, job_key
    from kube_batch_tpu.api.node_info import NodeInfo
    from kube_batch_tpu.api.queue_info import QueueInfo
    from kube_batch_tpu.apis.types import PodGroupPhase

    cluster = ClusterInfo()
    for node in nodes:
        cluster.nodes[node.name] = NodeInfo(node)
    for queue in queues or []:
        cluster.queues[queue.name] = QueueInfo(queue)

    for pg in pod_groups or []:
        if pg.status.phase == PodGroupPhase.PENDING:
            pg.status.phase = PodGroupPhase.INQUEUE
        jid = job_key(pg.metadata.namespace, pg.name)
        job = JobInfo(jid)
        job.set_pod_group(pg)
        cluster.jobs[jid] = job

    for pod in pods:
        task = TaskInfo(pod)
        jid = get_job_id(pod) or f"{pod.namespace}/{pod.name}-shadow"
        if jid not in cluster.jobs:
            shadow = build_pod_group(
                name=f"{pod.name}-shadow", namespace=pod.namespace, min_member=1
            )
            shadow.status.phase = PodGroupPhase.INQUEUE
            job = JobInfo(jid)
            job.set_pod_group(shadow)
            cluster.jobs[jid] = job
        task.job = jid
        cluster.jobs[jid].add_task_info(task)
        if task.node_name and task.node_name in cluster.nodes:
            cluster.nodes[task.node_name].add_task(task)
    return cluster


class FakeCache:
    """Session-facing cache with fake write-side, for action-level tests
    (the pattern of reference actions/allocate/allocate_test.go:38-212:
    real model, fake Binder/Evictor)."""

    def __init__(
        self,
        cluster,
        binder: Optional[FakeBinder] = None,
        evictor: Optional[FakeEvictor] = None,
        status_updater: Optional[FakeStatusUpdater] = None,
        volume_binder: Optional[FakeVolumeBinder] = None,
    ) -> None:
        self.cluster = cluster
        self.binder = binder or FakeBinder()
        self.evictor = evictor or FakeEvictor()
        self.status_updater = status_updater or FakeStatusUpdater()
        self.volume_binder = volume_binder or FakeVolumeBinder()

    def snapshot(self):
        from kube_batch_tpu.api.cluster_info import ClusterInfo

        return ClusterInfo(
            jobs={uid: job.clone() for uid, job in self.cluster.jobs.items()},
            nodes={name: node.clone() for name, node in self.cluster.nodes.items()},
            queues={name: q.clone() for name, q in self.cluster.queues.items()},
        )

    def bind(self, task, hostname: str) -> None:
        self.binder.bind(task.pod, hostname)

    def bind_many(self, pairs: list, keys=None) -> None:
        if keys is not None:
            # keyed fast path: the binder never touches the pods
            self.binder.bind_many(pairs, keys=keys)
            return
        self.binder.bind_many([(task.pod, hostname) for task, hostname in pairs])

    def bind_many_keyed(self, tasks: list, hostnames: list, keys: list) -> None:
        """Parallel-list bulk bind (replay fast path): tasks/hostnames/
        keys are same-length columns; no per-bind tuple objects."""
        self.binder.bind_many_keyed(keys, hostnames)

    def evict(self, task, reason: str) -> None:
        self.evictor.evict(task.pod)

    def update_job_status(self, job):
        self.status_updater.update_pod_group(job.pod_group)
        return job

    def record_job_status_event(self, job) -> None:
        return None

    def allocate_volumes(self, task, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def bind_volumes(self, task) -> None:
        self.volume_binder.bind_volumes(task)
