"""kbt-ctl — the queue admin CLI (reference cmd/cli/queue.go +
pkg/cli/queue/{create,list}.go).

The reference CLI talks to the Kubernetes API server with a generated
clientset; here the scheduler server's HTTP queue API
(kube_batch_tpu/server.py, the in-process CRD surface) is the backend:

    kbt-ctl queue create --name q1 --weight 3
    kbt-ctl queue list
    kbt-ctl queue delete --name q1
    kbt-ctl explain --gang default/my-gang
    kbt-ctl version

`--server` points at the scheduler's listen address (the reference's
--master/--kubeconfig pair collapses to one URL with no auth layer).
"""

from kube_batch_tpu.cli.queue import main

__all__ = ["main"]
