"""queue create / list / delete against the scheduler's HTTP API
(reference pkg/cli/queue/create.go:46-67, list.go:54-87), plus the
``explain`` subcommand over /debug/explain (unschedulability
forensics: dominant reason, plane eliminations, near-miss nodes)."""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional, TextIO

from kube_batch_tpu.version import info as version_info

DEFAULT_SERVER = "http://127.0.0.1:8080"


def _request(
    method: str, url: str, body: Optional[dict] = None, timeout: float = 10
) -> dict:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


def print_queues(items: list[dict], out: TextIO) -> None:
    """PrintQueues parity: %-25s%-8s columns (list.go:72-87)."""
    out.write(f"{'Name':<25}{'Weight':<8}\n")
    for q in items:
        out.write(f"{q.get('name', ''):<25}{q.get('weight', 0):<8}\n")


def cmd_create(args, out: TextIO) -> int:
    _request(
        "POST",
        f"{args.server}/apis/v1alpha1/queues",
        {"name": args.name, "weight": args.weight},
    )
    return 0


def cmd_list(args, out: TextIO) -> int:
    payload = _request("GET", f"{args.server}/apis/v1alpha1/queues")
    items = payload.get("items", [])
    if not items and not getattr(args, "watch", False):
        out.write("No resources found\n")  # list.go:63-65
        return 0
    print_queues(items, out)
    if getattr(args, "watch", False):
        _watch_queues(args, payload.get("resourceVersion", 0), out)
    return 0


def _watch_queues(args, since: int, out: TextIO) -> None:
    """Long-poll /watch/queues from the list's resourceVersion, printing
    one line per event until interrupted (kubectl get -w shape)."""
    while True:
        try:
            payload = _request(
                "GET",
                f"{args.server}/apis/v1alpha1/watch/queues"
                f"?since={since}&timeout={args.watch_timeout}",
                timeout=args.watch_timeout + 10,
            )
        except urllib.error.HTTPError as err:
            if err.code == 410:  # fell behind the ring: re-list and resume
                listing = _request("GET", f"{args.server}/apis/v1alpha1/queues")
                print_queues(listing.get("items", []), out)
                since = listing.get("resourceVersion", 0)
                continue
            raise
        for ev in payload.get("events", []):
            q = ev.get("object", {})
            out.write(
                f"{ev.get('type', ''):<10}{q.get('name', ''):<25}"
                f"{q.get('weight', 0):<8}\n"
            )
            out.flush()
        since = payload.get("resourceVersion", since)
        if getattr(args, "watch_once", False) and payload.get("events"):
            return


def cmd_delete(args, out: TextIO) -> int:
    _request("DELETE", f"{args.server}/apis/v1alpha1/queues/{args.name}")
    return 0


def cmd_explain(args, out: TextIO) -> int:
    """Fetch unschedulability forensics from /debug/explain: the
    per-gang dominant reason, per-plane elimination counts, would-fit-if
    planes and near-miss nodes from the last allocate cycle."""
    url = f"{args.server}/debug/explain"
    if args.gang:
        url += "?gang=" + urllib.parse.quote(args.gang)
    payload = _request("GET", url)
    if args.as_json:
        out.write(json.dumps(payload, sort_keys=True) + "\n")
        return 0
    if not payload.get("enabled", False):
        out.write("explain is disabled (set KBT_EXPLAIN=1 or conf "
                  "'explain: \"1\"')\n")
        return 0
    recs = payload.get("records", [])
    if args.gang:
        if not recs:
            out.write(f"no explain record for gang {args.gang!r} "
                      "(bound earlier, or not seen by the last cycle)\n")
            return 1
        for rec in recs:
            out.write(json.dumps(rec, sort_keys=True, indent=2) + "\n")
        return 0
    out.write(f"{'Gang':<32}{'Verdict':<15}{'Reason':<12}"
              f"{'Ready':<7}{'Min':<5}\n")
    for rec in sorted(recs, key=lambda r: r.get("name", "")):
        out.write(
            f"{rec.get('name', ''):<32}{rec.get('verdict', ''):<15}"
            f"{rec.get('reason', ''):<12}{rec.get('ready', 0):<7}"
            f"{rec.get('min', 0):<5}\n"
        )
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kbt-ctl", description="kube-batch-tpu admin CLI"
    )
    parser.add_argument(
        "--server",
        default=DEFAULT_SERVER,
        help="scheduler server address (default %(default)s)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    sub.add_parser("version", help="print client version")

    queue = sub.add_parser("queue", help="queue operations")
    qsub = queue.add_subparsers(dest="queue_command", required=True)

    create = qsub.add_parser("create", help="create a queue (create.go:46-67)")
    create.add_argument("--name", required=True, help="queue name")
    create.add_argument(
        "--weight", type=int, default=1, help="proportion weight (default 1)"
    )
    create.set_defaults(fn=cmd_create)

    lst = qsub.add_parser("list", help="list queues (list.go:54-70)")
    lst.add_argument(
        "--watch", action="store_true",
        help="after listing, stream queue add/update/delete events",
    )
    lst.add_argument(
        "--watch-timeout", type=float, default=30.0, help=argparse.SUPPRESS
    )
    lst.add_argument(
        "--watch-once", action="store_true", help=argparse.SUPPRESS
    )  # exit after the first event batch (tests)
    lst.set_defaults(fn=cmd_list)

    delete = qsub.add_parser("delete", help="delete a queue")
    delete.add_argument("--name", required=True, help="queue name")
    delete.set_defaults(fn=cmd_delete)

    explain = sub.add_parser(
        "explain",
        help="why gangs are unschedulable (/debug/explain forensics)",
    )
    explain.add_argument(
        "--gang", default=None,
        help="filter to one gang (uid, PodGroup name, or namespace/name)",
    )
    explain.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print the raw /debug/explain payload",
    )
    explain.set_defaults(fn=cmd_explain)

    return parser


def main(argv: Optional[list[str]] = None, out: TextIO = sys.stdout) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        out.write("\n".join(version_info()) + "\n")
        return 0
    try:
        return args.fn(args, out)
    except urllib.error.HTTPError as err:
        detail = err.read().decode(errors="replace").strip()
        print(f"Error: {err.code} {err.reason}: {detail}", file=sys.stderr)
        return 1
    except urllib.error.URLError as err:
        print(f"Error: cannot reach {args.server}: {err.reason}", file=sys.stderr)
        return 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
