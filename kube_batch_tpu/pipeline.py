"""Pipelined scheduling cycles (``KBT_PIPELINE``, default off).

The synchronous cycle is encode-upload -> device solve -> replay/dispatch,
back to back. This module owns the machinery that overlaps the third
phase with the *next* cycle:

- :class:`DispatchFence` — a process-wide rendezvous between cycle N's
  deferred replay/dispatch (submitted onto the cache's kb-write pool by
  ``actions/xla_allocate``) and cycle N+1, which must not snapshot the
  cluster until N's binds have landed. The fence preserves the
  statement/journal ordering the synchronous path gets for free:
  dispatch N < snapshot N+1 < dispatch N+1.
- **Loud degradation** — a fence timeout (wedged writer pool, or the
  ``pipeline.fence`` fault point in a drill) marks the pipeline
  degraded: :func:`enabled` flips false, every subsequent cycle runs the
  synchronous path, a degraded-cycle metric and a flight-recorder dump
  fire. Degradation is sticky until :func:`reset` (operator action /
  test hygiene) because a fence that timed out once has already proven
  the overlap assumption wrong for this process.
- overlap accounting — ``pipeline_overlap_fraction`` is
  ``(dispatch_duration - fence_wait) / dispatch_duration``: 1.0 means
  the dispatch finished entirely under the next cycle's work, 0.0 means
  the fence serialized the cycles after all. ``pipeline_fence_wait_seconds``
  records every wait.

Sessions carry the in-flight work as ``ssn.deferred_dispatch`` (a
``concurrent.futures.Future``); ``framework.close_session`` joins it
before the commit write-back so job status never races the binds it
describes. Caches without a writer pool (``testing.FakeCache``) fall
back to a lazy module-level single-thread executor, so the pipelined
path is testable without the full cache daemon.

Env knobs: ``KBT_PIPELINE`` turns the pipeline on;
``KBT_PIPELINE_FENCE_TIMEOUT_S`` bounds the fence wait (default 30s).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Optional

from kube_batch_tpu import faults, log, metrics

__all__ = [
    "ENV",
    "FENCE_TIMEOUT_ENV",
    "DispatchFence",
    "fence",
    "enabled",
    "env_on",
    "fence_timeout_s",
    "submit",
    "join_session",
    "reset",
]

ENV = "KBT_PIPELINE"
FENCE_TIMEOUT_ENV = "KBT_PIPELINE_FENCE_TIMEOUT_S"

_TRUTHY = ("1", "true", "on", "yes")


def env_on() -> bool:
    """The raw env gate, ignoring degradation state."""
    return (os.environ.get(ENV, "") or "").strip().lower() in _TRUTHY


def enabled() -> bool:
    """Pipelined cycles are on: env gate set AND the fence has not
    degraded this process to the synchronous path."""
    return env_on() and fence.degraded_reason is None


def fence_timeout_s() -> float:
    raw = os.environ.get(FENCE_TIMEOUT_ENV, "")
    try:
        return float(raw) if raw else 30.0
    except ValueError:
        log.errorf("%s=%r is not a number; using 30", FENCE_TIMEOUT_ENV, raw)
        return 30.0


class DispatchFence:
    """Rendezvous between cycle N's deferred dispatch and cycle N+1.

    ``arm(future)`` is called by the action after submitting the
    post-solve phase; ``wait()`` is called at the top of the next cycle
    (and by the bench harness between repeats). ``wait()`` returning
    False means the caller must NOT proceed with a pipelined cycle: the
    dispatch either timed out (still in flight — the future stays armed
    so a later wait can re-join it) or raised (already logged by the
    finisher; the fence only records the degradation).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._future: Optional[Future] = None
        self._dispatch_s = 0.0
        self.degraded_reason: Optional[str] = None

    def arm(self, future: Future) -> None:
        with self._lock:
            self._future = future

    def pending(self) -> bool:
        with self._lock:
            return self._future is not None and not self._future.done()

    def record_dispatch_seconds(self, seconds: float) -> None:
        """Called by the deferred finisher with its own wall duration —
        the denominator of the overlap fraction."""
        with self._lock:
            self._dispatch_s = float(seconds)

    def degrade(self, reason: str) -> None:
        """Sticky: flips :func:`enabled` false for the process, loudly."""
        if self.degraded_reason is None:
            self.degraded_reason = reason
            log.errorf(
                "pipeline degraded to synchronous cycles: %s "
                "(sticky until pipeline.reset())", reason,
            )
            metrics.register_degraded_cycle("pipeline", reason.split(":")[0])
            from kube_batch_tpu import obs

            obs.recorder.dump(reason="pipeline.degraded", min_interval_s=5.0)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the in-flight deferred dispatch. True = clean (or
        nothing in flight); False = the caller must take the synchronous
        path (the fence has already degraded the pipeline)."""
        with self._lock:
            fut = self._future
        if fut is None:
            return True
        if timeout is None:
            timeout = fence_timeout_s()
        wedged = faults.should_fire("pipeline.fence")
        t0 = time.perf_counter()
        ok = True
        try:
            if wedged:
                raise _FutureTimeout()
            fut.result(timeout=timeout)
        except _FutureTimeout:
            ok = False
            reason = (
                "fault injected: pipeline.fence" if wedged
                else f"fence timeout: dispatch still in flight after {timeout:g}s"
            )
            self.degrade(reason)
            # the future stays armed: the dispatch may still land, and
            # the (now synchronous) next cycle must re-join it first
        except Exception as e:  # noqa: BLE001 - finisher already logged it
            ok = False
            self.degrade(f"deferred dispatch raised {type(e).__name__}: {e}")
            with self._lock:
                self._future = None
        waited = time.perf_counter() - t0
        metrics.observe_pipeline_fence_wait(waited)
        with self._lock:
            if ok:
                self._future = None
            d = self._dispatch_s
        if ok and d > 0.0:
            metrics.set_pipeline_overlap_fraction(
                max(0.0, min(1.0, (d - waited) / d))
            )
        return ok

    def reset(self) -> None:
        with self._lock:
            fut = self._future
            self._future = None
            self._dispatch_s = 0.0
        self.degraded_reason = None
        if fut is not None and not fut.done():
            try:
                fut.result(timeout=fence_timeout_s())
            except Exception:  # noqa: BLE001 - reset is best-effort teardown
                pass


fence = DispatchFence()

# Lazy fallback executor for caches without a kb-write pool (FakeCache,
# the interleave harness): one thread keeps the deferred dispatches of a
# single scheduler strictly ordered, which is all the fence needs.
_fallback: Optional[ThreadPoolExecutor] = None
_fallback_lock = threading.Lock()


def submit(cache, fn: Callable[[], None]) -> Future:
    """Submit the post-solve dispatch closure: onto the cache's writer
    pool when it exposes one, else onto the module fallback thread."""
    sub = getattr(cache, "submit_dispatch", None)
    if callable(sub):
        return sub(fn)
    global _fallback
    with _fallback_lock:
        if _fallback is None:
            _fallback = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kbt-pipeline"
            )
    return _fallback.submit(fn)


def join_session(ssn, timeout: Optional[float] = None) -> None:
    """Block until ``ssn``'s deferred dispatch (if any) has landed,
    re-raising its exception. close_session calls this before the commit
    write-back; benches call it before reading binder state."""
    fut = getattr(ssn, "deferred_dispatch", None)
    if fut is None:
        return
    ssn.deferred_dispatch = None
    fut.result(timeout=timeout if timeout is not None else fence_timeout_s())


def reset() -> None:
    """Clear fence + degradation state (test hygiene between drills)."""
    fence.reset()
