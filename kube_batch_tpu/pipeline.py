"""Pipelined scheduling cycles (``KBT_PIPELINE``, default off).

The synchronous cycle is encode-upload -> device solve -> replay/dispatch,
back to back. This module owns the machinery that overlaps the third
phase with the *next* cycle:

- :class:`DispatchFence` — a process-wide rendezvous between cycle N's
  deferred replay/dispatch (submitted onto the cache's kb-write pool by
  ``actions/xla_allocate``) and cycle N+1, which must not snapshot the
  cluster until N's binds have landed. The fence preserves the
  statement/journal ordering the synchronous path gets for free:
  dispatch N < snapshot N+1 < dispatch N+1.
- **Loud degradation** — a fence timeout (wedged writer pool, or the
  ``pipeline.fence`` fault point in a drill) marks the pipeline
  degraded: :func:`enabled` flips false, every subsequent cycle runs the
  synchronous path, a degraded-cycle metric and a flight-recorder dump
  fire. Degradation is sticky until :func:`reset` (operator action /
  test hygiene) because a fence that timed out once has already proven
  the overlap assumption wrong for this process.
- overlap accounting — ``pipeline_overlap_fraction`` is MEASURED, not
  inferred: the deferred finisher stamps its dispatch window
  ``[d0, d1]`` (:meth:`DispatchFence.record_dispatch_window`), the
  consumer's join stamps its blocked window ``[w0, w1]``, and the
  fraction is ``1 - |[w0,w1] ∩ [d0,d1]| / (d1 - d0)``: 1.0 means the
  dispatch ran entirely under the next cycle's work, 0.0 means the
  fence serialized the cycles after all. Exposed as
  ``fence.last_overlap_fraction`` for the bench's per-row column;
  ``pipeline_fence_wait_seconds`` records every wait.

Sessions carry the in-flight work as ``ssn.deferred_dispatch`` (a
``concurrent.futures.Future``); ``framework.close_session`` joins it
before the commit write-back so job status never races the binds it
describes. Caches without a writer pool (``testing.FakeCache``) fall
back to a lazy module-level single-thread executor, so the pipelined
path is testable without the full cache daemon.

Env knobs: ``KBT_PIPELINE`` turns the pipeline on;
``KBT_PIPELINE_FENCE_TIMEOUT_S`` bounds the fence wait (default 30s).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Optional

from kube_batch_tpu import faults, log, metrics

__all__ = [
    "ENV",
    "FENCE_TIMEOUT_ENV",
    "DispatchFence",
    "fence",
    "enabled",
    "env_on",
    "fence_timeout_s",
    "submit",
    "join_session",
    "reset",
]

ENV = "KBT_PIPELINE"
FENCE_TIMEOUT_ENV = "KBT_PIPELINE_FENCE_TIMEOUT_S"

_TRUTHY = ("1", "true", "on", "yes")


def env_on() -> bool:
    """The raw env gate, ignoring degradation state."""
    return (os.environ.get(ENV, "") or "").strip().lower() in _TRUTHY


def enabled() -> bool:
    """Pipelined cycles are on: env gate set AND the fence has not
    degraded this process to the synchronous path."""
    return env_on() and fence.degraded_reason is None


def fence_timeout_s() -> float:
    raw = os.environ.get(FENCE_TIMEOUT_ENV, "")
    try:
        return float(raw) if raw else 30.0
    except ValueError:
        log.errorf("%s=%r is not a number; using 30", FENCE_TIMEOUT_ENV, raw)
        return 30.0


class DispatchFence:
    """Rendezvous between cycle N's deferred dispatch and cycle N+1.

    ``arm(future)`` is called by the action after submitting the
    post-solve phase; ``wait()`` is called at the top of the next cycle
    (and by the bench harness between repeats). ``wait()`` returning
    False means the caller must NOT proceed with a pipelined cycle: the
    dispatch either timed out (still in flight — the future stays armed
    so a later wait can re-join it) or raised (already logged by the
    finisher; the fence only records the degradation).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._future: Optional[Future] = None  #: guarded_by _lock
        self._dispatch_s = 0.0  #: guarded_by _lock
        self._dispatch_t0 = 0.0  #: guarded_by _lock
        self._dispatch_t1 = 0.0  #: guarded_by _lock
        # one overlap sample per dispatch window: the FIRST join after a
        # window records it, later joins of the same window do not
        self._overlap_fresh = False  #: guarded_by _lock
        self.last_overlap_fraction: Optional[float] = None  #: guarded_by _lock
        self.degraded_reason: Optional[str] = None  #: guarded_by _lock

    def arm(self, future: Future) -> None:
        with self._lock:
            self._future = future

    def pending(self) -> bool:
        with self._lock:
            return self._future is not None and not self._future.done()

    def record_dispatch_window(self, t0: float, t1: float) -> None:
        """Called by the deferred finisher with its own
        ``time.perf_counter()`` start/end stamps — the denominator of
        the measured overlap fraction."""
        with self._lock:
            self._dispatch_t0 = float(t0)
            self._dispatch_t1 = float(t1)
            self._dispatch_s = max(0.0, float(t1) - float(t0))
            self._overlap_fresh = True

    def record_dispatch_seconds(self, seconds: float) -> None:
        """Back-compat duration form: a dispatch that just finished,
        ``seconds`` long (window ends now)."""
        now = time.perf_counter()
        self.record_dispatch_window(now - float(seconds), now)

    def record_join(self, w0: float, w1: float) -> None:
        """One consumer join of the deferred dispatch, blocked over
        ``[w0, w1]``. Computes the device-event-honest overlap fraction
        against the recorded dispatch window: the share of the dispatch
        that did NOT block the join."""
        with self._lock:
            if not self._overlap_fresh or self._dispatch_t1 <= self._dispatch_t0:
                return
            d0, d1 = self._dispatch_t0, self._dispatch_t1
            self._overlap_fresh = False
            blocked = max(0.0, min(w1, d1) - max(w0, d0))
            fraction = max(0.0, min(1.0, 1.0 - blocked / (d1 - d0)))
            self.last_overlap_fraction = fraction
        metrics.set_pipeline_overlap_fraction(fraction)

    def degrade(self, reason: str) -> None:
        """Sticky: flips :func:`enabled` false for the process, loudly."""
        with self._lock:
            if self.degraded_reason is not None:
                return
            self.degraded_reason = reason
        log.errorf(
            "pipeline degraded to synchronous cycles: %s "
            "(sticky until pipeline.reset())", reason,
        )
        metrics.register_degraded_cycle("pipeline", reason.split(":")[0])
        from kube_batch_tpu import obs

        obs.recorder.dump(reason="pipeline.degraded", min_interval_s=5.0)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the in-flight deferred dispatch. True = clean (or
        nothing in flight); False = the caller must take the synchronous
        path (the fence has already degraded the pipeline)."""
        with self._lock:
            fut = self._future
        if fut is None:
            return True
        if timeout is None:
            timeout = fence_timeout_s()
        wedged = faults.should_fire("pipeline.fence")
        t0 = time.perf_counter()
        ok = True
        try:
            if wedged:
                raise _FutureTimeout()
            fut.result(timeout=timeout)
        except _FutureTimeout:
            ok = False
            reason = (
                "fault injected: pipeline.fence" if wedged
                else f"fence timeout: dispatch still in flight after {timeout:g}s"
            )
            self.degrade(reason)
            # the future stays armed: the dispatch may still land, and
            # the (now synchronous) next cycle must re-join it first
        except Exception as e:  # noqa: BLE001 - finisher already logged it
            ok = False
            self.degrade(f"deferred dispatch raised {type(e).__name__}: {e}")
            with self._lock:
                if self._future is fut:  # a newer future may be armed
                    self._future = None
        t1 = time.perf_counter()
        metrics.observe_pipeline_fence_wait(t1 - t0)
        with self._lock:
            if ok and self._future is fut:  # don't drop a newer arm()
                self._future = None
        if ok:
            self.record_join(t0, t1)
        return ok

    def reset(self) -> None:
        with self._lock:
            fut = self._future
            self._future = None
            self._dispatch_s = 0.0
            self._dispatch_t0 = 0.0
            self._dispatch_t1 = 0.0
            self._overlap_fresh = False
            self.last_overlap_fraction = None
            self.degraded_reason = None
        if fut is not None and not fut.done():
            try:
                fut.result(timeout=fence_timeout_s())
            except Exception:  # noqa: BLE001 - reset is best-effort teardown
                pass


fence = DispatchFence()

# Lazy fallback executor for caches without a kb-write pool (FakeCache,
# the interleave harness): one thread keeps the deferred dispatches of a
# single scheduler strictly ordered, which is all the fence needs.
_fallback: Optional[ThreadPoolExecutor] = None
_fallback_lock = threading.Lock()


def submit(cache, fn: Callable[[], None]) -> Future:
    """Submit the post-solve dispatch closure: onto the cache's writer
    pool when it exposes one, else onto the module fallback thread."""
    sub = getattr(cache, "submit_dispatch", None)
    if callable(sub):
        return sub(fn)
    global _fallback
    with _fallback_lock:
        if _fallback is None:
            _fallback = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kbt-pipeline"
            )
    return _fallback.submit(fn)


def join_session(ssn, timeout: Optional[float] = None) -> None:
    """Block until ``ssn``'s deferred dispatch (if any) has landed,
    re-raising its exception. close_session calls this before the commit
    write-back; benches call it before reading binder state. The join
    window feeds the measured overlap fraction (the first join after a
    dispatch window records it)."""
    fut = getattr(ssn, "deferred_dispatch", None)
    if fut is None:
        return
    ssn.deferred_dispatch = None
    w0 = time.perf_counter()
    fut.result(timeout=timeout if timeout is not None else fence_timeout_s())
    fence.record_join(w0, time.perf_counter())


def reset() -> None:
    """Clear fence + degradation state (test hygiene between drills),
    and retire the lazy fallback thread so drills do not leak it."""
    global _fallback
    with _fallback_lock:
        pool = _fallback
        _fallback = None
    if pool is not None:
        pool.shutdown(wait=True)
    fence.reset()
