"""Session: the per-cycle scheduling world + tiered plugin-fn dispatch
(reference pkg/scheduler/framework/session.go:37-423,
session_plugins.go:25-440, framework.go:30-63).

Dispatch semantics (the heart of the policy engine, pinned by unit tests):

- job/queue/task order: chain tiers in order, first non-zero comparison
  wins; fallback = creation-time then UID (session_plugins.go:253-341).
- predicates: AND across every enabled plugin; first failure raises
  (session_plugins.go:344-361).
- node order: sum of scores across enabled plugins (:364-384).
- preemptable/reclaimable: within a tier victims are the intersection of
  every enabled plugin's candidate set; the first tier returning a
  non-None set decides (:90-172).
- overused: OR (:175-189). job ready/pipelined: AND (:192-231).
- job valid: first failure wins (:234-250).

Deviation (documented): the reference runs its JobValid gate inside
openSession *before* tiers are assigned and plugins are registered
(session.go:90-112 vs framework.go:30-51), so the gate can never fire —
dead code upstream. Here the gate runs after plugin registration, making
gang's minMember validation actually reject invalid jobs at session open,
which is the documented intent (SURVEY.md section 2.4).
"""

from __future__ import annotations

import time
import uuid as _uuid
from typing import TYPE_CHECKING, Callable, Optional

if TYPE_CHECKING:
    # runtime import stays inside Session.statement() — statement.py
    # imports this module's Session for ITS annotations (same cycle)
    from kube_batch_tpu.framework.statement import Statement

from kube_batch_tpu import metrics, obs
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.types import TaskStatus, ValidateResult, allocated_status
from kube_batch_tpu.apis.types import (
    POD_GROUP_UNSCHEDULABLE_TYPE,
    PodGroupCondition,
    PodGroupPhase,
    PodGroupStatus,
)
from kube_batch_tpu.conf import Tier
from kube_batch_tpu.framework.event import Event, EventHandler
from kube_batch_tpu.framework.interface import Cache, Plugin
from kube_batch_tpu.framework.registry import get_plugin_builder


class Session:
    """reference session.go:37-63."""

    def __init__(self, cache: Cache) -> None:
        self.uid: str = str(_uuid.uuid4())
        self.cache = cache
        # Monotonic counter bumped by every session-state mutation
        # (allocate/pipeline/evict and Statement do/undo ops); plugins use
        # it to invalidate per-task caches (nodeorder's InterPodAffinity
        # memo) without recomputing per (task, node) call.
        self.state_seq: int = 0

        self.jobs: dict[str, JobInfo] = {}
        self.nodes: dict[str, NodeInfo] = {}
        self.queues: dict[str, QueueInfo] = {}
        self.tiers: list[Tier] = []
        # Per-action arguments from the conf's optional `actionArguments`
        # map (an extension over the reference schema — the reference has
        # no action-level knobs; ours carries e.g. xla_allocate's device
        # mesh selection). Keyed by action name.
        self.action_arguments: dict[str, dict[str, str]] = {}

        # Per-gang unschedulability forensics published by the allocate
        # actions when KBT_EXPLAIN is on (obs/explain.py); empty when
        # explain is off or no allocate action ran. Keyed by JobInfo.uid.
        # Read by the gang plugin (condition messages), the journal
        # intent writer, and the flight-recorder span summaries.
        self.explain_records: dict[str, dict] = {}

        # Pipelined cycles (KBT_PIPELINE): the Future of this session's
        # in-flight post-solve dispatch, set by xla_allocate when it
        # defers the phase onto the kb-write pool. close_session joins
        # it before the commit write-back; the scheduler's actions loop
        # joins it before running a later action over the same session.
        self.deferred_dispatch = None

        self.plugins: dict[str, Plugin] = {}
        self.event_handlers: list[EventHandler] = []
        self.job_order_fns: dict[str, Callable] = {}
        self.queue_order_fns: dict[str, Callable] = {}
        self.task_order_fns: dict[str, Callable] = {}
        self.predicate_fns: dict[str, Callable] = {}
        self.node_order_fns: dict[str, Callable] = {}
        self.node_map_fns: dict[str, Callable] = {}
        self.node_reduce_fns: dict[str, Callable] = {}
        self.preemptable_fns: dict[str, Callable] = {}
        self.reclaimable_fns: dict[str, Callable] = {}
        self.overused_fns: dict[str, Callable] = {}
        self.job_ready_fns: dict[str, Callable] = {}
        self.job_pipelined_fns: dict[str, Callable] = {}
        self.job_valid_fns: dict[str, Callable] = {}

    def bump_state(self) -> None:
        """THE session-state mutation hook: every allocate/pipeline/evict,
        Statement do/undo op, and the bulk replay advances ``state_seq``
        through here (never by touching the counter directly — analysis
        check KBT-R006 enforces it). One site means one place to observe
        mutation: plugin score memos key off the counter, and the
        streaming micro-cycle's task-block reuse depends on every
        mutation path bumping it."""
        self.state_seq += 1

    # -- fn registration (session_plugins.go:25-88) -------------------------

    def add_job_order_fn(self, name: str, fn: Callable) -> None:
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name: str, fn: Callable) -> None:
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name: str, fn: Callable) -> None:
        self.task_order_fns[name] = fn

    def add_predicate_fn(self, name: str, fn: Callable) -> None:
        self.predicate_fns[name] = fn

    def add_node_order_fn(self, name: str, fn: Callable) -> None:
        self.node_order_fns[name] = fn

    def add_node_map_fn(self, name: str, fn: Callable) -> None:
        self.node_map_fns[name] = fn

    def add_node_reduce_fn(self, name: str, fn: Callable) -> None:
        self.node_reduce_fns[name] = fn

    def add_preemptable_fn(self, name: str, fn: Callable) -> None:
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name: str, fn: Callable) -> None:
        self.reclaimable_fns[name] = fn

    def add_overused_fn(self, name: str, fn: Callable) -> None:
        self.overused_fns[name] = fn

    def add_job_ready_fn(self, name: str, fn: Callable) -> None:
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name: str, fn: Callable) -> None:
        self.job_pipelined_fns[name] = fn

    def add_job_valid_fn(self, name: str, fn: Callable) -> None:
        self.job_valid_fns[name] = fn

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # -- tiered dispatch ----------------------------------------------------

    def _victims(
        self,
        fns: dict[str, Callable],
        flag: str,
        evictor: TaskInfo,
        evictees: list[TaskInfo],
    ) -> list[TaskInfo]:
        """Tiered victim-set intersection (session_plugins.go:90-172):
        within a tier, victims = intersection across enabled plugins; the
        first tier whose intersection is non-empty wins. Go parity note:
        the reference's early return checks ``victims != nil``, but Go
        slices are nil whenever empty here — plugins build victim lists
        with append (nil when none) and so does the intersection — so an
        empty result always falls through to the next tier."""
        for tier in self.tiers:
            victims: Optional[list[TaskInfo]] = None
            for plugin in tier.plugins:
                if not getattr(plugin, flag, None):
                    continue
                fn = fns.get(plugin.name)
                if fn is None:
                    continue
                candidates = fn(evictor, evictees) or []
                if victims is None:
                    victims = list(candidates)
                else:
                    candidate_uids = {c.uid for c in candidates}
                    victims = [v for v in victims if v.uid in candidate_uids]
            if victims:
                return victims
        return []

    def preemptable(self, preemptor: TaskInfo, preemptees: list[TaskInfo]) -> list[TaskInfo]:
        return self._victims(self.preemptable_fns, "enabled_preemptable", preemptor, preemptees)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: list[TaskInfo]) -> list[TaskInfo]:
        return self._victims(self.reclaimable_fns, "enabled_reclaimable", reclaimer, reclaimees)

    def overused(self, queue: QueueInfo) -> bool:
        """OR across plugins (session_plugins.go:175-189; note the
        reference does not gate this on an enable flag)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.overused_fns.get(plugin.name)
                if fn is not None and fn(queue):
                    return True
        return False

    def job_ready(self, job: JobInfo) -> bool:
        """AND (session_plugins.go:192-210)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_job_ready:
                    continue
                fn = self.job_ready_fns.get(plugin.name)
                if fn is not None and not fn(job):
                    return False
        return True

    def job_pipelined(self, job: JobInfo) -> bool:
        """AND (session_plugins.go:213-231)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_job_pipelined:
                    continue
                fn = self.job_pipelined_fns.get(plugin.name)
                if fn is not None and not fn(job):
                    return False
        return True

    def job_valid(self, job: JobInfo) -> Optional[ValidateResult]:
        """First failure wins (session_plugins.go:234-250; note the
        reference does not gate this on an enable flag)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.job_valid_fns.get(plugin.name)
                if fn is None:
                    continue
                vr = fn(job)
                if vr is not None and not vr.passed:
                    return vr
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """First non-zero across tiers; fallback creation-time then UID
        (session_plugins.go:253-277)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_job_order:
                    continue
                fn = self.job_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        """session_plugins.go:280-305."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_queue_order:
                    continue
                fn = self.queue_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        """session_plugins.go:308-326."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_task_order:
                    continue
                fn = self.task_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        """session_plugins.go:329-341."""
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        lt = l.pod.metadata.creation_timestamp
        rt = r.pod.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """AND across enabled plugins; raises on first failure
        (session_plugins.go:344-361)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_predicate:
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is not None:
                    fn(task, node)  # raises PredicateError on failure

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        """Sum of scores (session_plugins.go:364-384)."""
        total = 0.0
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_node_order:
                    continue
                fn = self.node_order_fns.get(plugin.name)
                if fn is not None:
                    total += fn(task, node)
        return total

    def node_order_map_fn(self, task: TaskInfo, node: NodeInfo) -> tuple[dict[str, float], float]:
        """Map phase: per-plugin map scores + summed order score
        (session_plugins.go:391-417)."""
        node_score_map: dict[str, float] = {}
        order_score = 0.0
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_node_order:
                    continue
                fn = self.node_order_fns.get(plugin.name)
                if fn is not None:
                    order_score += fn(task, node)
                mfn = self.node_map_fns.get(plugin.name)
                if mfn is not None:
                    node_score_map[plugin.name] = mfn(task, node)
        return node_score_map, order_score

    def node_order_reduce_fn(
        self, task: TaskInfo, plugin_node_scores: dict[str, list[tuple[str, int]]]
    ) -> dict[str, float]:
        """Reduce phase: per-node sum after optional plugin normalization
        (session_plugins.go:420-440)."""
        node_scores: dict[str, float] = {}
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_node_order:
                    continue
                rfn = self.node_reduce_fns.get(plugin.name)
                if rfn is None:
                    continue
                scores = plugin_node_scores.get(plugin.name, [])
                rfn(task, scores)
                for host, score in scores:
                    node_scores[host] = node_scores.get(host, 0.0) + score
        return node_scores

    # -- session mutations (session.go:191-362) -----------------------------

    def statement(self) -> "Statement":
        from kube_batch_tpu.framework.statement import Statement

        return Statement(self)

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Assign onto releasing resources; session-only, no bind
        (session.go:198-238)."""
        self.bump_state()
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Allocate idle resources; dispatch the whole gang once JobReady
        (the gang barrier, session.go:241-296)."""
        self.bump_state()
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))
        if self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()):
                self._dispatch(t)

    def _dispatch(self, task: TaskInfo) -> None:
        """session.go:298-322. A failed volume bind routes the task
        through the cache's errTasks resync queue (self-heal: the task
        re-syncs to its store state and is rescheduled next cycle) and
        propagates, leaving later gang members undispatched exactly like
        the reference's early return."""
        try:
            self.cache.bind_volumes(task)
        except Exception:
            resync = getattr(self.cache, "resync_task", None)
            if resync is not None:
                resync(task)
            raise
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.BINDING)
        wait = max(0.0, time.time() - task.pod.metadata.creation_timestamp)
        metrics.update_task_schedule_duration(wait)
        obs.slo.observe("queue_wait", job.queue, wait)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """session.go:325-362."""
        self.bump_state()
        self.cache.evict(reclaimee, reason)
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(reclaimee))

    def update_job_condition(self, job_info: JobInfo, cond: PodGroupCondition) -> None:
        """Replace-or-append by condition type (session.go:365-387)."""
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(f"failed to find job {job_info.namespace}/{job_info.name}")
        conditions = job.pod_group.status.conditions
        for i, c in enumerate(conditions):
            if c.type == cond.type:
                conditions[i] = cond
                return
        conditions.append(cond)

    def __repr__(self) -> str:
        return (
            f"Session {self.uid}: jobs {len(self.jobs)}, nodes {len(self.nodes)}, "
            f"queues {len(self.queues)}"
        )


def _job_status(ssn: Session, job: JobInfo) -> PodGroupStatus:
    """Recompute PodGroup status at session close (session.go:150-188).
    Parity note: the reference phases to Running only when allocated is
    *strictly greater* than MinMember (session.go:176) — kept as-is."""
    status = job.pod_group.status
    unschedulable = any(
        c.type == POD_GROUP_UNSCHEDULABLE_TYPE
        and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions
    )
    if job.task_status_index.get(TaskStatus.RUNNING) and unschedulable:
        status.phase = PodGroupPhase.UNKNOWN
    else:
        allocated = sum(
            len(tasks)
            for st, tasks in job.task_status_index.items()
            if allocated_status(st)
        )
        if allocated > job.pod_group.spec.min_member:
            status.phase = PodGroupPhase.RUNNING
        elif job.pod_group.status.phase != PodGroupPhase.INQUEUE:
            status.phase = PodGroupPhase.PENDING
    status.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
    status.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
    status.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
    return status


def open_session(
    cache: Cache,
    tiers: list[Tier],
    action_arguments: Optional[dict[str, dict[str, str]]] = None,
    world: Optional[tuple[dict, dict, dict]] = None,
) -> Session:
    """Snapshot + plugin instantiation + JobValid gate
    (framework.go:30-51 + session.go:66-119; gate ordering fixed, see
    module docstring).

    ``world`` — an explicit ``(jobs, nodes, queues)`` triple instead of a
    fresh ``cache.snapshot()``. The streaming micro-cycle passes its
    restricted dirty-gang job clones plus the resident node table here
    (kube_batch_tpu.streaming); everything downstream (plugin
    registration, JobValid gate, actions, close_session) is identical to
    a full cycle."""
    ssn = Session(cache)
    ssn.tiers = tiers
    ssn.action_arguments = action_arguments or {}

    if world is None:
        with obs.span("snapshot") as sspan:
            snapshot = cache.snapshot()
            ssn.jobs = snapshot.jobs
            ssn.nodes = snapshot.nodes
            ssn.queues = snapshot.queues
            sspan.set_attr("jobs", len(ssn.jobs))
            sspan.set_attr("nodes", len(ssn.nodes))
    else:
        ssn.jobs, ssn.nodes, ssn.queues = world

    for tier in tiers:
        for option in tier.plugins:
            builder = get_plugin_builder(option.name)
            if builder is None:
                continue
            from kube_batch_tpu.framework.arguments import Arguments

            plugin = builder(Arguments(option.arguments))
            ssn.plugins[plugin.name] = plugin

    for plugin in ssn.plugins.values():
        start = time.perf_counter()
        plugin.on_session_open(ssn)
        metrics.update_plugin_duration(plugin.name, "OnSessionOpen", time.perf_counter() - start)

    # JobValid gate: reject invalid jobs (gang minMember) and mark them
    # Unschedulable (session.go:90-112). Pending-phase PodGroups are
    # exempt: their pods may not exist yet ("delay pod creation") — they
    # are the enqueue action's input, and every other action skips them
    # anyway (allocate.go:53-55 etc.).
    for job in list(ssn.jobs.values()):
        if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            if job.pod_group is not None:
                ssn.update_job_condition(
                    job,
                    PodGroupCondition(
                        type=POD_GROUP_UNSCHEDULABLE_TYPE,
                        status="True",
                        transition_id=ssn.uid,
                        last_transition_time=time.time(),
                        reason=vr.reason,
                        message=vr.message,
                    ),
                )
            del ssn.jobs[job.uid]
    return ssn


def close_session(ssn: Session, discard: bool = False) -> None:
    """Plugin close hooks + PodGroup status write-back
    (framework.go:55-63 + session.go:123-148). With ``discard`` (a
    hard-deadline cycle abort, recovery/budget.py) the write-back is
    skipped: the aborted cycle's session state is rolled back wholesale
    — Statement.discard at cycle granularity — leaving the cache/store
    byte-identical to the cycle's start."""
    # Pipelined cycles (KBT_PIPELINE): a deferred post-solve dispatch
    # must land before anything below — the plugin close hooks and the
    # commit write-back read the session state the deferred replay
    # mutates, and job status must describe binds that actually
    # happened. A dispatch failure closes the session like the
    # synchronous path would (logged, no binds beyond what landed) and
    # degrades the pipeline loudly.
    if getattr(ssn, "deferred_dispatch", None) is not None:
        from kube_batch_tpu import log, pipeline

        try:
            pipeline.join_session(ssn)
        except Exception as e:  # noqa: BLE001 - parity with sync-path logging
            log.errorf(
                "deferred dispatch failed while closing session %s: %s", ssn.uid, e
            )
            pipeline.fence.degrade(
                f"deferred dispatch raised {type(e).__name__}: {e}"
            )

    for plugin in ssn.plugins.values():
        start = time.perf_counter()
        plugin.on_session_close(ssn)
        metrics.update_plugin_duration(plugin.name, "OnSessionClose", time.perf_counter() - start)

    if not discard:
        with obs.span("commit", jobs=len(ssn.jobs)):
            for job in ssn.jobs.values():
                if job.pod_group is None:
                    ssn.cache.record_job_status_event(job)
                    continue
                job.pod_group.status = _job_status(ssn, job)
                ssn.cache.update_job_status(job)

    ssn.jobs = {}
    ssn.nodes = {}
    ssn.queues = {}
    ssn.plugins = {}
    ssn.event_handlers = []
