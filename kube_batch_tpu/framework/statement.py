"""Statement: operation log for speculative preemption
(reference pkg/scheduler/framework/statement.go:26-222).

``evict``/``pipeline`` apply session-state changes immediately and append
ops; ``commit`` replays evictions against the real cache (pipelines need
no cache action); ``discard`` undoes everything in reverse order.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.event import Event

if TYPE_CHECKING:
    from kube_batch_tpu.framework.session import Session


class Statement:
    def __init__(self, ssn: "Session") -> None:
        self._ssn = ssn
        self._operations: list[tuple[str, tuple]] = []

    # -- speculative ops (session state only) -------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """statement.go:37-69: mark Releasing in session, log the op."""
        ssn = self._ssn
        ssn.bump_state()
        job = ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        for eh in ssn.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(reclaimee))
        self._operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """statement.go:113-154."""
        ssn = self._ssn
        ssn.bump_state()
        job = ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        for eh in ssn.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))
        self._operations.append(("pipeline", (task, hostname)))

    # -- undo helpers -------------------------------------------------------

    def _unevict(self, reclaimee: TaskInfo) -> None:
        """statement.go:83-110: restore the victim to Running."""
        ssn = self._ssn
        ssn.bump_state()
        job = ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
        node = ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        for eh in ssn.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(reclaimee))

    def _unpipeline(self, task: TaskInfo) -> None:
        """statement.go:159-195: back to Pending, off the node."""
        ssn = self._ssn
        ssn.bump_state()
        job = ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        for eh in ssn.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    # -- terminal -----------------------------------------------------------

    def commit(self) -> None:
        """Replay evictions against the real cache (statement.go:212-222);
        a failed cache evict is rolled back in session state (:71-81)."""
        for name, args in self._operations:
            if name == "evict":
                reclaimee, reason = args
                try:
                    self._ssn.cache.evict(reclaimee, reason)
                except Exception:
                    self._unevict(reclaimee)

    def discard(self) -> None:
        """Undo in reverse order (statement.go:198-209)."""
        for name, args in reversed(self._operations):
            if name == "evict":
                self._unevict(args[0])
            elif name == "pipeline":
                self._unpipeline(args[0])
