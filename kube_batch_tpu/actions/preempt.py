"""preempt action: Statement-wrapped speculative preemption for starved
jobs (reference pkg/scheduler/actions/preempt/preempt.go:45-273).

`run_preempt` is the whole control flow — queue-by-queue preemptor heaps,
Statement speculation with commit/discard, the intra-job pass —
parameterized over how Statements are built and how candidate nodes are
scanned, so the serial action here and the vectorized xla_preempt action
share one driver instead of diverging copies.
"""

from __future__ import annotations

from typing import Callable, Optional

from kube_batch_tpu import log, metrics
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.framework.statement import Statement
from kube_batch_tpu.utils import (
    PriorityQueue,
    get_node_list,
    predicate_nodes,
    prioritize_nodes,
    sort_nodes,
)

# candidates(ssn, preemptor) -> nodes to try, best-scored first
CandidatesFn = Callable[[Session, TaskInfo], list[NodeInfo]]
StatementFactory = Callable[[Session], Statement]


def serial_candidates(ssn: Session, preemptor: TaskInfo) -> list[NodeInfo]:
    """The reference scan: PredicateNodes + PrioritizeNodes + SortNodes
    (preempt.go:185-191) over every node."""
    all_nodes = get_node_list(ssn.nodes)
    cands = predicate_nodes(preemptor, all_nodes, lambda t, n: ssn.predicate_fn(t, n))
    return sort_nodes(
        prioritize_nodes(
            preemptor, cands, ssn.node_order_map_fn, ssn.node_order_reduce_fn
        )
    )


def _validate_victims(victims: list[TaskInfo], resreq: Resource) -> Optional[str]:
    """preempt.go:258-273."""
    if not victims:
        return "no victims"
    all_res = Resource.empty()
    for v in victims:
        all_res.add(v.resreq)
    if all_res.less(resreq):
        return "not enough resources"
    return None


def _preempt(
    ssn: Session,
    stmt: Statement,
    preemptor: TaskInfo,
    filter_fn: Callable[[TaskInfo], bool],
    candidates_fn: CandidatesFn,
) -> bool:
    """One preemptor against candidate nodes (preempt.go:176-256)."""
    for node in candidates_fn(ssn, preemptor):
        preemptees = [task.clone() for task in node.tasks.values() if filter_fn(task)]
        victims = ssn.preemptable(preemptor, preemptees)
        metrics.update_preemption_victims_count(len(victims))

        resreq = preemptor.init_resreq.clone()
        if _validate_victims(victims, resreq) is not None:
            continue

        # Evict lowest-priority victims first until covered (preempt.go:215-236).
        victims_queue = PriorityQueue(lambda l, r: not ssn.task_order_fn(l, r))
        for victim in victims:
            victims_queue.push(victim)
        preempted = Resource.empty()
        while not victims_queue.empty():
            preemptee = victims_queue.pop()
            log.V(3).infof(
                "evicting task <%s/%s> for preemptor <%s/%s>",
                preemptee.namespace, preemptee.name,
                preemptor.namespace, preemptor.name,
            )
            stmt.evict(preemptee, "preempt")
            preempted.add(preemptee.resreq)
            if resreq.less_equal(preempted):
                break

        metrics.register_preemption_attempts()

        if preemptor.init_resreq.less_equal(preempted):
            log.V(3).infof(
                "preempted <%s> on node <%s> for task <%s/%s>",
                preempted, node.name, preemptor.namespace, preemptor.name,
            )
            stmt.pipeline(preemptor, node.name)
            return True

    return False


def run_preempt(
    ssn: Session,
    statement_factory: StatementFactory = Statement,
    candidates_fn: CandidatesFn = serial_candidates,
) -> None:
    """The full preempt pass (preempt.go:58-170)."""
    preemptors_map: dict[str, PriorityQueue] = {}
    preemptor_tasks: dict[str, PriorityQueue] = {}
    under_request: list[JobInfo] = []
    queues: dict[str, object] = {}

    for job in ssn.jobs.values():
        if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
            continue
        queue = ssn.queues.get(job.queue)
        if queue is None:
            continue
        queues.setdefault(queue.name, queue)
        if job.task_status_index.get(TaskStatus.PENDING):
            if job.queue not in preemptors_map:
                preemptors_map[job.queue] = PriorityQueue(ssn.job_order_fn)
            preemptors_map[job.queue].push(job)
            under_request.append(job)
            preemptor_tasks[job.uid] = PriorityQueue(ssn.task_order_fn)
            for task in job.task_status_index[TaskStatus.PENDING].values():
                preemptor_tasks[job.uid].push(task)

    for queue in queues.values():
        # Preemption between jobs within the queue (preempt.go:81-135).
        while True:
            preemptors = preemptors_map.get(queue.name)
            if preemptors is None or preemptors.empty():
                break
            preemptor_job = preemptors.pop()

            stmt = statement_factory(ssn)
            assigned = False
            while True:
                if preemptor_tasks[preemptor_job.uid].empty():
                    break
                preemptor = preemptor_tasks[preemptor_job.uid].pop()

                def job_filter(task: TaskInfo) -> bool:
                    # Running victims of *other* jobs in the same queue
                    # (preempt.go:106-118).
                    if task.status != TaskStatus.RUNNING:
                        return False
                    victim_job = ssn.jobs.get(task.job)
                    if victim_job is None:
                        return False
                    return (
                        victim_job.queue == preemptor_job.queue
                        and preemptor.job != task.job
                    )

                if _preempt(ssn, stmt, preemptor, job_filter, candidates_fn):
                    assigned = True

                if ssn.job_pipelined(preemptor_job):
                    break

            # Settle the statement on every way out of the task loop:
            # the empty-queue break could previously leak it open when
            # the job was already pipelined (its evictions then never
            # replayed to the cache).
            if ssn.job_pipelined(preemptor_job):
                stmt.commit()
            else:
                stmt.discard()
                continue

            if assigned:
                preemptors.push(preemptor_job)

        # Preemption between tasks within one job (preempt.go:138-170).
        for job in under_request:
            while True:
                tasks = preemptor_tasks.get(job.uid)
                if tasks is None or tasks.empty():
                    break
                preemptor = tasks.pop()

                def intra_job_filter(task: TaskInfo) -> bool:
                    if task.status != TaskStatus.RUNNING:
                        return False
                    return preemptor.job == task.job

                stmt = statement_factory(ssn)
                assigned = _preempt(ssn, stmt, preemptor, intra_job_filter, candidates_fn)
                stmt.commit()
                if not assigned:
                    break


class PreemptAction(Action):
    @property
    def name(self) -> str:
        return "preempt"

    def execute(self, ssn: Session) -> None:
        run_preempt(ssn)


def new() -> Action:
    return PreemptAction()
