"""allocate action: the two-level fair scheduling loop
(reference pkg/scheduler/actions/allocate/allocate.go:44-191).

Queue heap by QueueOrderFn, per-queue job heap by JobOrderFn, per-job task
heap by TaskOrderFn; per task: resource-fit + plugin predicates over all
nodes, score, best node; fits Idle -> allocate, else record NodesFitDelta
and, if it fits Releasing, pipeline. Jobs are re-pushed when JobReady
(gang barrier), queues round-robin until drained.

This serial loop is the correctness oracle for the vectorized
``xla_allocate`` action (kube_batch_tpu.actions.xla_allocate), which
replaces the inner per-task node scan (HOT LOOP #1/#2,
scheduler_helper.go:34-109) with one jitted feasibility/score/argmax per
job batch.
"""

from __future__ import annotations

from kube_batch_tpu import log, obs
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.plugins.predicates import PredicateError
from kube_batch_tpu.utils import (
    PriorityQueue,
    get_node_list,
    predicate_nodes,
    prioritize_nodes,
    select_best_node,
)


class AllocateAction(Action):
    @property
    def name(self) -> str:
        return "allocate"

    def execute(self, ssn: Session) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        jobs_map: dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            # Pending PodGroups wait for the enqueue action (allocate.go:53-55).
            if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
                continue
            if job.queue not in ssn.queues:
                continue
            queues.push(ssn.queues[job.queue])
            if job.queue not in jobs_map:
                jobs_map[job.queue] = PriorityQueue(ssn.job_order_fn)
            jobs_map[job.queue].push(job)

        pending_tasks: dict[str, PriorityQueue] = {}
        all_nodes = get_node_list(ssn.nodes)

        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            # Resource fit on Idle OR Releasing, then plugin predicates
            # (allocate.go:78-92).
            if not task.init_resreq.less_equal(node.idle) and not task.init_resreq.less_equal(
                node.releasing
            ):
                raise PredicateError(
                    f"task <{task.namespace}/{task.name}> ResourceFit failed "
                    f"on node <{node.name}>"
                )
            ssn.predicate_fn(task, node)

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue

            jobs = jobs_map.get(queue.name)
            if jobs is None or jobs.empty():
                continue

            job = jobs.pop()
            if job.uid not in pending_tasks:
                tasks = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(TaskStatus.PENDING, {}).values():
                    # BestEffort tasks are backfill's business (allocate.go:120-125).
                    if task.resreq.is_empty():
                        continue
                    tasks.push(task)
                pending_tasks[job.uid] = tasks
            tasks = pending_tasks[job.uid]

            while not tasks.empty():
                task = tasks.pop()

                # Only the last non-fitting task's deltas survive the loop
                # (allocate.go:139-145).
                if job.nodes_fit_delta:
                    job.nodes_fit_delta = {}

                candidates = predicate_nodes(task, all_nodes, predicate_fn)
                if not candidates:
                    log.V(3).infof(
                        "no node fits task <%s/%s>; job <%s> leaves the cycle",
                        task.namespace, task.name, job.name,
                    )
                    break

                node_scores = prioritize_nodes(
                    task, candidates, ssn.node_order_map_fn, ssn.node_order_reduce_fn
                )
                node = select_best_node(node_scores)

                if task.init_resreq.less_equal(node.idle):
                    log.V(3).infof(
                        "binding task <%s/%s> to node <%s>",
                        task.namespace, task.name, node.name,
                    )
                    try:
                        ssn.allocate(task, node.name)
                    except Exception as e:  # noqa: BLE001
                        # reference allocate.go:158-161: log and move on —
                        # a volume-assume or dispatch failure must not
                        # kill the cycle; the task stays unallocated.
                        log.errorf(
                            "Failed to allocate task %s on %s: %s",
                            task.uid, node.name, e,
                        )
                else:
                    # Record the miss, try the releasing pool (allocate.go:162-180).
                    delta = node.idle.clone()
                    delta.fit_delta(task.init_resreq)
                    job.nodes_fit_delta[node.name] = delta
                    if task.init_resreq.less_equal(node.releasing):
                        log.V(3).infof(
                            "pipelining task <%s/%s> onto releasing node <%s>",
                            task.namespace, task.name, node.name,
                        )
                        ssn.pipeline(task, node.name)

                if ssn.job_ready(job):
                    jobs.push(job)
                    break

            # Round-robin the queue until it has no jobs left (allocate.go:189).
            queues.push(queue)

        # Post-solve forensics (obs/explain): the serial action is the
        # correctness-oracle side of explain parity, re-encoding the
        # closed-over world and walking the planes task by task. Covers
        # both direct serial confs and every xla_allocate fallback.
        from kube_batch_tpu.obs import explain as _explain

        if _explain.enabled():
            with obs.span("explain") as sp:
                recs = _explain.explain_session(ssn)
                _explain.publish(ssn, recs)
                for k, v in _explain.summary(recs).items():
                    sp.set_attr(k, v)


def new() -> Action:
    return AllocateAction()
