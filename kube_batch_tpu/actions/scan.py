"""Vectorized candidate-node scan shared by the xla_preempt and
xla_reclaim actions.

The serial preempt/reclaim hot loop is the same per-task node walk as
allocate's (predicate -> [score ->] select, reference
scheduler_helper.go:34-109 / reclaim.go:113-128). `VectorScan` replaces
it with one float64 numpy pass over the encoder's dedup'd matrices —
bit-identical to the serial float64 oracle including score tie-breaks —
plus incremental mirrors of the scan-visible dynamic node state (pod
count, host ports, Used cpu/mem). Only `pipeline`/`unpipeline` move those
quantities (an evict flips a resident Running->Releasing, which changes
none of them — node_info.go:168-174), so `ScanStatement` keeps the
mirrors in sync through Statement rollbacks and direct-evict actions need
no hooks at all.

Host-only tasks (required pod affinity), ports beyond the 63-bit mask,
and snapshots with live InterPodAffinity scores fall back to the serial
walk per task.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.framework.statement import Statement



class VectorScan:
    """Vectorized predicate + score scan over the node axis.

    Wraps the encoder's dedup'd matrices with float64 mirrors of the
    scan-visible dynamic node state (pod count, host ports, Used cpu/mem).
    `candidates(task)` reproduces predicate_nodes + prioritize_nodes +
    sort_nodes for one task; returns None for host-only tasks (required
    pod affinity) so the caller can scan serially.
    """

    def __init__(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.xla_allocate import _nodeorder_weights
        from kube_batch_tpu.ops.encode import encode_session

        enc = encode_session(
            ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64, session=ssn
        )
        self.enc = enc
        a = enc.arrays
        N = enc.n_nodes
        self.node_list = [ssn.nodes[name] for name in enc.node_names]
        self.node_row = {name: i for i, name in enumerate(enc.node_names)}
        self.task_row = {t.uid: i for i, t in enumerate(enc.tasks)}
        self.task_gid = np.asarray(a["task_gid"])
        self.host_only = np.asarray(a["task_host_only"])
        self.compat = np.asarray(a["compat"])
        self.aff_sc = np.asarray(a["aff_sc"], np.float64)
        self.node_gid = np.asarray(a["node_gid"])[:N]
        self.node_ok = np.asarray(a["node_ok"])[:N]
        self.max_tasks = np.asarray(a["node_max_tasks"])[:N]
        self.cap_cpu = np.asarray(a["node_alloc"], np.float64)[:N, 0]
        self.cap_mem = np.asarray(a["node_alloc"], np.float64)[:N, 1]
        # dynamic mirrors (see module docstring)
        self.ntasks = np.asarray(a["node_ntasks"])[:N].copy()
        P = a["task_ports"].shape[1]
        # int64 bitmask: shifting by >= 64 silently yields 0 in numpy, so
        # beyond 63 distinct host ports every task scans serially instead;
        # live InterPodAffinity scores (pod-affinity terms anywhere) are
        # resident-dependent and recomputable only against the live
        # session, so those snapshots scan serially too
        self.disabled = P > 63 or enc.interpod_active
        bits = 1 << np.arange(min(P, 63), dtype=np.int64)
        ports = np.asarray(a["task_ports"])[:, : min(P, 63)]
        self.task_ports = (ports * bits).sum(axis=1)
        self.node_ports = (
            np.asarray(a["node_ports"])[:N, : min(P, 63)] * bits
        ).sum(axis=1)
        self.used_cpu = np.asarray(a["node_used"], np.float64)[:N, 0].copy()
        self.used_mem = np.asarray(a["node_used"], np.float64)[:N, 1].copy()
        self.rowidx = np.arange(N)
        self.w_least, self.w_balanced, self.w_aff, _ = _nodeorder_weights(ssn)

    def _mask(self, task: TaskInfo):
        """Predicate verdict over all nodes, or None for serial fallback."""
        if self.disabled:
            return None
        row = self.task_row.get(task.uid)
        if row is None or self.host_only[row]:
            return None
        g = int(self.task_gid[row])
        return (
            self.compat[g, self.node_gid]
            & self.node_ok
            & (self.ntasks < self.max_tasks)
            & ((self.task_ports[row] & self.node_ports) == 0)
        )

    def feasible(self, task: TaskInfo) -> Optional[list[NodeInfo]]:
        """Predicate-passing nodes in name order — the reclaim walk
        (reclaim.go:113-128 iterates nodes without scoring)."""
        cand = self._mask(task)
        if cand is None:
            return None
        return [self.node_list[r] for r in np.nonzero(cand)[0]]

    def candidates(self, task: TaskInfo) -> Optional[list[NodeInfo]]:
        cand = self._mask(task)
        if cand is None:
            return None
        row = self.task_row[task.uid]
        g = int(self.task_gid[row])
        if not cand.any():
            return []

        # nodeorder score, float64-identical to plugins/nodeorder.py
        from kube_batch_tpu.plugins.nodeorder import vectorized_least_balanced

        least, balanced = vectorized_least_balanced(
            self.used_cpu + task.resreq.milli_cpu,
            self.used_mem + task.resreq.memory,
            self.cap_cpu,
            self.cap_mem,
        )
        score = (
            least * self.w_least
            + balanced * self.w_balanced
            + self.aff_sc[g, self.node_gid] * self.w_aff
        )
        # sort_nodes order: score desc, ties by node row (= name order)
        order = np.lexsort((self.rowidx, -score))
        order = order[cand[order]]
        return [self.node_list[r] for r in order]

    # -- Statement-visible mutations --------------------------------------

    def on_pipeline(self, task: TaskInfo, hostname: str) -> None:
        n = self.node_row[hostname]
        self.ntasks[n] += 1
        self.used_cpu[n] += task.resreq.milli_cpu
        self.used_mem[n] += task.resreq.memory
        row = self.task_row.get(task.uid)
        if row is not None:
            self.node_ports[n] |= self.task_ports[row]

    def on_unpipeline(self, task: TaskInfo, hostname: str) -> None:
        n = self.node_row[hostname]
        self.ntasks[n] -= 1
        self.used_cpu[n] -= task.resreq.milli_cpu
        self.used_mem[n] -= task.resreq.memory
        row = self.task_row.get(task.uid)
        if row is not None:
            # exclusive holder: two tasks with the same host port can never
            # co-reside (the predicate forbids it), so clearing is exact
            self.node_ports[n] &= ~self.task_ports[row]


class ScanStatement(Statement):
    """Statement that keeps the vector scan's node mirrors in sync."""

    def __init__(self, ssn: Session, scan: VectorScan) -> None:
        super().__init__(ssn)
        self._scan = scan

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        super().pipeline(task, hostname)
        self._scan.on_pipeline(task, hostname)

    def _unpipeline(self, task: TaskInfo) -> None:
        hostname = task.node_name
        super()._unpipeline(task)
        self._scan.on_unpipeline(task, hostname)


