"""xla_preempt action: preempt with a vectorized candidate-node scan.

The serial preempt action's hot loop is the same per-task node scan as
allocate's (reference pkg/scheduler/actions/preempt/preempt.go:176-256:
`util.PredicateNodes` + `util.PrioritizeNodes` over every node for every
starved preemptor task, 16-goroutine fan-out in Go). This action keeps
the reference's control flow — queue-by-queue preemptor heaps, Statement
speculation with commit/discard, victim selection by task order
(preempt.go:81-170) — entirely host-side, and replaces only the
per-preemptor node scan with one vectorized pass over the encoder's
(task-group x node-group) predicate matrices and the nodeorder score
formulas.

Design note (SURVEY.md section 7(b)): unlike the allocate solve — a
>50k-iteration sequential loop that lives on-device as a fused Pallas
kernel (ops/pallas_solve.py) — the preempt scan is one O(N x R) data-
parallel pass per preemptor with Statement mutations between scans. At
cluster sizes (N <= 100k nodes) that pass is microseconds of SIMD work,
far below a single host<->device round-trip, so it runs as float64 numpy:
bit-identical to the serial float64 oracle (including score tie-breaks),
which keeps `xla_preempt ≡ preempt` exact rather than
float32-approximate. The matrices it reads are the same ones the device
path consumes (ops/encode.py).

Scan-visible dynamic state: a Statement changes node residency only
through `pipeline` (evict flips a resident Running->Releasing, which
changes neither pod count, ports, nor Used — node_info.go:168-174), so
the mirror updates on pipeline/unpipeline alone; `_ScanStatement` keeps
it in sync through discard rollbacks.

Tasks whose pod spec carries required pod (anti-)affinity are pairwise-
dynamic (predicates.go:187-199) and scan serially, exactly like the
allocate hybrid routes them host-side.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.framework.statement import Statement

MAX_PRIORITY = 10


class _VectorScan:
    """Vectorized predicate + score scan over the node axis.

    Wraps the encoder's dedup'd matrices with float64 mirrors of the
    scan-visible dynamic node state (pod count, host ports, Used cpu/mem).
    `candidates(task)` reproduces predicate_nodes + prioritize_nodes +
    sort_nodes for one task; returns None for host-only tasks (required
    pod affinity) so the caller can scan serially.
    """

    def __init__(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.xla_allocate import _nodeorder_weights
        from kube_batch_tpu.ops.encode import encode_session

        enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=np.float64)
        self.enc = enc
        a = enc.arrays
        N = enc.n_nodes
        self.node_list = [ssn.nodes[name] for name in enc.node_names]
        self.node_row = {name: i for i, name in enumerate(enc.node_names)}
        self.task_row = {t.uid: i for i, t in enumerate(enc.tasks)}
        self.task_gid = np.asarray(a["task_gid"])
        self.host_only = np.asarray(a["task_host_only"])
        self.compat = np.asarray(a["compat"])
        self.aff_sc = np.asarray(a["aff_sc"], np.float64)
        self.node_gid = np.asarray(a["node_gid"])[:N]
        self.node_ok = np.asarray(a["node_ok"])[:N]
        self.max_tasks = np.asarray(a["node_max_tasks"])[:N]
        self.cap_cpu = np.asarray(a["node_alloc"], np.float64)[:N, 0]
        self.cap_mem = np.asarray(a["node_alloc"], np.float64)[:N, 1]
        # dynamic mirrors (see module docstring)
        self.ntasks = np.asarray(a["node_ntasks"])[:N].copy()
        P = a["task_ports"].shape[1]
        # int64 bitmask: shifting by >= 64 silently yields 0 in numpy, so
        # beyond 63 distinct host ports every task scans serially instead
        self.disabled = P > 63
        bits = 1 << np.arange(min(P, 63), dtype=np.int64)
        ports = np.asarray(a["task_ports"])[:, : min(P, 63)]
        self.task_ports = (ports * bits).sum(axis=1)
        self.node_ports = (
            np.asarray(a["node_ports"])[:N, : min(P, 63)] * bits
        ).sum(axis=1)
        self.used_cpu = np.asarray(a["node_used"], np.float64)[:N, 0].copy()
        self.used_mem = np.asarray(a["node_used"], np.float64)[:N, 1].copy()
        self.rowidx = np.arange(N)
        self.w_least, self.w_balanced, self.w_aff = _nodeorder_weights(ssn)

    def candidates(self, task: TaskInfo) -> Optional[list[NodeInfo]]:
        if self.disabled:
            return None
        row = self.task_row.get(task.uid)
        if row is None or self.host_only[row]:
            return None
        g = int(self.task_gid[row])
        cand = (
            self.compat[g, self.node_gid]
            & self.node_ok
            & (self.ntasks < self.max_tasks)
            & ((self.task_ports[row] & self.node_ports) == 0)
        )
        if not cand.any():
            return []

        # nodeorder score, float64-identical to plugins/nodeorder.py
        req_cpu = self.used_cpu + task.resreq.milli_cpu
        req_mem = self.used_mem + task.resreq.memory

        def least_dim(rq, cp):
            safe = np.where(cp == 0.0, 1.0, cp)
            sc = np.floor_divide((cp - rq) * MAX_PRIORITY, safe)
            return np.where((cp == 0.0) | (rq > cp), 0.0, sc)

        least = np.floor_divide(
            least_dim(req_cpu, self.cap_cpu) + least_dim(req_mem, self.cap_mem), 2.0
        )
        cpu_f = np.where(
            self.cap_cpu != 0.0, req_cpu / np.where(self.cap_cpu == 0.0, 1.0, self.cap_cpu), 1.0
        )
        mem_f = np.where(
            self.cap_mem != 0.0, req_mem / np.where(self.cap_mem == 0.0, 1.0, self.cap_mem), 1.0
        )
        balanced = np.where(
            (cpu_f >= 1.0) | (mem_f >= 1.0),
            0.0,
            np.trunc(MAX_PRIORITY - np.abs(cpu_f - mem_f) * MAX_PRIORITY),
        )
        score = (
            least * self.w_least
            + balanced * self.w_balanced
            + self.aff_sc[g, self.node_gid] * self.w_aff
        )
        # sort_nodes order: score desc, ties by node row (= name order)
        order = np.lexsort((self.rowidx, -score))
        order = order[cand[order]]
        return [self.node_list[r] for r in order]

    # -- Statement-visible mutations --------------------------------------

    def on_pipeline(self, task: TaskInfo, hostname: str) -> None:
        n = self.node_row[hostname]
        self.ntasks[n] += 1
        self.used_cpu[n] += task.resreq.milli_cpu
        self.used_mem[n] += task.resreq.memory
        row = self.task_row.get(task.uid)
        if row is not None:
            self.node_ports[n] |= self.task_ports[row]

    def on_unpipeline(self, task: TaskInfo, hostname: str) -> None:
        n = self.node_row[hostname]
        self.ntasks[n] -= 1
        self.used_cpu[n] -= task.resreq.milli_cpu
        self.used_mem[n] -= task.resreq.memory
        row = self.task_row.get(task.uid)
        if row is not None:
            # exclusive holder: two tasks with the same host port can never
            # co-reside (the predicate forbids it), so clearing is exact
            self.node_ports[n] &= ~self.task_ports[row]


class _ScanStatement(Statement):
    """Statement that keeps the vector scan's node mirrors in sync."""

    def __init__(self, ssn: Session, scan: _VectorScan) -> None:
        super().__init__(ssn)
        self._scan = scan

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        super().pipeline(task, hostname)
        self._scan.on_pipeline(task, hostname)

    def _unpipeline(self, task: TaskInfo) -> None:
        hostname = task.node_name
        super()._unpipeline(task)
        self._scan.on_unpipeline(task, hostname)


class XlaPreemptAction(Action):
    """Drop-in replacement for the serial preempt action (conf
    ``actions: "...,xla_preempt,..."``): the shared run_preempt driver
    (actions/preempt.py) with the vectorized node scan and the
    mirror-syncing Statement."""

    @property
    def name(self) -> str:
        return "xla_preempt"

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.preempt import run_preempt, serial_candidates

        scan = _VectorScan(ssn)

        def candidates(s: Session, preemptor: TaskInfo):
            selected = scan.candidates(preemptor)
            if selected is None:
                # host-only task (required pod affinity / scan disabled):
                # the serial predicate walk, allocate-hybrid twin
                return serial_candidates(s, preemptor)
            return selected

        run_preempt(
            ssn,
            statement_factory=lambda s: _ScanStatement(s, scan),
            candidates_fn=candidates,
        )


def new() -> Action:
    return XlaPreemptAction()
