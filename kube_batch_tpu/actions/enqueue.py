"""enqueue action: gate Pending PodGroups into Inqueue when the cluster's
(1.2x overcommitted) idle headroom covers their MinResources
(reference pkg/scheduler/actions/enqueue/enqueue.go:42-128; design doc
doc/design/delay-pod-creation.md)."""

from __future__ import annotations

from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu.utils import PriorityQueue

OVERCOMMIT_FACTOR = 1.2  # enqueue.go:80


class EnqueueAction(Action):
    @property
    def name(self) -> str:
        return "enqueue"

    def execute(self, ssn: Session) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        seen_queues: set[str] = set()
        jobs_map: dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.name not in seen_queues:
                seen_queues.add(queue.name)
                queues.push(queue)
            if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
                if job.queue not in jobs_map:
                    jobs_map[job.queue] = PriorityQueue(ssn.job_order_fn)
                jobs_map[job.queue].push(job)

        # Idle headroom with 1.2x overcommit (enqueue.go:78-82) —
        # computed lazily: it is only consumed by the MinResources
        # admission branch (jobs whose pods don't exist yet), and the
        # per-node Resource churn is the action's whole cost on big
        # clusters (streaming micro-cycles run this action per arrival,
        # where every gang has pods and the sweep would be dead work).
        empty = Resource.empty()
        nodes_idle: Resource = None  # type: ignore[assignment]

        def idle() -> Resource:
            nonlocal nodes_idle
            if nodes_idle is None:
                nodes_idle = Resource.empty()
                for node in ssn.nodes.values():
                    nodes_idle.add(
                        node.allocatable.clone().multi(OVERCOMMIT_FACTOR).sub(node.used)
                    )
            return nodes_idle

        while not queues.empty():
            # per-node overcommitted idle is never negative, so the sum
            # only goes negative after a MinResources subtraction — no
            # need to force the sweep just for this check
            if nodes_idle is not None and nodes_idle.less(empty):
                break
            queue = queues.pop()
            jobs = jobs_map.get(queue.name)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            inqueue = False
            if job.task_status_index.get(TaskStatus.PENDING):
                # Pods already exist: always admit (enqueue.go:106-108).
                inqueue = True
            elif job.pod_group.spec.min_resources is None:
                inqueue = True
            else:
                pg_resource = Resource.from_resource_list(job.pod_group.spec.min_resources)
                if pg_resource.less_equal(idle()):
                    idle().sub(pg_resource)
                    inqueue = True

            if inqueue:
                job.pod_group.status.phase = PodGroupPhase.INQUEUE

            queues.push(queue)


def new() -> Action:
    return EnqueueAction()
