"""xla_backfill action: BestEffort placement with a vectorized scan.

The serial backfill walks every node per zero-request pending task,
running the full predicate chain inline until the first feasible node
(reference pkg/scheduler/actions/backfill/backfill.go:41-76 — no
scoring, first hit in node order wins). That is O(tasks x nodes)
Python predicate calls for work whose per-node verdict depends only on
the task's (selector, affinity, tolerations, ports) signature and the
node's (labels, taints, cordon) signature plus two dynamic counters
(pod count, host-port occupancy).

This action computes the verdicts once per (task-group x node-group)
pair — the encoder's dedup idea (ops/encode.py) applied to the
backfill predicate subset — and walks tasks in the serial order,
picking the first node whose group verdict + dynamic counters pass,
then calling ``ssn.allocate`` exactly as the serial loop does (same
session machinery, same events, same metrics). Session state therefore
stays live: tasks with required pod (anti-)affinity terms — whose
verdict is pairwise over residents (predicates.go:187-199) — walk the
serial predicate chain per task against that live state, and >63
distinct host ports disable the bitmask (the VectorScan convention).
Out-of-envelope confs (plugins whose predicate fns the scan does not
model) fall back to the serial action wholesale.
"""

from __future__ import annotations

import numpy as np

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import PodGroupPhase
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session
from kube_batch_tpu import log


class XlaBackfillAction(Action):
    @property
    def name(self) -> str:
        return "xla_backfill"

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.backfill import BackfillAction
        from kube_batch_tpu.actions.envelope import scan_supported

        if not scan_supported(ssn):
            log.V(3).infof("conf outside scan envelope; running serial backfill")
            BackfillAction().execute(ssn)
            return

        from kube_batch_tpu.ops.encode import (
            _node_signature,
            _task_ports,
            _task_signature,
            build_static_compat,
            group_by_signature,
        )
        from kube_batch_tpu.plugins.predicates import (
            check_node_condition,
            check_pressure,
        )
        from kube_batch_tpu.utils import get_node_list

        # -- candidate tasks in the serial iteration order ----------------
        work: list = []  # TaskInfo, serial (job, task) order
        for job in ssn.jobs.values():
            if (
                job.pod_group is not None
                and job.pod_group.status.phase == PodGroupPhase.PENDING
            ):
                continue
            for task in list(
                job.task_status_index.get(TaskStatus.PENDING, {}).values()
            ):
                if task.init_resreq.is_empty():
                    work.append(task)
        if not work:
            return

        nodes = get_node_list(ssn.nodes)
        n = len(nodes)
        if n == 0:
            return

        # -- distinct host ports the candidates use (bitmask domain) ------
        all_ports = sorted({p for t in work for p in _task_ports(t)})
        if len(all_ports) > 63:
            # int64 bitmask exhausted — same convention as VectorScan:
            # correctness first, scan another day
            log.V(3).infof(">63 distinct host ports; running serial backfill")
            BackfillAction().execute(ssn)
            return
        port_bit = {p: np.int64(1) << i for i, p in enumerate(all_ports)}

        def ports_mask(task) -> np.int64:
            m = np.int64(0)
            for p in _task_ports(task):
                m |= port_bit[p]
            return m

        # -- static node facts + dynamic counters -------------------------
        label_keys: set[str] = set()
        for t in work:
            label_keys.update(t.pod.node_selector)
            aff = t.pod.affinity
            if aff is not None:
                for term in aff.node_affinity_required:
                    label_keys.add(term.key)
                for _, term in aff.node_affinity_preferred:
                    label_keys.add(term.key)
        frozen_keys = frozenset(label_keys)
        node_ok = np.zeros(n, bool)
        max_tasks = np.zeros(n, np.int64)
        ntasks = np.zeros(n, np.int64)
        node_ports = np.zeros(n, np.int64)
        for i, node in enumerate(nodes):
            node_ok[i] = (
                node.node is not None
                and check_node_condition(node.node)
                and check_pressure(node.node)
            )
            max_tasks[i] = node.allocatable.max_task_num
            ntasks[i] = len(node.tasks)
            if all_ports:
                for rt in node.tasks.values():
                    for p in _task_ports(rt):
                        bit = port_bit.get(p)
                        if bit is not None:
                            node_ports[i] |= bit

        # -- dedup groups + (group x node-group) verdicts (shared with
        #    the encoder: ops/encode.py group_by_signature/build_static_compat)
        node_gid, n_reps = group_by_signature(
            nodes, lambda nd: _node_signature(nd, frozen_keys)
        )
        task_gid, t_reps = group_by_signature(work, _task_signature)
        compat = build_static_compat(t_reps, n_reps)

        # -- the walk, serial order, live session mutations ---------------
        placed = 0
        for t, gid in zip(work, task_gid):
            aff = t.pod.affinity
            if aff is not None and (
                aff.pod_affinity_required or aff.pod_anti_affinity_required
            ):
                # pairwise-over-residents verdict: serial chain against the
                # live session (exactly backfill.go's inner loop)
                hit = self._serial_step(ssn, t, nodes)
            else:
                tp = ports_mask(t)
                mask = (
                    compat[gid, node_gid]
                    & node_ok
                    & (ntasks < max_tasks)
                    & ((tp & node_ports) == 0)
                )
                hit = None
                for i in np.nonzero(mask)[0].tolist():
                    try:
                        ssn.allocate(t, nodes[i].name)
                    except Exception:  # noqa: BLE001 -- serial `continue`
                        continue
                    hit = i
                    break
            if hit is not None:
                ntasks[hit] += 1
                node_ports[hit] |= ports_mask(t)
                placed += 1
        if placed:
            log.V(3).infof("backfilled %d BestEffort tasks", placed)

    @staticmethod
    def _serial_step(ssn: Session, task, nodes):
        """backfill.go:52-71 for one task: first predicate-passing node,
        allocate, break; returns the node row or None."""
        for i, node in enumerate(nodes):
            try:
                ssn.predicate_fn(task, node)
            except Exception:  # noqa: BLE001
                continue
            try:
                ssn.allocate(task, node.name)
            except Exception:  # noqa: BLE001
                continue
            return i
        return None


def new() -> Action:
    return XlaBackfillAction()
