"""xla_allocate action: the allocate loop as one device program.

Drop-in replacement for the serial allocate action (conf
``actions: "enqueue, xla_allocate, backfill"``): encodes the session
snapshot to SoA tensors (ops.encode), runs the gang-aware device solve —
the fused Pallas kernel (ops.pallas_solve) on TPU, the jitted XLA
`lax.while_loop` twin (ops.kernels.solve_allocate) elsewhere and as the
runtime fallback — which vectorizes the reference's per-task node scans
(scheduler_helper.go:34-109) over the whole node axis, then
**bulk-replays** the resulting assignments into the session — the same
state mutations `ssn.allocate`/`ssn.pipeline` would make (status index
moves, node accounting, drf/proportion event bookkeeping, the gang
dispatch barrier with cache binds), applied in kernel assignment order
but without 50k Python call frames of per-task session machinery.

Policy envelope: the kernel hardwires the reference's *default* conf
semantics (util.go:31-42) — priority/gang ordering + barrier, drf job
shares, proportion queue shares + overused gate, predicates masks,
nodeorder scores. Anything else (extra plugins, disabled enable flags,
a chain order the kernel's selection keys do not model) falls back to
the serial action for the cycle — correctness first.

Pod (anti-)affinity is pairwise-dynamic over resident pods
(predicates.go:187-199) and stays host-side, but no longer forces a
wholesale fallback: the kernel pauses when a flagged task reaches the
head of its job (ops/kernels.py `paused_at`), the action replays the
segment, serial-steps that one task against the live session (identical
to the serial inner loop, allocate.go:139-180), patches the solver state
and resumes — a snapshot with one affinity task costs one extra device
round-trip, not a serial cycle.

NodesFitDelta diagnostics (allocate.go:139-145,162-168) are reproduced
only on the host-stepped tasks — they are human-readable FitError text,
not policy.

Float dtype (round-2 advisor finding): float64 by default — bit-identical
to the serial float64 path. When x64 is unavailable (default TPU config)
the action runs float32 — exact for milli-CPU/MiB-granular quantities but
able to flip least-requested/balanced floor/tie boundaries on off-grid
values — and logs that it did so.
"""

from __future__ import annotations

import logging
import os

import jax  # noqa: F401  -- fail registration, not mid-cycle, when absent
import numpy as np

from kube_batch_tpu import faults, metrics, obs
from kube_batch_tpu import log as _glog
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session

from kube_batch_tpu.actions.envelope import kernel_supported as _kernel_supported
from kube_batch_tpu.native import lib as _native

log = logging.getLogger("kube_batch_tpu.actions.xla_allocate")


class _DeviceSolveError(RuntimeError):
    """Every device tier failed (or the XLA twin's breaker rejected the
    cycle mid-solve): the caller degrades to serial for this cycle."""


def _nonfinite_inputs(arrays: dict) -> list[str]:
    """Names of float solver inputs carrying NaN/Inf. One reduction per
    array (any non-finite value propagates through sum; a finite array
    overflowing the sum is an overflow worth flagging too) — cheap next
    to the solve, and the guard that turns a poisoned score tensor into
    a logged serial cycle instead of silently wrong placements."""
    bad = []
    for name, v in arrays.items():
        a = np.asarray(v)
        if a.dtype.kind == "f" and not np.isfinite(a.sum()):
            bad.append(name)
    return bad


def _nodeorder_weights(ssn: Session) -> tuple[float, float, float, float]:
    """(w_least, w_balanced, w_aff, w_podaff) from the tiers, matching the
    serial plugin's defaults (nodeorder.go:139-153)."""
    from kube_batch_tpu.framework.arguments import Arguments
    from kube_batch_tpu.plugins.nodeorder import (
        BALANCED_RESOURCE_WEIGHT,
        LEAST_REQUESTED_WEIGHT,
        NODE_AFFINITY_WEIGHT,
        POD_AFFINITY_WEIGHT,
    )

    for tier in ssn.tiers:
        for option in tier.plugins:
            if option.name in ("nodeorder", "tensorscore") and option.enabled_node_order:
                args = Arguments(option.arguments)
                return (
                    args.get_int(LEAST_REQUESTED_WEIGHT, 1),
                    args.get_int(BALANCED_RESOURCE_WEIGHT, 1),
                    args.get_int(NODE_AFFINITY_WEIGHT, 1),
                    args.get_int(POD_AFFINITY_WEIGHT, 1),
                )
    return 0.0, 0.0, 0.0, 0.0


class XlaAllocateAction(Action):
    """The TPU-native allocate. Falls back to serial when out of envelope."""

    def __init__(self, dtype=None) -> None:
        self._dtype = dtype
        self._warned_f32 = False
        # Device-resident tensor arena (ops/encode_cache.TensorArena):
        # persists across cycles on the registered action instance, so
        # warm cycles upload only changed rows of the node slabs / group
        # matrices instead of re-transferring the full tensor set.
        from kube_batch_tpu.ops.encode_cache import TensorArena

        self._arena = TensorArena()
        # Wall-clock split of the last execute() (bench.py reads this).
        self.last_timings: dict[str, float] = {}
        # Devices in the mesh the last execute() resolved (1 = single-chip);
        # the driver dryrun asserts on this to prove the sharded path ran.
        self.last_mesh_size = 1
        # Which rung actually solved the last execute() ("mesh_pallas",
        # "sharded_xla", "pallas", "xla", "serial"); bench rows assert on
        # this so a silent downgrade cannot masquerade as evidence.
        self.last_solver_tier = "none"
        # Gang iterations the last execute() committed from K-deep
        # batched mesh exchanges (KBT_EXCHANGE_BATCH; 0 off the batched
        # program). Bench rows read this as amortization evidence.
        self.last_batched_iters = 0
        # Stats dict from the last class-compressed solve (ops/class_solve,
        # KBT_CLASS_COMPRESS): class_count, compression_ratio, splits,
        # remerges, group_s/kernel_s solve-cost split. None when the
        # compression was off or degraded for the cycle; bench rows read
        # this as the compression-honesty evidence.
        self.last_class_stats = None
        # Whether the last FULL-cycle encode saw any pod-affinity terms
        # (pending or resident). Streaming micro-cycles pass this as the
        # resident_interpod hint so the encode skips the O(resident-pods)
        # sweep over every node's task map (see encode_session).
        self.last_interpod_active = False

    @property
    def name(self) -> str:
        return "xla_allocate"

    # -- main ----------------------------------------------------------------

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.ops.encode import encode_session
        from kube_batch_tpu.ops.kernels import result_of, solve_allocate_state

        self.last_timings = {}  # never report a previous cycle's path
        self.last_solver_tier = "none"
        self.last_batched_iters = 0
        self.last_class_stats = None
        if not _kernel_supported(ssn):
            log.info("conf outside kernel envelope; running serial allocate")
            self._fallback(ssn)
            return

        mesh = self._resolve_mesh(ssn)

        # Size floor: one device solve costs a fixed dispatch round trip
        # (~0.1 s over a remote chip) regardless of payload, while the
        # serial loop clears tiny snapshots in microseconds-per-pair —
        # route (tasks x nodes) below the floor to the serial action
        # (bit-exact float64, no device). A mesh *request* — even one
        # that failed to resolve — is a statement of device intent and
        # skips the floor (the multichip dryrun relies on this).
        if mesh is None and not self._mesh_requested(ssn):
            pend = sum(
                len(j.task_status_index.get(TaskStatus.PENDING, {}))
                for j in ssn.jobs.values()
            )
            if pend * max(len(ssn.nodes), 1) < self._min_device_pairs(ssn):
                log.debug(
                    "snapshot below the device size floor (%d pending x %d "
                    "nodes); running serial allocate",
                    pend,
                    len(ssn.nodes),
                )
                import time as _time

                t0 = _time.perf_counter()
                self._fallback(ssn)
                self.last_timings = {
                    "serial_routed_s": _time.perf_counter() - t0
                }
                return

        import jax.numpy as jnp

        dtype = self._dtype
        if dtype is None:
            if jnp.zeros(0).dtype == np.float64:
                dtype = np.float64
            else:
                dtype = np.float32
                if not self._warned_f32:
                    log.warning(
                        "jax x64 disabled: solving in float32 — exact on "
                        "milli-CPU/MiB-granular requests, but off-grid values "
                        "can flip score floor/tie boundaries vs the serial "
                        "float64 path (enable jax_enable_x64 for bit parity)"
                    )
                    self._warned_f32 = True

        import time as _time

        # Degradation ladder (kube_batch_tpu.faults): the XLA twin is the
        # device floor — every other device tier falls back onto it — so
        # with its breaker open the whole device path sits the cycle out
        # and serial (the bottom rung, the correctness oracle) runs. The
        # breaker recovers through half-open probes, unlike the previous
        # one-way exception fallback.
        ladder = faults.solver_ladder
        if not ladder.allow("xla"):
            log.warning(
                "device-solve breaker open; running serial allocate for this cycle"
            )
            metrics.register_degraded_cycle("serial", "breaker_open")
            t0 = _time.perf_counter()
            self._fallback(ssn)
            self.last_timings = {"serial_degraded_s": _time.perf_counter() - t0}
            return

        order = [o.name for t in ssn.tiers for o in t.plugins]
        enable_drf = "drf" in order
        enable_proportion = "proportion" in order

        micro = bool(getattr(ssn, "micro_cycle", False))
        t0 = _time.perf_counter()
        with obs.span("encode", micro=micro) as espan:
            enc = encode_session(
                ssn.jobs,
                ssn.nodes,
                ssn.queues,
                dtype=dtype,
                drf=ssn.plugins.get("drf") if enable_drf else None,
                proportion=ssn.plugins.get("proportion") if enable_proportion else None,
                session=ssn,
                resident_interpod=self.last_interpod_active if micro else None,
            )
            if not micro:
                self.last_interpod_active = bool(enc.interpod_active)
            espan.set_attr("tasks", len(enc.tasks))
            espan.set_attr("nodes", len(ssn.nodes))
            # cross-cycle encode-cache temperature of THIS encode
            espan.set_attr("warm_fraction", metrics.encode_warm_fraction.value())
        if not enc.tasks:
            return
        t_encode = _time.perf_counter() - t0

        w_least, w_balanced, w_aff, w_podaff = _nodeorder_weights(ssn)
        arrays = dict(enc.arrays)
        # host-only metadata: the replay's latency stamps read it from
        # enc.arrays — keep it out of the kernel input dict (it would
        # ride every solve's transfer and change the jit pytree)
        arrays.pop("task_created", None)
        arrays["w_least"] = dtype(w_least)
        arrays["w_balanced"] = dtype(w_balanced)
        arrays["w_aff"] = dtype(w_aff)
        arrays["w_podaff"] = dtype(w_podaff)

        # Fault point solve.nan: a poisoned score tensor, the failure the
        # finite guard below exists to catch.
        if faults.should_fire("solve.nan"):
            arrays["w_least"] = dtype(float("nan"))
        bad = _nonfinite_inputs(arrays)
        if bad:
            log.error(
                "non-finite solver inputs (%s); running serial allocate for "
                "this cycle", ", ".join(bad),
            )
            metrics.register_degraded_cycle("serial", "nonfinite")
            t0 = _time.perf_counter()
            self._fallback(ssn)
            self.last_timings = {"serial_degraded_s": _time.perf_counter() - t0}
            return

        replay = _Replayer(ssn, enc, arrays, enable_drf, enable_proportion)

        # Device-resident arena: the XLA rungs (single-chip twin and the
        # GSPMD sharded solver) take persistent device handles — warm
        # cycles upload only changed rows of the node slabs / group
        # matrices. The Pallas rungs pack host-side and keep numpy. Any
        # arena failure degrades to plain host arrays (jit's own
        # transfer), never the cycle.
        from kube_batch_tpu.ops import encode_cache as _encode_cache

        dev_arrays = None
        if _encode_cache.enabled():
            try:
                dev_arrays = self._arena.device_view(arrays, mesh=mesh)
            except Exception:  # noqa: BLE001 -- residency is an optimization
                log.exception("tensor arena upload failed; solving from host arrays")
                self._arena.clear()
                dev_arrays = None

        # Cycle deadline budget (recovery/budget.py), threaded from
        # run_once via the session: the solver entry receives the
        # remaining budget and every pre-dispatch boundary checks it.
        budget = getattr(ssn, "cycle_budget", None)
        solve_fn = self._make_solver(
            arrays, enable_drf, enable_proportion, dtype, mesh, budget=budget,
            dev_arrays=dev_arrays,
        )

        t0 = _time.perf_counter()
        sspan = obs.span("solve", mesh=self.last_mesh_size)
        compile0 = 0
        if sspan is not obs.NOOP_SPAN:
            from kube_batch_tpu.analysis.trace.sentinel import compile_count

            compile0 = compile_count()
        try:
            with sspan, obs.annotate("kbt.solve"):
                state = solve_fn(None)
                while int(state.paused_at) >= 0:
                    if budget is not None:
                        budget.check("between solve segments")
                    # Segmented hybrid: sync the session up to the pause point,
                    # serial-step the host-only task, resume the kernel.
                    sspan.event("host_step", step=int(state.step))
                    s = jax.tree_util.tree_map(np.array, state)  # writable host copy
                    replay.apply_upto(s.assign_pos, s.assigned_node, s.assigned_kind, int(s.step))
                    s = self._host_step(ssn, enc, arrays, replay, s)
                    if enc.interpod_active:
                        # the host-stepped pod carries pod-affinity terms; once
                        # resident it shifts every group's InterPodAffinity score
                        from kube_batch_tpu.ops.encode import compute_pod_sc

                        arrays["pod_sc"] = compute_pod_sc(
                            enc.task_reps,
                            ssn.nodes,
                            enc.node_names,
                            np.asarray(arrays["pod_sc"]).shape[1],
                            dtype,
                        )
                        if dev_arrays is not None:
                            # mirror the refresh into the device view the
                            # XLA rungs solve from
                            dev_arrays["pod_sc"] = self._arena.upload(
                                "pod_sc", arrays["pod_sc"], mesh=mesh
                            )
                    state = solve_fn(s)

                result = result_of(state)
                # Device fencepost (device-phase telemetry): block until
                # the solver's outputs have materialized ON DEVICE before
                # the host transfers below — solve_device_s is then a
                # device-event-measured phase boundary, not a wall-clock
                # figure with transfer time folded in.
                jax.block_until_ready(result.assign_pos)
                t_solve_device = _time.perf_counter() - t0
                # all three result vectors come off-device here: the transfer is
                # part of the solve's device round-trip, not of the replay
                assign_pos = np.asarray(result.assign_pos)
                assigned_node = np.asarray(result.assigned_node)
                assigned_kind = np.asarray(result.assigned_kind)
                sspan.set_attr("tier", self.last_solver_tier)
                if sspan is not obs.NOOP_SPAN:
                    from kube_batch_tpu.analysis.trace.sentinel import compile_count

                    compiled = compile_count() - compile0
                    if compiled:
                        # a warm cycle that compiles is THE regression the
                        # CompileSentinel exists for — make it visible on
                        # the trace, not just in the budget assert
                        sspan.event("compile", count=compiled)
        except _DeviceSolveError as e:
            # Bottom of the ladder: serial finishes the cycle. Any
            # already-replayed host-step segments stand — serial allocate
            # simply continues over the remaining pending tasks, the same
            # session semantics as a mixed actions string.
            log.error("device solve failed (%s); degrading to serial allocate", e)
            metrics.register_degraded_cycle("serial", "solve_failed")
            t0 = _time.perf_counter()
            self._fallback(ssn)
            self.last_timings = {"serial_degraded_s": _time.perf_counter() - t0}
            return
        t_solve = _time.perf_counter() - t0

        # Pipelined cycles (kube_batch_tpu.pipeline, KBT_PIPELINE): the
        # post-solve phase — statement replay, forensics, dispatch — is
        # pure host/cache work that needs nothing further from the
        # device, so it can ride the cache's kb-write pool while the
        # next cycle encodes and solves. The dispatch fence keeps the
        # ordering the synchronous path gets for free (dispatch N <
        # snapshot N+1), close_session joins before the commit
        # write-back, and micro-cycles never defer (their outcome
        # accounting reads the session synchronously).
        from kube_batch_tpu import pipeline as _pipeline

        defer = _pipeline.enabled() and not micro
        if defer and budget is not None:
            # The last pre-dispatch gate must stay on the scheduling
            # thread so a deadline abort (and the cycle.overrun drill's
            # inject=True) still unwinds through run_once's discard path
            # with zero cache mutation.
            budget.check("dispatch barrier", inject=True)

        timings: dict[str, float] = {
            "encode_s": t_encode,
            "solve_s": t_solve,
            "solve_device_s": t_solve_device,
        }
        self.last_timings = timings

        def _post_solve(parent=None) -> float:
            t0 = _time.perf_counter()
            t_explain = 0.0
            with obs.span(
                "gang.assign", parent=parent, assigned=int(result.n_assigned)
            ):
                replay.apply_upto(assign_pos, assigned_node, assigned_kind, int(result.n_assigned))
                if not defer and budget is not None:
                    # The last pre-dispatch gate: past this point binds reach
                    # the cache and the cycle can no longer abort cleanly. The
                    # cycle.overrun drill injects here (inject=True) — maximal
                    # discardable work, zero cache mutation.
                    budget.check("dispatch barrier", inject=True)
                # Post-solve forensics (obs/explain): batched plane/score
                # reductions against the FINAL solver state, published before
                # replay.finish so the journal intents it writes can attach
                # per-gang reason payloads — and after the budget gate, so an
                # aborted cycle leaves no half-cycle records behind.
                from kube_batch_tpu.obs import explain as _explain

                if _explain.enabled():
                    te = _time.perf_counter()
                    with obs.span("explain", micro=micro) as xsp:
                        recs = _explain.explain_post_solve(ssn, enc, arrays, state, result)
                        _explain.publish(ssn, recs)
                        for k, v in _explain.summary(recs).items():
                            xsp.set_attr(k, v)
                    t_explain = _time.perf_counter() - te
                replay.finish(np.asarray(result.ready_cnt))
            dur = _time.perf_counter() - t0
            timings["replay_s"] = dur - t_explain
            if t_explain:
                timings["explain_s"] = t_explain
            return dur

        if defer:
            ctx = obs.current()  # pool threads don't inherit the contextvar

            def _deferred() -> None:
                # stamp the dispatch window for the measured overlap
                # fraction: [d0, d1] intersected with the consumer's
                # join window is the serialized share
                d0 = _time.perf_counter()
                _post_solve(parent=ctx)
                _pipeline.fence.record_dispatch_window(d0, _time.perf_counter())

            fut = _pipeline.submit(ssn.cache, _deferred)
            ssn.deferred_dispatch = fut
            _pipeline.fence.arm(fut)
        else:
            _post_solve()

    def _mesh_requested(self, ssn: Session) -> bool:
        """True when the conf/env names a mesh at all — resolution may
        still fail (bad backend, one device), but the operator asked for
        the device path, so the size floor must not reroute to serial."""
        spec = ssn.action_arguments.get(self.name, {}).get(
            "mesh", os.environ.get("KBT_MESH", "")
        )
        return (spec or "").strip().lower() not in ("", "off", "none", "0", "1")

    def _min_device_pairs(self, ssn: Session) -> int:
        """(pending tasks x nodes) below which the serial action is the
        faster allocator. Default 32768: at ~6 us/pair the serial loop
        finishes in ~0.2 s, the break-even with the device round trip.
        Conf `actionArguments: {xla_allocate: {min_device_pairs: N}}`
        or env KBT_MIN_DEVICE_PAIRS overrides; 0 forces the device path
        (how the parity suites pin the kernel under test)."""
        spec = ssn.action_arguments.get(self.name, {}).get(
            "min_device_pairs", os.environ.get("KBT_MIN_DEVICE_PAIRS", "")
        )
        try:
            return int(spec)
        except (TypeError, ValueError):
            if str(spec).strip():
                log.warning(
                    "min_device_pairs=%r is not an integer; using default", spec
                )
            return 32768

    def _resolve_mesh(self, ssn: Session):
        """Conf-selected device mesh for the solve, or None (single-chip).

        `actionArguments: {xla_allocate: {mesh: ...}}` (env KBT_MESH as
        the conf-less override): ``off``/``0``/``1`` -> single chip;
        ``auto`` -> every visible device; an integer -> that many; an
        explicit ``backend:count`` (e.g. ``cpu:8``) pins the JAX backend
        — how the driver/tests exercise the multi-chip path on a virtual
        CPU mesh when the ambient default backend is a single TPU. The
        mesh size is clamped to the largest power of two available so it
        always divides the encoder's power-of-two node buckets. The
        resolved size lands in `self.last_mesh_size` so callers can
        verify the sharded path actually engaged."""
        self.last_mesh_size = 1
        spec = ssn.action_arguments.get(self.name, {}).get(
            "mesh", os.environ.get("KBT_MESH", "")
        )
        spec = (spec or "").strip().lower()
        if spec in ("", "off", "none", "0", "1"):
            return None
        import jax as _jax

        backend = None
        if ":" in spec:
            backend, spec = spec.split(":", 1)
        try:
            devices = _jax.devices(backend)
        except RuntimeError:
            log.warning(
                "mesh backend %r unavailable; running single-chip", backend
            )
            return None
        if spec == "auto":
            want = len(devices)
        else:
            try:
                want = int(spec)
            except ValueError:
                # A bad conf value must not kill the scheduling loop
                # (scheduler.py's rule for parse errors applies to
                # values too) — degrade to single-chip and say so.
                log.warning(
                    "unrecognized mesh spec %r; running single-chip", spec
                )
                return None
        if want < 1:
            log.warning("mesh=%s is not a device count; running single-chip", spec)
            return None
        n = min(want, len(devices))
        n = 1 << (n.bit_length() - 1)  # largest pow2 <= n
        # The encoder buckets the node axis to multiples of 128, which
        # every pow2 mesh up to 128 divides; a larger mesh would break
        # the GSPMD divisibility invariant.
        if n > 128:
            log.warning(
                "mesh clamped from %d to 128 devices (node-bucket divisibility)", n
            )
            n = 128
        if n <= 1:
            if spec != "auto" and want > 1:
                log.warning(
                    "mesh=%s requested but only %d device(s) visible; "
                    "running single-chip",
                    spec,
                    len(devices),
                )
            return None
        if n != want and spec != "auto":
            log.warning("mesh=%s clamped to %d devices (pow2, available)", spec, n)
        from kube_batch_tpu.parallel import make_mesh

        self.last_mesh_size = n
        return make_mesh(n, devices=devices[:n])

    def _make_solver(
        self,
        arrays,
        enable_drf: bool,
        enable_proportion: bool,
        dtype,
        mesh=None,
        budget=None,
        dev_arrays=None,
    ):
        """Pick the device solve: with a conf-selected multi-chip mesh,
        the GSPMD node-axis-sharded XLA kernel (parallel.ShardedSolver);
        single-chip, the fused Pallas kernel on TPU-class backends
        (float32, in-envelope snapshots), else the XLA `lax.while_loop`
        kernel. `KBT_PALLAS=0` forces the XLA kernel; `KBT_PALLAS=interpret`
        runs the Pallas kernel in interpreter mode (CPU parity tests).
        Live InterPodAffinity scores no longer force the XLA kernel: the
        Pallas solver re-folds its affinity static whenever the action
        refreshes arrays["pod_sc"] between pause/resume segments
        (pallas_solve.fold_affinity_scores).

        Tier health flows through the faults.solver_ladder breakers: a
        pallas failure (init or solve) both falls back within the cycle
        AND records against the pallas breaker, so a persistently broken
        tier sits out its backoff instead of being retried blindly every
        cycle (and, unlike the old `solver = None`, is probed again once
        the backoff elapses). An XLA-twin failure raises
        _DeviceSolveError so execute() degrades the cycle to serial."""
        from kube_batch_tpu.ops.kernels import solve_allocate_state

        ladder = faults.solver_ladder
        # The single-chip XLA twin solves from the arena's device
        # handles when available; with a mesh the arena view is sharded
        # for the GSPMD rung, so the single-chip fallback keeps host
        # arrays (resharding a committed mesh array into a single-chip
        # program is a cross-device copy jit would have to insert).
        xla_arrays = dev_arrays if (dev_arrays is not None and mesh is None) else arrays

        def _wrap(fn):
            """Node-class compressed layer (ops/class_solve,
            KBT_CLASS_COMPRESS): runs feasibility+score+argmax at class
            granularity over whichever rung was picked, expanding back
            to node-space SolveState at every segment boundary. Inside
            the budget gate — a compressed segment is still a solver
            entry — and any class-table failure degrades to ``fn``
            within the call, so the rung ladder below is unchanged."""
            from kube_batch_tpu.ops import class_solve

            if not class_solve.enabled():
                return fn
            return class_solve.wrap_solver(
                self, fn, arrays, enable_drf, enable_proportion, dtype,
                mesh=mesh,
            )

        def _with_budget(fn):
            """Solver-entry budget gate: a device solve is the cycle's
            dominant cost, so a hard budget already gone must abort
            BEFORE another segment dispatches — outside the tier
            try/except blocks, so the abort cannot be mistaken for a
            tier failure and feed a breaker."""
            if budget is None:
                return fn

            def checked(st):
                budget.check("solver entry")
                return fn(st)

            return checked

        def _xla_solve(st):
            # The device floor. Failures (organic or the solve.xla fault
            # point) feed the xla breaker and surface as _DeviceSolveError
            # — execute() runs serial for the cycle; the breaker's
            # half-open probe re-tries the device path later.
            try:
                if faults.should_fire("solve.xla"):
                    raise faults.FaultInjected("solve.xla")
                out = solve_allocate_state(
                    xla_arrays, st, enable_drf=enable_drf,
                    enable_proportion=enable_proportion,
                )
            except Exception as e:
                log.exception("XLA solve failed")
                ladder.record_failure("xla")
                raise _DeviceSolveError(str(e)) from e
            ladder.record_success("xla")
            self.last_solver_tier = "xla"
            return out

        if mesh is not None:
            from kube_batch_tpu.ops import pallas_solve
            from kube_batch_tpu.parallel import ShardedSolver

            xla_sharded = None
            try:
                # arena handles (sharded placement) when available —
                # the solver's in_shardings match, so warm cycles skip
                # the full host->mesh scatter
                xla_sharded = ShardedSolver(
                    dev_arrays if dev_arrays is not None else arrays,
                    mesh, enable_drf=enable_drf,
                    enable_proportion=enable_proportion,
                )
            except Exception:
                log.exception(
                    "sharded solver init failed; using single-chip path"
                )

            def solve_sharded(st):
                # The mesh's XLA rung. First solve still traces/compiles
                # lazily; fall back to the single-chip XLA kernel on
                # failure rather than losing the cycle.
                nonlocal xla_sharded
                if xla_sharded is not None:
                    try:
                        out = xla_sharded.solve(st)
                        self.last_solver_tier = "sharded_xla"
                        return out
                    except Exception:
                        log.exception(
                            "sharded solve failed; falling back to "
                            "single-chip XLA kernel"
                        )
                        xla_sharded = None
                return _xla_solve(st)

            # Top rung of the mesh path: the blocked sharded-Pallas
            # solver (parallel.sharded_pallas) — the fused block kernel
            # per shard, one argmax exchange per gang iteration. The
            # VMEM gate is PER SHARD (pallas_solve.mesh_supported): a
            # snapshot that overflows one chip's vmem_budget() stays on
            # the Pallas rung when its node block divided over the mesh
            # fits, instead of falling to the ~9x-slower XLA twin.
            # KBT_MESH_PALLAS=0/off disables the rung; mosaic/interpret/
            # jnp pin the block backend (default auto: mosaic on TPU
            # meshes, the jnp twin elsewhere).
            mesh_pallas = None
            mmode = (
                os.environ.get("KBT_MESH_PALLAS", "auto").strip().lower()
                or "auto"
            )
            if (
                mmode not in ("0", "off")
                and dtype == np.float32
                and ladder.allow("mesh_pallas")
                and pallas_solve.mesh_supported(arrays, mesh.devices.size)
            ):
                from kube_batch_tpu.parallel.sharded_pallas import (
                    ShardedPallasSolver,
                )

                try:
                    mesh_pallas = ShardedPallasSolver(
                        arrays, mesh, enable_drf=enable_drf,
                        enable_proportion=enable_proportion,
                        block_impl=mmode,
                    )
                    log.info(
                        "solving with blocked sharded-Pallas kernel "
                        "(%s block) over a %d-device mesh",
                        mesh_pallas.block_impl, mesh.devices.size,
                    )
                except Exception:
                    log.exception(
                        "sharded-Pallas solver init failed; using the "
                        "mesh XLA rung"
                    )
                    ladder.record_failure("mesh_pallas")

            if mesh_pallas is not None:
                mp = mesh_pallas

                def solve_mesh_pallas(st):
                    # Tracing/compile is lazy here too; a failed solve
                    # feeds the mesh_pallas breaker and degrades to the
                    # mesh XLA rung within the cycle.
                    nonlocal mp
                    if mp is not None:
                        try:
                            if faults.should_fire("solve.mesh_pallas"):
                                raise faults.FaultInjected("solve.mesh_pallas")
                            before = mp.batched_iters
                            out = mp.solve(st)
                            gained = mp.batched_iters - before
                            if gained:
                                self.last_batched_iters += gained
                                metrics.register_exchange_batched_iters(
                                    gained
                                )
                            ladder.record_success("mesh_pallas")
                            self.last_solver_tier = "mesh_pallas"
                            return out
                        except Exception:
                            log.exception(
                                "sharded-Pallas solve failed; falling "
                                "back to the mesh XLA rung"
                            )
                            ladder.record_failure("mesh_pallas")
                            mp = None
                    return solve_sharded(st)

                return _with_budget(_wrap(solve_mesh_pallas))
            if xla_sharded is not None:
                log.info(
                    "solving with node-axis-sharded XLA kernel over a "
                    "%d-device mesh", mesh.devices.size,
                )
                return _with_budget(_wrap(solve_sharded))

        mode = os.environ.get("KBT_PALLAS", "1")
        solver = None
        if mode != "0" and dtype == np.float32 and ladder.allow("pallas"):
            import jax as _jax

            from kube_batch_tpu.ops import pallas_solve

            interpret = mode == "interpret"
            on_tpu = _jax.default_backend() == "tpu"  # Mosaic kernels are TPU-only
            if (on_tpu or interpret) and pallas_solve.supported(arrays):
                try:
                    solver = pallas_solve.PallasSolver(
                        arrays, enable_drf, enable_proportion, interpret=interpret
                    )
                    log.debug("solving with fused pallas kernel")
                except Exception:
                    log.exception("pallas solver init failed; using XLA kernel")
                    ladder.record_failure("pallas")
                    solver = None

        def solve_fn(st):
            # Tracing/Mosaic lowering is lazy — the first solve call can
            # still fail, so the fallback has to live here, not only at
            # solver construction. Both solvers speak SolveState, so the
            # XLA kernel resumes exactly from wherever pallas left off.
            nonlocal solver
            if solver is not None:
                try:
                    if faults.should_fire("solve.pallas"):
                        raise faults.FaultInjected("solve.pallas")
                    out = solver.solve(st)
                    ladder.record_success("pallas")
                    self.last_solver_tier = "pallas"
                    return out
                except Exception:
                    log.exception("pallas solve failed; falling back to XLA kernel")
                    ladder.record_failure("pallas")
                    solver = None
            return _xla_solve(st)

        return _with_budget(_wrap(solve_fn))

    # -- host-side serial step for one pod-affinity task ---------------------

    def _host_step(self, ssn: Session, enc, arrays, replay: "_Replayer", s):
        """Exactly the serial inner-loop body (allocate.py:90-119 /
        reference allocate.go:139-185) for the paused task, then patch the
        solver state: pointer, node vectors, job lifecycle."""
        from kube_batch_tpu.ops.kernels import KIND_ALLOCATED, KIND_PIPELINED
        from kube_batch_tpu.plugins.predicates import PredicateError
        from kube_batch_tpu.utils import (
            get_node_list,
            predicate_nodes,
            prioritize_nodes,
            select_best_node,
        )

        row = int(s.paused_at)
        task = enc.tasks[row]
        job = ssn.jobs[task.job]
        jrow = int(s.cur)
        all_nodes = get_node_list(ssn.nodes)

        def predicate_fn(t, node):
            if not t.init_resreq.less_equal(node.idle) and not t.init_resreq.less_equal(
                node.releasing
            ):
                raise PredicateError(
                    f"task <{t.namespace}/{t.name}> ResourceFit failed "
                    f"on node <{node.name}>"
                )
            ssn.predicate_fn(t, node)

        if job.nodes_fit_delta:
            job.nodes_fit_delta = {}

        s.ptr[jrow] += 1
        candidates = predicate_nodes(task, all_nodes, predicate_fn)
        if not candidates:
            # serial `break`: the job leaves the heap unassigned.
            log.debug("host step: no candidates for %s; abandoning job", task.uid)
            s.job_active[jrow] = False
            return s._replace(cur=np.int32(-1), it=s.it + 1)

        node_scores = prioritize_nodes(
            task, candidates, ssn.node_order_map_fn, ssn.node_order_reduce_fn
        )
        node = select_best_node(node_scores)
        nrow = replay.node_idx[node.name]

        if task.init_resreq.less_equal(node.idle):
            kind = KIND_ALLOCATED
        else:
            delta = node.idle.clone()
            delta.fit_delta(task.init_resreq)
            job.nodes_fit_delta[node.name] = delta
            kind = KIND_PIPELINED if task.init_resreq.less_equal(node.releasing) else 0

        cur = jrow
        if kind:
            try:
                replay.apply_immediate(row, nrow, kind, int(s.step))
            except Exception as e:  # noqa: BLE001
                # Volume assume failed (the first mutation apply_one makes,
                # so session state is untouched): serial semantics — the
                # task is consumed unassigned and the loop moves on
                # (allocate.go:158-161 logs and continues).
                log.error(
                    "host step: failed to allocate task %s on %s: %s",
                    task.uid, node.name, e,
                )
                return s._replace(cur=np.int32(cur), it=s.it + np.int32(1))
            res = np.asarray(arrays["task_res"][row], s.idle.dtype)
            s.used[nrow] += res
            if kind == KIND_ALLOCATED:
                s.idle[nrow] -= res
                s.ready_cnt[jrow] += 1
            else:
                s.rel[nrow] -= res
            s.ntasks[nrow] += 1
            s.nports[nrow] |= arrays["task_ports"][row]
            s.assigned_node[row] = nrow
            s.assigned_kind[row] = kind
            s.assign_pos[row] = int(s.step)
            if replay.drf is not None:
                s.job_alloc[jrow] += res
            qrow = int(arrays["job_queue"][jrow])
            if replay.prop is not None:
                s.q_alloc[qrow] += res
                s.q_alloc_has_sc[qrow] |= bool(arrays["task_res_has_sc"][row])
            s = s._replace(step=s.step + np.int32(1))
            if int(s.ready_cnt[jrow]) >= int(arrays["job_min"][jrow]):
                cur = -1
        return s._replace(cur=np.int32(cur), it=s.it + np.int32(1))

    def _fallback(self, ssn: Session) -> None:
        from kube_batch_tpu.actions.allocate import AllocateAction

        self.last_solver_tier = "serial"
        AllocateAction().execute(ssn)


class _Replayer:
    """Applies kernel assignments to the session in bulk — the exact net
    state mutations of `ssn.allocate`/`ssn.pipeline` (session.go:198-296)
    without per-task Python session machinery:

    - task status index surgery + `job.allocated` growth (job_info.go:233-259);
    - node task map + idle/releasing/used accounting aggregated per node
      (node_info.go:108-136) — exact because milli-CPU/byte quantities are
      integers, so float addition order cannot change the sums; scalar-map
      key presence follows the same add/sub rules as the sequential path;
    - drf/proportion allocated vectors advanced per event in kernel order
      with one final share recompute (the intermediate shares the serial
      event handlers maintain are never read between events);
    - the gang dispatch barrier at `finish`: jobs whose final ready count
      clears min_available get every Allocated task dispatched —
      BindVolumes + cache.Bind + Binding status, exactly the set the
      serial flip-time dispatches produce (session.go:285-322).
    """

    def __init__(self, ssn: Session, enc, arrays, enable_drf: bool, enable_prop: bool) -> None:
        self.ssn = ssn
        self.enc = enc
        self.arrays = arrays
        # Native extension boundary: the 'native.load' fault point
        # simulates the extension failing to load for this cycle — every
        # native fast path below degrades to its Python twin at once.
        self._native = None if faults.should_fire("native.load") else _native
        self.task_res64 = np.asarray(arrays["task_res"], np.float64)
        self.task_job = np.asarray(arrays["task_job"])
        self.task_res_has_sc = np.asarray(arrays["task_res_has_sc"])
        self.job_queue = np.asarray(arrays["job_queue"])
        self.drf = ssn.plugins.get("drf") if enable_drf else None
        self.prop = ssn.plugins.get("proportion") if enable_prop else None
        self.node_idx = {name: i for i, name in enumerate(enc.node_names)}
        # Row-indexed hot lookups for the bulk loop. row_of is lazy: the
        # numeric dispatch-column path never needs it, so the 200k-entry
        # dict build is paid only on the fallback paths.
        self.task_keys = [f"{t.namespace}/{t.name}" for t in enc.tasks]
        self._row_of: "Optional[dict]" = None
        self.node_by_row = [ssn.nodes[name] for name in enc.node_names]
        self.node_tasks_by_row = [n.tasks for n in self.node_by_row]
        self.replayed = 0  # assignment events already applied
        self.alloc_jobs: set[str] = set()  # jobs with >=1 Allocated event
        # vectorized twin of alloc_jobs (job-row indexed) + the bulk
        # replay's per-segment Allocated event log — what the dispatch
        # barrier's numpy mask and numeric bind columns are built from
        self._alloc_flags = np.zeros(len(enc.jobs), bool)
        self._bulk_alloc_log: list[tuple] = []  # (rows, nrows, jrows) per segment
        # jobs that took a host-stepped (apply_immediate) event: their
        # allocated tasks may carry volume claims / binder-managed
        # volume_ready, so finish() keeps the per-task checks for them
        self.stepped_jobs: set[str] = set()
        # per-node aggregation buffers (flushed once per segment)
        self._node_buf: dict[int, _NodeDelta] = {}
        self._touched_drf: set[str] = set()
        self._touched_prop: set[str] = set()
        # wall time each task's assignment came OFF the device (its solve
        # segment's completion) — the honest per-task schedule timestamp
        # for the bulk path (reference metrics.go:66-72 stamps at
        # dispatch; one batch timestamp would smear the whole action's
        # replay time into every task's latency)
        self.decided_at = np.zeros(len(enc.tasks))

    @property
    def row_of(self) -> dict:
        if self._row_of is None:
            self._row_of = {t.uid: r for r, t in enumerate(self.enc.tasks)}
        return self._row_of

    # -- one event -----------------------------------------------------------

    def apply_one(self, row: int, nrow: int, kind: int) -> None:
        from kube_batch_tpu.ops.kernels import KIND_ALLOCATED

        ssn = self.ssn
        task = self.enc.tasks[row]
        job = ssn.jobs[task.job]
        hostname = self.enc.node_names[nrow]
        status = TaskStatus.ALLOCATED if kind == KIND_ALLOCATED else TaskStatus.PIPELINED

        if kind == KIND_ALLOCATED:
            ssn.cache.allocate_volumes(task, hostname)
            self.alloc_jobs.add(job.uid)
            self._alloc_flags[self.task_job[row]] = True
        self.stepped_jobs.add(job.uid)

        # status index surgery == update_task_status's net effect
        pend = job.task_status_index.get(TaskStatus.PENDING)
        if pend is not None:
            pend.pop(task.uid, None)
            if not pend:
                del job.task_status_index[TaskStatus.PENDING]
        task.status = status
        task.node_name = hostname
        job.task_status_index.setdefault(status, {})[task.uid] = task
        if kind == KIND_ALLOCATED:
            job.allocated.add(task.resreq)

        # node: task map entry (a clone, node_info.go:117) + deferred sums
        node = ssn.nodes[hostname]
        node.tasks[self.task_keys[row]] = task.clone_for_residency()
        buf = self._node_buf.get(nrow)
        if buf is None:
            buf = self._node_buf[nrow] = _NodeDelta()
        res64 = self.task_res64[row]
        if kind == KIND_ALLOCATED:
            buf.alloc += res64
        else:
            buf.pipe += res64
        if task.resreq.scalars:
            buf.scalar_keys.update(task.resreq.scalars)

        # drf / proportion event handlers (drf.go:135-154, proportion.go:202-223)
        if self.drf is not None:
            self.drf.job_attrs[job.uid].allocated.add(task.resreq)
            self._touched_drf.add(job.uid)
        if self.prop is not None:
            self.prop.queue_attrs[job.queue].allocated.add(task.resreq)
            self._touched_prop.add(job.queue)

    # -- a segment -----------------------------------------------------------

    def apply_immediate(self, row: int, nrow: int, kind: int, pos: int) -> None:
        """One host-stepped event, applied and flushed right away (the next
        host step's predicates need the node state current)."""
        self.apply_one(row, nrow, kind)
        import time as _time

        self.decided_at[row] = _time.time()
        self.replayed = pos + 1
        self._flush_nodes()
        # Invalidate state_seq-keyed score memos (nodeorder/tensorscore):
        # the replay mutates node accounting without going through
        # ssn.allocate/pipeline, which are what normally bump the seq.
        self.ssn.bump_state()

    def apply_upto(self, assign_pos, assigned_node, assigned_kind, step: int) -> None:
        """Apply all events with replayed <= pos < step — the same net
        state mutations as per-event `apply_one`, but with every
        order-independent aggregate (node idle/releasing/used, job
        allocated, drf/proportion vectors) computed as a vectorized
        segment sum. Exact: all quantities are integer-grid float64, so
        addition order cannot change the sums, and scalar-map key
        creation follows the same per-event add/sub rules via the
        tracked key sets."""
        from kube_batch_tpu.ops.kernels import KIND_ALLOCATED

        if step <= self.replayed:
            return
        sel = (assign_pos >= self.replayed) & (assign_pos < step)
        rows = np.nonzero(sel)[0]
        self.replayed = step
        if rows.size == 0:
            return
        import time as _time

        self.decided_at[rows] = _time.time()  # this segment's solve completion
        # Same memo invalidation as apply_immediate: bulk replay mutates
        # node.used/tasks behind the session's back.
        self.ssn.bump_state()
        rows = rows[np.argsort(assign_pos[rows], kind="stable")]
        nrows = assigned_node[rows]
        kinds = assigned_kind[rows]
        alloc = kinds == KIND_ALLOCATED
        res = self.task_res64[rows]
        tjob = self.task_job[rows]
        scalar_names = self.enc.scalar_names
        R = res.shape[1]
        empty: frozenset = frozenset()

        # -- scalar-key bookkeeping (only rows whose resreq has scalars) --
        nkeys_alloc: dict[int, set] = {}
        nkeys_pipe: dict[int, set] = {}
        jkeys_alloc: dict[int, set] = {}
        jkeys_all: dict[int, set] = {}
        qkeys: dict[int, set] = {}
        for i in np.nonzero(self.task_res_has_sc[rows])[0].tolist():
            keys = self.enc.tasks[int(rows[i])].resreq.scalars.keys()
            n_i, j_i = int(nrows[i]), int(tjob[i])
            (nkeys_alloc if alloc[i] else nkeys_pipe).setdefault(n_i, set()).update(keys)
            if alloc[i]:
                jkeys_alloc.setdefault(j_i, set()).update(keys)
            jkeys_all.setdefault(j_i, set()).update(keys)
            qkeys.setdefault(int(self.job_queue[j_i]), set()).update(keys)

        # -- node accounting (node_info.go:108-136 net effect) ------------
        touched_n = np.unique(nrows)
        compn = np.searchsorted(touched_n, nrows)
        n_alloc_vec = _segment_sum(compn[alloc], res[alloc], touched_n.size, R)
        n_pipe_vec = _segment_sum(compn[~alloc], res[~alloc], touched_n.size, R)
        # The dense cpu/mem columns update natively in one pass per pool
        # (identical f64 adds, just without 60k interpreter round trips);
        # scalar dimensions keep the Go nil-map semantics on the Python
        # side and only run for the (rare) pools whose key sets are
        # non-empty.
        axpy_native = (
            getattr(self._native, "bulk_res_axpy", None) if self._native else None
        )

        def axpy(objs, mat, sign) -> None:
            # Per-POOL fallback: the native prepass guarantees failures
            # are pre-mutation, so a variant Resource pool degrades to
            # the Python loop without double-applying sibling pools.
            if axpy_native is not None:
                try:
                    axpy_native(objs, mat, sign)
                    return
                except (TypeError, AttributeError):
                    pass
            for k, res in enumerate(objs):
                res.milli_cpu += sign * float(mat[k, 0])
                res.memory += sign * float(mat[k, 1])

        touched_n_l = touched_n.tolist()
        nodes_t = [self.node_by_row[nrow] for nrow in touched_n_l]
        axpy([n.idle for n in nodes_t], n_alloc_vec, -1)
        axpy([n.releasing for n in nodes_t], n_pipe_vec, -1)
        axpy([n.used for n in nodes_t], n_alloc_vec + n_pipe_vec, 1)
        for nrow in set(nkeys_alloc) | set(nkeys_pipe):
            k = int(np.searchsorted(touched_n, nrow))
            node = self.node_by_row[nrow]
            ka = nkeys_alloc.get(nrow, empty)
            kp = nkeys_pipe.get(nrow, empty)
            _res_scalars(node.idle, n_alloc_vec[k], scalar_names, ka, -1, nil_map=True)
            _res_scalars(node.releasing, n_pipe_vec[k], scalar_names, kp, -1, nil_map=True)
            _res_scalars(
                node.used, n_alloc_vec[k] + n_pipe_vec[k], scalar_names, ka | kp, 1
            )

        # -- job.allocated + drf/proportion event bookkeeping -------------
        touched_j = np.unique(tjob)
        compj = np.searchsorted(touched_j, tjob)
        j_tot = _segment_sum(compj, res, touched_j.size, R)
        j_alloc = _segment_sum(compj[alloc], res[alloc], touched_j.size, R)
        wa = np.unique(tjob[alloc])
        self._alloc_flags[wa] = True
        drf = self.drf
        touched_j_l = touched_j.tolist()
        jobs_t = [self.enc.jobs[jrow] for jrow in touched_j_l]
        wa_pos = np.searchsorted(touched_j, wa)
        jobs_wa = [jobs_t[p] for p in wa_pos.tolist()]
        axpy([j.allocated for j in jobs_wa], j_alloc[wa_pos], 1)
        self.alloc_jobs.update(j.uid for j in jobs_wa)
        if drf is not None:
            axpy([drf.job_attrs[j.uid].allocated for j in jobs_t], j_tot, 1)
            self._touched_drf.update(j.uid for j in jobs_t)
        for jrow in jkeys_alloc:
            k = int(np.searchsorted(touched_j, jrow))
            _res_scalars(
                jobs_t[k].allocated, j_alloc[k], scalar_names,
                jkeys_alloc[jrow], 1,
            )
        if drf is not None:
            for jrow in jkeys_all:
                k = int(np.searchsorted(touched_j, jrow))
                _res_scalars(
                    drf.job_attrs[jobs_t[k].uid].allocated, j_tot[k],
                    scalar_names, jkeys_all[jrow], 1,
                )
        prop = self.prop
        if prop is not None:
            qrow_arr = self.job_queue[tjob]
            touched_q = np.unique(qrow_arr)
            compq = np.searchsorted(touched_q, qrow_arr)
            q_tot = _segment_sum(compq, res, touched_q.size, R)
            attrs_q = [
                prop.queue_attrs[self.enc.queues[qrow].name]
                for qrow in touched_q.tolist()
            ]
            axpy([a.allocated for a in attrs_q], q_tot, 1)
            self._touched_prop.update(a.name for a in attrs_q)
            for qrow in qkeys:
                k = int(np.searchsorted(touched_q, qrow))
                _res_scalars(
                    attrs_q[k].allocated, q_tot[k], scalar_names, qkeys[qrow], 1
                )

        # -- per-task surgery (status index, node task map, volumes) ------
        # Rows grouped per job (stable sort preserves assign order within
        # a job, which is what fixes sidx insertion order and therefore
        # dispatch/bind order); the status-index moves then land as one
        # C-level dict.update per (job, status) instead of per-task
        # get/setdefault (VERDICT r3 item 8, the replay diet). The
        # per-event body itself — status flip, node_name set, residency
        # clone, node task-map insert — runs in the native module when
        # built (kube_batch_tpu/native, round-4 replay diet), with the
        # Python loop as fallback and for volume-carrying rows.
        jobs_l = self.enc.jobs
        ALLOCATED, PIPELINED = TaskStatus.ALLOCATED, TaskStatus.PIPELINED
        order = np.argsort(compj, kind="stable")
        counts = np.bincount(compj, minlength=touched_j.size).tolist()
        rows_a = np.ascontiguousarray(rows[order], np.int64)
        nrows_a = np.ascontiguousarray(nrows[order], np.int64)
        alloc_a = alloc[order]
        # log this segment's Allocated events (job-major, assign order
        # within job — exactly the status-index insertion order) for the
        # dispatch barrier's numeric bind-column reconstruction
        self._bulk_alloc_log.append(
            (rows_a[alloc_a], nrows_a[alloc_a], tjob[order][alloc_a])
        )
        segments = None
        if self._native is not None:
            try:
                if faults.should_fire("native.prepass"):
                    raise ValueError("fault injected: native.prepass")
                # index vectors go down as int64 buffers — no 2x200k
                # PyLong boxing/unboxing round trip
                # trusted=True: encode_session routes volume-carrying
                # tasks host_only, so bulk rows are volume-free by
                # construction and the prepass skips its per-event
                # pod.volumes attribute read (~half of bulk_assign's
                # cost at 400k). "task_created" marks our encoder; a
                # custom EncodedSnapshot keeps the defensive prepass.
                segments = self._native.bulk_assign(
                    self.enc.tasks,
                    self.task_keys,
                    self.node_tasks_by_row,
                    self.enc.node_names,
                    rows_a,
                    nrows_a,
                    alloc_a.astype(np.uint8).tobytes(),
                    counts,
                    ALLOCATED,
                    PIPELINED,
                    "task_created" in self.enc.arrays,
                )
            except (ValueError, TypeError, AttributeError):
                # ValueError: a bulk row carries volume claims (custom
                # encoder/binder). TypeError/AttributeError: a TaskInfo
                # variant without the expected plain member slots. Either
                # way the prepass mutated nothing — take the Python path,
                # which routes volumes through cache.allocate_volumes and
                # handles any attribute layout.
                segments = None
        if segments is None:
            segments = self._assign_segments_py(
                rows_a.tolist(), nrows_a.tolist(), alloc_a.tolist(), counts
            )
        for k, jrow in enumerate(touched_j.tolist()):
            alloc_d, pipe_d = segments[k]
            sidx = jobs_l[jrow].task_status_index
            pend = sidx.get(TaskStatus.PENDING)
            if pend is not None:
                if len(alloc_d) + len(pipe_d) == len(pend):
                    # this segment consumed the job's every remaining
                    # pending task (uids are distinct and all drawn from
                    # pend) — drop the bucket whole instead of 200k
                    # one-at-a-time pops across the batch
                    del sidx[TaskStatus.PENDING]
                else:
                    for uid in alloc_d:
                        pend.pop(uid, None)
                    for uid in pipe_d:
                        pend.pop(uid, None)
                    if not pend:
                        del sidx[TaskStatus.PENDING]
            if alloc_d:
                d = sidx.get(ALLOCATED)
                if d is None:
                    sidx[ALLOCATED] = alloc_d
                else:
                    d.update(alloc_d)
            if pipe_d:
                d = sidx.get(PIPELINED)
                if d is None:
                    sidx[PIPELINED] = pipe_d
                else:
                    d.update(pipe_d)

    def _assign_segments_py(self, rows_o, nrows_o, alloc_o, counts):
        """Pure-Python twin of native.bulk_assign: per-event status flip,
        node_name set, residency clone, node task-map insert; returns one
        (alloc_d, pipe_d) pair per job segment."""
        tasks = self.enc.tasks
        tkeys = self.task_keys
        node_by_row = self.node_by_row
        alloc_volumes = self.ssn.cache.allocate_volumes
        ALLOCATED, PIPELINED = TaskStatus.ALLOCATED, TaskStatus.PIPELINED
        segments = []
        pos = 0
        for cnt in counts:
            end = pos + cnt
            alloc_d: dict = {}
            pipe_d: dict = {}
            for row, nrow_i, is_alloc in zip(
                rows_o[pos:end], nrows_o[pos:end], alloc_o[pos:end]
            ):
                task = tasks[row]
                node = node_by_row[nrow_i]
                if is_alloc:
                    if task.pod.volumes:
                        # bulk rows cannot carry claims (encode routes
                        # volume pods host_only) — guard kept for custom
                        # encoders/binders; the job keeps finish()'s
                        # per-task volume checks
                        alloc_volumes(task, node.name)
                        self.stepped_jobs.add(task.job)
                    else:
                        task.volume_ready = True
                    task.status = ALLOCATED
                    alloc_d[task.uid] = task
                else:
                    task.status = PIPELINED
                    pipe_d[task.uid] = task
                task.node_name = node.name
                node.tasks[tkeys[row]] = task.clone_for_residency()
            pos = end
            segments.append((alloc_d, pipe_d))
        return segments

    def _numeric_columns(self, mask_arr, to_bind):
        """(rows, keys, hostnames, created) for the pure-bulk dispatch
        list, reconstructed from the replay's Allocated event log by
        array gathers alone. Valid only when the log covers the ENTIRE
        dispatch list (a prior action in the actions string can leave
        Allocated tasks this encode never saw — the count check detects
        that and the caller falls back to the per-task column pass).
        Order matches bulk_dispatch's list: both are job-major with
        status-index insertion order within a job."""
        if not self._bulk_alloc_log or "task_created" not in self.enc.arrays:
            return None
        n_to_bind = len(to_bind)
        if len(self._bulk_alloc_log) == 1:
            rows_all, nrows_all, jrows_all = self._bulk_alloc_log[0]
        else:
            rows_all = np.concatenate([s[0] for s in self._bulk_alloc_log])
            nrows_all = np.concatenate([s[1] for s in self._bulk_alloc_log])
            jrows_all = np.concatenate([s[2] for s in self._bulk_alloc_log])
        sel = mask_arr[jrows_all]
        if int(sel.sum()) != n_to_bind:
            return None
        rows_b = rows_all[sel]
        nrows_b = nrows_all[sel]
        if len(self._bulk_alloc_log) > 1:
            # job-major across segments, preserving per-segment (=
            # bucket insertion) order within a job
            order = np.argsort(jrows_all[sel], kind="stable")
            rows_b = rows_b[order]
            nrows_b = nrows_b[order]
        tasks = self.enc.tasks
        if n_to_bind and (
            to_bind[0] is not tasks[int(rows_b[0])]
            or to_bind[-1] is not tasks[int(rows_b[-1])]
        ):
            # order drift (should not happen) — take the per-task pass
            return None
        keys = np.asarray(self.task_keys, dtype=object)[rows_b].tolist()
        hostnames = np.asarray(self.enc.node_names, dtype=object)[nrows_b].tolist()
        created = np.asarray(self.enc.arrays["task_created"], np.float64)[rows_b]
        return rows_b, keys, hostnames, created

    def _flush_nodes(self) -> None:
        """Fold the per-node resource deltas into NodeInfo, following
        Resource.add/sub scalar-map key rules (resource_info.go:146-166)."""
        scalar_names = self.enc.scalar_names
        for nrow, buf in self._node_buf.items():
            node = self.ssn.nodes[self.enc.node_names[nrow]]
            total = buf.alloc + buf.pipe
            _res_sub(node.idle, buf.alloc, scalar_names, buf.scalar_keys)
            _res_sub(node.releasing, buf.pipe, scalar_names, buf.scalar_keys)
            _res_add(node.used, total, scalar_names, buf.scalar_keys)
        self._node_buf = {}

    # -- end of action -------------------------------------------------------

    def _finish_dispatch_py(self, ready_cnt_l, job_min_l, to_bind, pure_bulk,
                            BINDING, bind_volumes, debug_on) -> None:
        """The per-job dispatch barrier loop (Python twin of the native
        bulk_dispatch fast path; also the only path handling host-stepped
        jobs, whose tasks may carry volumes)."""
        ssn = self.ssn
        for i, job in enumerate(self.enc.jobs):
            if job.uid not in self.alloc_jobs:
                continue
            if ready_cnt_l[i] < job_min_l[i]:
                continue
            allocated = job.task_status_index.get(TaskStatus.ALLOCATED)
            if not allocated:
                continue
            if job.uid not in self.stepped_jobs:
                # Pure-bulk gang: every task came through bulk_assign, so
                # it is volume-less with volume_ready=True — no per-task
                # checks, one bulk index move; the status flip for ALL
                # pure-bulk gangs is a single native call after the loop
                # (nothing observes status between here and there).
                dispatched = list(allocated.values())
                pure_bulk.extend(dispatched)
                to_bind.extend(dispatched)
                binding = job.task_status_index.setdefault(BINDING, {})
                binding.update(allocated)
                job.task_status_index.pop(TaskStatus.ALLOCATED, None)
                if debug_on:
                    log.debug(
                        "dispatched gang job %s (%d tasks)", job.uid, ready_cnt_l[i]
                    )
                continue
            dispatched = []
            failed = False
            for task in allocated.values():
                if task.pod.volumes or not task.volume_ready:
                    try:
                        bind_volumes(task)
                    except Exception as e:  # noqa: BLE001
                        # Same routing as session._dispatch: errTasks
                        # resync + stop dispatching this gang (the serial
                        # path's early return, session.go:285-295).
                        log.error("failed to bind volumes of %s: %s", task.uid, e)
                        resync = getattr(ssn.cache, "resync_task", None)
                        if resync is not None:
                            resync(task)
                        failed = True
                        break
                task.status = BINDING
                dispatched.append(task)
                to_bind.append(task)
            # status-index move as one bulk update instead of per-task
            # pop/insert; on a volume failure only the dispatched prefix
            # moves (the rest stay Allocated, exactly like the serial
            # early return).
            binding = job.task_status_index.setdefault(BINDING, {})
            if not failed:
                binding.update(allocated)
                job.task_status_index.pop(TaskStatus.ALLOCATED, None)
            else:
                for task in dispatched:
                    allocated.pop(task.uid, None)
                    binding[task.uid] = task
            if debug_on:
                log.debug("dispatched gang job %s (%d tasks)", job.uid, ready_cnt_l[i])

    def finish(self, ready_cnt) -> None:
        """Final share sync + the gang dispatch barrier."""
        from kube_batch_tpu import metrics

        ssn = self.ssn
        if self.drf is not None:
            drf = self.drf
            tot = drf.total_resource
            attrs = [drf.job_attrs[uid] for uid in self._touched_drf]
            if attrs and not tot.scalars:
                # vectorized final share sync: same comparison-dtype
                # division as helpers.share, one array op instead of
                # 2 boxed divisions x 18k touched jobs
                from kube_batch_tpu.api.numerics import comparison_dtype

                dt = comparison_dtype()
                a = np.array(
                    [(at.allocated.milli_cpu, at.allocated.memory) for at in attrs],
                    dtype=dt,
                )
                t = np.array([tot.milli_cpu, tot.memory], dtype=dt)
                s = np.where(
                    t == 0,
                    np.where(a == 0, dt(0.0), dt(1.0)),
                    a / np.where(t == 0, dt(1.0), t),
                )
                shares = np.maximum(np.maximum(s[:, 0], s[:, 1]), 0.0)
                for at, sv in zip(attrs, shares.tolist()):
                    at.share = sv
            else:
                for attr in attrs:
                    drf._update_share(attr)
        if self.prop is not None:
            for qname in self._touched_prop:
                attr = self.prop.queue_attrs[qname]
                self.prop._update_share(attr)

        job_min = self.arrays["job_min"]
        bind_volumes = ssn.cache.bind_volumes
        BINDING = TaskStatus.BINDING
        to_bind: list = []  # dispatched tasks, in dispatch order
        pure_bulk: list = []  # pure-bulk gangs' tasks: ONE status flip below
        ready_cnt_l = ready_cnt.tolist()  # one C pass, not 2 np getitems/job
        job_min_l = np.asarray(job_min).tolist()
        # Gate per-gang debug narration on the PACKAGE verbosity, not on
        # isEnabledFor: kube_batch_tpu.log._ensure_handler sets the parent
        # logger to DEBUG the first time ANY glog line is emitted (leader
        # election chatter, any errorf), which this module logger inherits
        # — isEnabledFor would then disable the native bulk_dispatch fast
        # path for the process lifetime at -v 0 (ADVICE r5, medium).
        debug_on = _glog.get_verbosity() >= 4
        mask_arr = None
        if (
            not self.stepped_jobs
            and not debug_on
            and self._native is not None
            and hasattr(self._native, "bulk_dispatch")
        ):
            # Every gang is pure-bulk (no volumes, no host steps): the
            # whole dispatch barrier is one native pass — per GANG the
            # ALLOCATED bucket moves wholesale under BINDING (dict move
            # when no bucket exists), tasks returned in dispatch order.
            # The gang-ready mask is one vector compare instead of a
            # per-job Python genexpr (the replay diet, round 6).
            jn = len(self.enc.jobs)
            mask_arr = self._alloc_flags[:jn] & (
                np.asarray(ready_cnt)[:jn] >= np.asarray(job_min)[:jn]
            )
            mask = mask_arr.astype(np.uint8).tobytes()
            try:
                if faults.should_fire("native.dispatch"):
                    raise TypeError("fault injected: native.dispatch")
                to_bind = self._native.bulk_dispatch(
                    self.enc.jobs, mask, TaskStatus.ALLOCATED, BINDING
                )
                pure_bulk = to_bind
            except (TypeError, AttributeError):
                to_bind, pure_bulk = [], []
                self._finish_dispatch_py(
                    ready_cnt_l, job_min_l, to_bind, pure_bulk, BINDING,
                    bind_volumes, debug_on,
                )
        else:
            self._finish_dispatch_py(
                ready_cnt_l, job_min_l, to_bind, pure_bulk, BINDING,
                bind_volumes, debug_on,
            )
        # Status flip + bind columns (rows / created / keys / hostnames).
        # Preferred: NUMERIC reconstruction from the bulk replay's own
        # Allocated event log — pure array gathers, no per-task dict
        # lookups or attribute reads (replaces native finish_columns on
        # the pure-bulk path); the flip is one native bulk_set_slot.
        # Fallbacks: the native finish_columns single pass, then the
        # Python per-task loop. The flip covers every dispatched task —
        # stepped-path tasks are already BINDING, re-setting the
        # identical value is a no-op.
        rows_b = created = keys = hostnames = None
        if to_bind and pure_bulk is to_bind and mask_arr is not None:
            cols = self._numeric_columns(mask_arr, to_bind)
            if cols is not None:
                rows_b, keys, hostnames, created = cols
                flipped = False
                if self._native is not None:
                    try:
                        self._native.bulk_set_slot(to_bind, "status", BINDING)
                        flipped = True
                    except (TypeError, AttributeError):
                        pass
                if not flipped:
                    for task in to_bind:
                        task.status = BINDING
        if to_bind and rows_b is None:
            if self._native is not None and hasattr(self._native, "finish_columns"):
                try:
                    rb, cb, keys, hostnames = self._native.finish_columns(
                        to_bind, self.row_of, self.task_keys, BINDING
                    )
                    rows_b = np.frombuffer(rb, np.int64)
                    created = np.frombuffer(cb, np.float64)
                except (TypeError, AttributeError):
                    rows_b = created = keys = hostnames = None
            if rows_b is None:
                # flip the pure-bulk gangs (a partial native prefix flip
                # is harmless: same value re-set)
                flipped = False
                if pure_bulk and self._native is not None:
                    try:
                        self._native.bulk_set_slot(pure_bulk, "status", BINDING)
                        flipped = True
                    except (TypeError, AttributeError):
                        pass
                if pure_bulk and not flipped:
                    for task in pure_bulk:
                        task.status = BINDING
                row_of = self.row_of
                tk = self.task_keys
                rows_b = np.fromiter(
                    (row_of.get(t.uid, -1) for t in to_bind),
                    np.int64,
                    count=len(to_bind),
                )
                created = np.fromiter(
                    (t.pod.metadata.creation_timestamp for t in to_bind),
                    np.float64,
                    count=len(to_bind),
                )
                keys = [
                    tk[r] if r >= 0 else f"{t.namespace}/{t.name}"
                    for t, r in zip(to_bind, rows_b.tolist())
                ]
                hostnames = [t.node_name for t in to_bind]
        # Bulk bind: one cache mutex acquisition + one async write batch
        # for the whole action's dispatches (the replay-diet half of
        # VERDICT r3 item 8 — per-task cache.bind was the replay's
        # single largest cost at 50k).
        if to_bind:
            keyed_bind = getattr(ssn.cache, "bind_many_keyed", None)
            bind_many = getattr(ssn.cache, "bind_many", None)
            if keyed_bind is not None:
                # parallel-list form: no 200k (task, host) tuple builds
                keyed_bind(to_bind, hostnames, keys)
            elif bind_many is not None:
                pairs = list(zip(to_bind, hostnames))
                if _accepts_keys(bind_many):
                    bind_many(pairs, keys=keys)
                else:
                    bind_many(pairs)
            else:
                for t, h in zip(to_bind, hostnames):
                    ssn.cache.bind(t, h)
        if to_bind:
            # e2e scheduling latency per dispatched pod, as one vector op
            # instead of a 50k-iteration max() loop. Each task's latency
            # ends at ITS solve segment's completion (decided_at), not at
            # one post-replay batch timestamp (reference metrics.go:66-72
            # stamps per task at dispatch). A gang can also carry tasks a
            # PRIOR action allocated (e.g. serial allocate earlier in the
            # actions string) that this encode never saw — those stamp at
            # dispatch time, exactly as the serial path would have.
            import time as _time

            decided = np.where(
                rows_b >= 0, self.decided_at[np.maximum(rows_b, 0)], _time.time()
            )
            metrics.update_task_schedule_durations(
                np.maximum(0.0, decided - created)
            )


def _accepts_keys(bind_many) -> bool:
    """Signature-probe for the keys= extension — catching TypeError
    around the CALL would misread an internal TypeError raised after
    partial submission as 'no keys support' and double-submit the
    batch."""
    import inspect

    try:
        params = inspect.signature(bind_many).parameters
    except (TypeError, ValueError):
        return False
    return "keys" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    )


def _segment_sum(seg_ids, vecs, n_segments: int, R: int) -> np.ndarray:
    """[n_segments, R] column-wise weighted bincount — the net effect of
    `np.add.at(out, seg_ids, vecs)` but ~10x faster (ufunc.at is a
    scalar scatter loop; bincount is one C pass per column). Exact:
    integer-grid float64 sums are order-independent."""
    out = np.zeros((n_segments, R))
    if seg_ids.size == 0 or n_segments == 0:
        return out
    for r in range(R):
        out[:, r] = np.bincount(seg_ids, weights=vecs[:, r], minlength=n_segments)
    return out


class _NodeDelta:
    __slots__ = ("alloc", "pipe", "scalar_keys")

    def __init__(self) -> None:
        self.alloc = 0.0  # np broadcasts to [R] on first +=
        self.pipe = 0.0
        self.scalar_keys: set[str] = set()


def _res_sub(res, vec, scalar_names, keys) -> None:
    """Resource -= vec with the Go nil-map branch: scalar entries change
    only when the receiver already tracks scalars (resource_info.go:151-153)."""
    if np.ndim(vec) == 0:  # this pool saw no assignments
        return
    res.milli_cpu -= float(vec[0])
    res.memory -= float(vec[1])
    if res.scalars and keys:
        for k in keys:
            res.scalars[k] = res.scalars.get(k, 0.0) - float(vec[2 + scalar_names.index(k)])


def _res_add(res, vec, scalar_names, keys) -> None:
    if np.ndim(vec) == 0:
        return
    res.milli_cpu += float(vec[0])
    res.memory += float(vec[1])
    for k in keys:
        res.scalars[k] = res.scalars.get(k, 0.0) + float(vec[2 + scalar_names.index(k)])


def _res_scalars(res, vec, scalar_names, keys, sign, nil_map: bool = False) -> None:
    """Scalar-dimension half of _res_add/_res_sub, for when the dense
    cpu/mem columns already went through native bulk_res_axpy. With
    ``nil_map`` the receiver's empty scalar map stays empty
    (resource_info.go:151-153 sub semantics); adds create entries."""
    if not keys or np.ndim(vec) == 0:
        return
    if nil_map and not res.scalars:
        return
    for k in keys:
        res.scalars[k] = res.scalars.get(k, 0.0) + sign * float(
            vec[2 + scalar_names.index(k)]
        )


def new() -> Action:
    return XlaAllocateAction()
