"""xla_allocate action: the allocate loop as one XLA program.

Drop-in replacement for the serial allocate action (conf
``actions: "enqueue, xla_allocate, backfill"``): encodes the session
snapshot to SoA tensors (ops.encode), runs the jitted gang-aware solve
(ops.kernels.solve_allocate) that vectorizes the reference's per-task
node scans (scheduler_helper.go:34-109) over the whole node axis, then
replays the resulting assignments through the ordinary session mutations
in kernel order — so plugin event handlers, the gang dispatch barrier
(session.go:285-293) and cache binds fire exactly as the serial action
would have fired them.

Scope guard: snapshots outside the kernel's modeled policy envelope fall
back to the serial action for that cycle (correctness first):

- pending tasks with required pod (anti-)affinity — pairwise-dynamic
  predicate (predicates.go:187-199), host-side only;
- tiers enabling plugins with dynamic ordering/share state the kernel
  does not yet fold into its loop (drf, proportion).

NodesFitDelta diagnostics (allocate.go:139-145,162-168) are not
reproduced — they are human-readable FitError text, not policy.
"""

from __future__ import annotations

import jax  # noqa: F401  -- fail registration, not mid-cycle, when absent
import numpy as np

from kube_batch_tpu.framework.interface import Action
from kube_batch_tpu.framework.session import Session

# Plugins whose session hooks the kernel models exactly (priority/gang
# ordering + barrier, predicates masks, nodeorder score) or that register
# nothing the allocate path consults (conformance: preempt/reclaim only).
_SUPPORTED_PLUGINS = {"priority", "gang", "predicates", "nodeorder", "conformance"}


def _nodeorder_weights(ssn: Session) -> tuple[float, float, float]:
    """(w_least, w_balanced, w_aff) from the tiers, matching the serial
    plugin's defaults (nodeorder.go:139-153)."""
    from kube_batch_tpu.framework.arguments import Arguments
    from kube_batch_tpu.plugins.nodeorder import (
        BALANCED_RESOURCE_WEIGHT,
        LEAST_REQUESTED_WEIGHT,
        NODE_AFFINITY_WEIGHT,
    )

    for tier in ssn.tiers:
        for option in tier.plugins:
            if option.name == "nodeorder" and option.enabled_node_order:
                args = Arguments(option.arguments)
                return (
                    args.get_int(LEAST_REQUESTED_WEIGHT, 1),
                    args.get_int(BALANCED_RESOURCE_WEIGHT, 1),
                    args.get_int(NODE_AFFINITY_WEIGHT, 1),
                )
    return 0.0, 0.0, 0.0


# The per-plugin enable flags the conf schema knows (conf/__init__.py);
# the kernel models the all-defaults (True) configuration of each.
_ENABLE_FLAGS = (
    "enabled_job_order",
    "enabled_job_ready",
    "enabled_job_pipelined",
    "enabled_task_order",
    "enabled_preemptable",
    "enabled_reclaimable",
    "enabled_queue_order",
    "enabled_predicate",
    "enabled_node_order",
)


def _kernel_supported(ssn: Session) -> bool:
    """True when the tiers describe exactly the policy the kernel
    hardwires: priority ordering first, then the gang barrier, with
    predicate masks on — i.e. the reference's default tier-1 plus
    predicates/nodeorder. Anything else (extra plugins, disabled enable
    flags, gang before priority, missing gang/predicates) would make the
    kernel silently diverge from the serial oracle, so it falls back."""
    order: list[str] = []
    for tier in ssn.tiers:
        for option in tier.plugins:
            if option.name not in _SUPPORTED_PLUGINS:
                return False
            if not all(getattr(option, flag, True) for flag in _ENABLE_FLAGS):
                return False
            order.append(option.name)
    # priority + gang must both be present, priority first (the kernel's
    # job/task keys are (-prio, ready, creation/uid) in that order).
    if "priority" not in order or "gang" not in order:
        return False
    if order.index("priority") > order.index("gang"):
        return False
    return "predicates" in order


class XlaAllocateAction(Action):
    """The TPU-native allocate. Falls back to serial when out of envelope."""

    def __init__(self, dtype=None) -> None:
        # float64 gives bit-parity with the serial float64 path (CPU
        # equivalence tests); float32 is the TPU bench dtype — exact for
        # milli/MiB-granular quantities (ops/encode.py docstring).
        self._dtype = dtype

    @property
    def name(self) -> str:
        return "xla_allocate"

    def execute(self, ssn: Session) -> None:
        from kube_batch_tpu.ops.encode import encode_session
        from kube_batch_tpu.ops.kernels import (
            KIND_ALLOCATED,
            KIND_PIPELINED,
            solve_allocate,
        )

        if not _kernel_supported(ssn):
            self._fallback(ssn)
            return

        import jax.numpy as jnp

        dtype = self._dtype
        if dtype is None:
            dtype = np.float64 if jnp.zeros(0).dtype == np.float64 else np.float32

        enc = encode_session(ssn.jobs, ssn.nodes, ssn.queues, dtype=dtype)
        if enc.has_host_only:
            self._fallback(ssn)
            return
        if not enc.tasks:
            return

        w_least, w_balanced, w_aff = _nodeorder_weights(ssn)
        arrays = dict(enc.arrays)
        arrays["w_least"] = dtype(w_least)
        arrays["w_balanced"] = dtype(w_balanced)
        arrays["w_aff"] = dtype(w_aff)

        result = solve_allocate(arrays)
        assigned_node = np.asarray(result.assigned_node)
        assigned_kind = np.asarray(result.assigned_kind)
        assign_pos = np.asarray(result.assign_pos)

        # Replay in kernel assignment order so event handlers and the
        # gang dispatch barrier fire in the serial action's order.
        rows = np.nonzero(assign_pos >= 0)[0]
        rows = rows[np.argsort(assign_pos[rows], kind="stable")]
        for row in rows:
            task = enc.tasks[row]
            hostname = enc.node_names[int(assigned_node[row])]
            if assigned_kind[row] == KIND_ALLOCATED:
                ssn.allocate(task, hostname)
            elif assigned_kind[row] == KIND_PIPELINED:
                ssn.pipeline(task, hostname)

    @staticmethod
    def _fallback(ssn: Session) -> None:
        from kube_batch_tpu.actions.allocate import AllocateAction

        AllocateAction().execute(ssn)


def new() -> Action:
    return XlaAllocateAction()
