"""Build the native hot-loop extension with the system toolchain.

No pip/pybind11: the module is plain CPython C API, compiled with g++
straight against this interpreter's headers. `ensure()` is idempotent
and cheap — it rebuilds only when `_hotloops.cpp` is newer than the
built artifact — so the package can call it lazily at import and a
toolchain-less host simply falls back to the pure-Python loops.

Manual (re)build:  python -m kube_batch_tpu.native.build
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_DIR, "_hotloops.cpp")


def artifact_path() -> str:
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_DIR, "_hotloops" + ext)


def ensure(verbose: bool = False) -> str:
    """Build if stale/missing; return the artifact path."""
    out = artifact_path()
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(SOURCE):
        return out
    include = sysconfig.get_paths()["include"]
    # A build killed mid-compile leaves its pid-stamped temp behind —
    # sweep stale siblings before writing a fresh one. Age-gated so a
    # concurrent builder's live temp (the reason temps are per-pid at
    # all) is never yanked out from under its linker.
    import time

    cutoff = time.time() - 300
    for stale in os.listdir(_DIR):
        if stale.startswith("_hotloops") and stale.endswith(".tmp"):
            p = os.path.join(_DIR, stale)
            try:
                if os.path.getmtime(p) < cutoff:
                    os.unlink(p)
            except OSError:
                pass
    tmp = f"{out}.{os.getpid()}.tmp"  # per-process: concurrent builds race on os.replace, not on the write
    cmd = [
        "g++",
        "-O2",
        "-std=c++17",
        "-fPIC",
        "-shared",
        f"-I{include}",
        SOURCE,
        "-o",
        tmp,
    ]
    subprocess.run(
        cmd,
        check=True,
        stdout=None if verbose else subprocess.DEVNULL,
        stderr=None if verbose else subprocess.PIPE,
    )
    os.replace(tmp, out)  # atomic vs concurrent importers
    return out


if __name__ == "__main__":
    print(ensure(verbose=True))
