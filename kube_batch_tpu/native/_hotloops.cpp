/* Native hot loops for the replay path (CPython extension).
 *
 * The reference scheduler's runtime is compiled Go end to end; here the
 * TPU solve is compiled XLA/Mosaic, and this module compiles the one
 * remaining interpreter-bound stretch: the bulk session-mutation loop
 * that replays kernel assignments into Python session objects
 * (actions/xla_allocate._Replayer.apply_upto — the net state mutations
 * of ssn.allocate/pipeline, session.go:198-296, at 50k-100k events per
 * cycle).
 *
 * Approach: TaskInfo (api/job_info.py) is a __slots__ class, so its
 * attributes live at fixed byte offsets published by the class's
 * member descriptors (PyMemberDescrObject.d_member->offset). We cache
 * the offsets per type and do the per-event work — status flip,
 * node_name set, residency clone (clone_for_residency parity: shares
 * Resource objects, copies every slot), node task-map insert, status-
 * index dict build — as direct pointer stores + PyDict_SetItem calls,
 * with no interpreter frames. Everything is plain public CPython API
 * (descrobject.h, PyType_GenericAlloc via tp_alloc); a type without
 * the expected slots raises and the caller falls back to the pure-
 * Python loop.
 *
 * Build: kube_batch_tpu/native/build.py (g++ -O2 -shared -fPIC);
 * loaded lazily by kube_batch_tpu/native/__init__.py with a pure-
 * Python fallback when the toolchain is absent (KBT_NATIVE=0 disables).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#include <algorithm>
#include <cstring>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

/* ---- slot offset cache --------------------------------------------------- */

constexpr int kNumSlots = 11;
/* Order matches TaskInfo.__slots__ (api/job_info.py); the clone copies
 * all of them, the surgery writes a subset. */
const char* const kSlotNames[kNumSlots] = {
    "uid",     "job",    "name",     "namespace", "resreq", "init_resreq",
    "node_name", "status", "priority", "volume_ready", "pod",
};
constexpr int kUid = 0;
constexpr int kName = 2;
constexpr int kNamespace = 3;
constexpr int kNodeName = 6;
constexpr int kStatus = 7;
constexpr int kPriority = 8;
constexpr int kVolumeReady = 9;
constexpr int kPod = 10;

struct SlotCache {
  PyTypeObject* type = nullptr;  // borrowed; identity-checked per call
  Py_ssize_t off[kNumSlots];
};

SlotCache g_task_slots;

/* Resolve the byte offset of each __slots__ member descriptor on `tp`.
 * Returns 0 on success, -1 (with a Python error set) when any name is
 * not a plain member slot — the caller then uses the Python path.
 * Resolves into a local table and commits atomically so a mid-loop
 * failure cannot leave a half-overwritten cache behind a stale type
 * identity. */
int resolve_slots(PyTypeObject* tp, SlotCache* cache) {
  SlotCache local;
  for (int i = 0; i < kNumSlots; i++) {
    PyObject* descr = PyObject_GetAttrString((PyObject*)tp, kSlotNames[i]);
    if (descr == nullptr) return -1;
    if (Py_TYPE(descr) != &PyMemberDescr_Type) {
      Py_DECREF(descr);
      PyErr_Format(PyExc_TypeError, "%s.%s is not a slot member",
                   tp->tp_name, kSlotNames[i]);
      return -1;
    }
    local.off[i] = ((PyMemberDescrObject*)descr)->d_member->offset;
    Py_DECREF(descr);
  }
  local.type = tp;
  *cache = local;
  return 0;
}

inline PyObject* get_slot(PyObject* o, Py_ssize_t off) {
  return *(PyObject**)((char*)o + off);  // borrowed
}

inline void set_slot(PyObject* o, Py_ssize_t off, PyObject* v) {
  PyObject** p = (PyObject**)((char*)o + off);
  Py_INCREF(v);
  PyObject* old = *p;
  *p = v;
  Py_XDECREF(old);
}

/* clone_for_residency parity: new instance of the same type, every slot
 * shared by reference (Resource objects included — they are never
 * mutated on a TaskInfo after construction; see job_info.py docstring).
 *
 * The clone is removed from cycle-GC tracking: nothing a TaskInfo
 * references (strings, Resource, Pod, TaskStatus) can reach the clone
 * back — the clone lives only in NodeInfo.tasks, never in the job
 * indexes — so it cannot participate in a cycle and plain refcounting
 * frees it. Untracking keeps 50k-100k fresh clones out of every gen-0
 * collection during the replay. */
PyObject* clone_slots(PyObject* task, const SlotCache& sc) {
  PyTypeObject* tp = Py_TYPE(task);
  PyObject* cl = tp->tp_alloc(tp, 0);
  if (cl == nullptr) return nullptr;
  for (int i = 0; i < kNumSlots; i++) {
    PyObject* v = get_slot(task, sc.off[i]);
    Py_XINCREF(v);
    *(PyObject**)((char*)cl + sc.off[i]) = v;
  }
  if (PyObject_GC_IsTracked(cl)) PyObject_GC_UnTrack(cl);
  return cl;
}

/* ---- bulk_assign --------------------------------------------------------- */

PyObject* g_volumes_name = nullptr;  // interned "volumes"

/* bulk_assign(tasks, tkeys, node_tasks, node_names, rows, nrows,
 *             allocs, counts, ALLOCATED, PIPELINED)
 *
 *   tasks      list[TaskInfo]  row-indexed (encoder order)
 *   tkeys      list[str]       row-indexed "ns/name" node-map keys
 *   node_tasks list[dict]      per node row: NodeInfo.tasks
 *   node_names list[str]       per node row: node name
 *   rows       list[int]       event rows, kernel order grouped per job
 *   nrows      list[int]       event node rows (parallel to rows)
 *   allocs     bytes           1 = Allocated, 0 = Pipelined (parallel)
 *   counts     list[int]       events per job segment (sum = len(rows))
 *   ALLOCATED / PIPELINED      TaskStatus members
 *
 * Per event, exactly the Python loop's mutations in its order:
 *   volume_ready=True (Allocated, volume-less), status flip, uid->task
 *   into the segment's alloc/pipe dict, node_name set, residency clone
 *   into node_tasks[nrow][tkeys[row]].
 * Returns list[(alloc_d, pipe_d)] per segment.
 *
 * A task with pod.volumes on an Allocated event needs the volume
 * binder (host-side assume) — detected in a mutation-free prepass and
 * raised as ValueError so the caller falls back cleanly. */
/* rows/nrows arrive as Python int lists OR int64 buffers (numpy
 * arrays) — the buffer form spares the caller 2n PyLong boxings and
 * this function 2n unboxings on the 200k-event replay path. */
static int read_index_vec(PyObject* obj, Py_ssize_t* out, Py_ssize_t n,
                          Py_ssize_t limit, const char* what) {
  if (PyList_Check(obj)) {
    if (PyList_GET_SIZE(obj) != n) {
      PyErr_Format(PyExc_ValueError, "%s length mismatch", what);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      Py_ssize_t v = PyLong_AsSsize_t(PyList_GET_ITEM(obj, i));
      if (v == -1 && PyErr_Occurred()) return -1;
      if (v < 0 || v >= limit) {
        PyErr_SetString(PyExc_IndexError, "row index out of range");
        return -1;
      }
      out[i] = v;
    }
    return 0;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(obj, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
    return -1;
  int rc = -1;
  if (view.ndim != 1 || view.shape[0] != n || view.itemsize != 8 ||
      view.format == nullptr ||
      !(view.format[0] == 'l' || view.format[0] == 'q')) {
    PyErr_Format(PyExc_TypeError, "%s must be an int64 vector of length %zd",
                 what, n);
  } else {
    const int64_t* src = (const int64_t*)view.buf;
    rc = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
      if (src[i] < 0 || src[i] >= limit) {
        PyErr_SetString(PyExc_IndexError, "row index out of range");
        rc = -1;
        break;
      }
      out[i] = (Py_ssize_t)src[i];
    }
  }
  PyBuffer_Release(&view);
  return rc;
}

PyObject* bulk_assign(PyObject*, PyObject* args) {
  PyObject *tasks, *tkeys, *node_tasks, *node_names, *rows, *nrows;
  PyObject *allocs, *counts, *st_alloc, *st_pipe;
  /* trusted=1: the caller vouches that no bulk row carries volume
   * claims (ops/encode.py routes volume pods host_only, so rows from
   * that encoder satisfy it by construction) — the per-event
   * pod.volumes GetAttr, measured at ~half this function's cost on a
   * 400k replay, is skipped. Custom encoders must pass 0 (default). */
  int trusted = 0;
  if (!PyArg_ParseTuple(args, "O!O!O!O!OOSO!OO|p", &PyList_Type, &tasks,
                        &PyList_Type, &tkeys, &PyList_Type, &node_tasks,
                        &PyList_Type, &node_names, &rows,
                        &nrows, &allocs, &PyList_Type, &counts,
                        &st_alloc, &st_pipe, &trusted))
    return nullptr;

  Py_ssize_t n = PyBytes_GET_SIZE(allocs);
  const char* is_alloc = PyBytes_AS_STRING(allocs);
  Py_ssize_t n_tasks = PyList_GET_SIZE(tasks);
  Py_ssize_t n_nodes = PyList_GET_SIZE(node_tasks);
  if (PyList_GET_SIZE(tkeys) != n_tasks ||
      PyList_GET_SIZE(node_names) != n_nodes) {
    PyErr_SetString(PyExc_ValueError, "tkeys/node_names length mismatch");
    return nullptr;
  }

  /* Decode row/nrow indices once, bounds-checked. */
  Py_ssize_t n_seg = PyList_GET_SIZE(counts);
  Py_ssize_t* seg_cnt = nullptr;  // freed at fail_ix (PyMem_Free(NULL) ok)
  Py_ssize_t* row_ix = (Py_ssize_t*)PyMem_Malloc(2 * n * sizeof(Py_ssize_t));
  if (row_ix == nullptr && n > 0) return PyErr_NoMemory();
  Py_ssize_t* nrow_ix = row_ix + n;
  if (read_index_vec(rows, row_ix, n, n_tasks, "rows") < 0 ||
      read_index_vec(nrows, nrow_ix, n, n_nodes, "nrows") < 0)
    goto fail_ix;

  {
    /* Slot offsets for this TaskInfo type (cached across calls). */
    if (n > 0) {
      PyTypeObject* tp = Py_TYPE(PyList_GET_ITEM(tasks, row_ix[0]));
      if (g_task_slots.type != tp && resolve_slots(tp, &g_task_slots) < 0)
        goto fail_ix;
    }
    const SlotCache& sc = g_task_slots;

    /* Mutation-free prepass: homogeneous types, the volume guard, and
     * segment-count consistency — every error this function can raise
     * is guaranteed pre-mutation, which is what the caller's "the
     * prepass mutated nothing" fallback comment relies on. Counts are
     * parsed ONCE here into a C array; the mutation loop below never
     * touches the Python list again, so the guarantee is structural. */
    seg_cnt = (Py_ssize_t*)PyMem_Malloc((n_seg > 0 ? n_seg : 1) *
                                        sizeof(Py_ssize_t));
    if (seg_cnt == nullptr) {
      PyErr_NoMemory();
      goto fail_ix;
    }
    {
      Py_ssize_t total = 0;
      for (Py_ssize_t s = 0; s < n_seg; s++) {
        Py_ssize_t cnt = PyLong_AsSsize_t(PyList_GET_ITEM(counts, s));
        if (cnt == -1 && PyErr_Occurred()) goto fail_ix;
        if (cnt < 0 || cnt > n - total) {  // keeps total <= n: no overflow
          PyErr_SetString(PyExc_ValueError, "segment count out of range");
          goto fail_ix;
        }
        seg_cnt[s] = cnt;
        total += cnt;
      }
      if (total != n) {
        PyErr_SetString(PyExc_ValueError,
                        "counts do not sum to the event total");
        goto fail_ix;
      }
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* task = PyList_GET_ITEM(tasks, row_ix[i]);
      if (Py_TYPE(task) != sc.type) {
        PyErr_SetString(PyExc_TypeError, "mixed TaskInfo types in batch");
        goto fail_ix;
      }
      PyObject* uid_pre = get_slot(task, sc.off[kUid]);
      if (uid_pre == nullptr) {
        // the mutation loop uses uid as a dict key — a NULL there would
        // crash the interpreter mid-mutation
        PyErr_SetString(PyExc_AttributeError, "task.uid slot unset");
        goto fail_ix;
      }
      if (!PyUnicode_Check(uid_pre)) {
        // non-str uid could raise at hash time inside the mutation loop
        PyErr_SetString(PyExc_TypeError, "task.uid is not a str");
        goto fail_ix;
      }
      if (is_alloc[i] && !trusted) {
        PyObject* pod = get_slot(task, sc.off[kPod]);
        if (pod == nullptr) {
          PyErr_SetString(PyExc_AttributeError, "task.pod slot unset");
          goto fail_ix;
        }
        PyObject* vols = PyObject_GetAttr(pod, g_volumes_name);
        if (vols == nullptr) goto fail_ix;
        int truthy = PyObject_IsTrue(vols);
        Py_DECREF(vols);
        if (truthy < 0) goto fail_ix;
        if (truthy) {
          PyErr_SetString(PyExc_ValueError,
                          "bulk row carries volume claims (needs host-side "
                          "assume); use the Python path");
          goto fail_ix;
        }
      }
    }

    PyObject* out = PyList_New(n_seg);
    if (out == nullptr) goto fail_ix;
    Py_ssize_t i = 0;
    for (Py_ssize_t s = 0; s < n_seg; s++) {
      PyObject* alloc_d = PyDict_New();
      PyObject* pipe_d = PyDict_New();
      PyObject* pair = (alloc_d && pipe_d) ? PyTuple_Pack(2, alloc_d, pipe_d)
                                           : nullptr;
      Py_XDECREF(alloc_d);
      Py_XDECREF(pipe_d);
      if (pair == nullptr) goto fail_out;
      PyList_SET_ITEM(out, s, pair);
      Py_ssize_t end = i + seg_cnt[s];  // prepass: sums to n exactly
      for (; i < end; i++) {
        PyObject* task = PyList_GET_ITEM(tasks, row_ix[i]);
        PyObject* uid = get_slot(task, sc.off[kUid]);
        if (is_alloc[i]) {
          set_slot(task, sc.off[kVolumeReady], Py_True);
          set_slot(task, sc.off[kStatus], st_alloc);
          if (PyDict_SetItem(alloc_d, uid, task) < 0) goto fail_out;
        } else {
          set_slot(task, sc.off[kStatus], st_pipe);
          if (PyDict_SetItem(pipe_d, uid, task) < 0) goto fail_out;
        }
        set_slot(task, sc.off[kNodeName],
                 PyList_GET_ITEM(node_names, nrow_ix[i]));
        PyObject* cl = clone_slots(task, sc);
        if (cl == nullptr) goto fail_out;
        PyObject* ntd = PyList_GET_ITEM(node_tasks, nrow_ix[i]);
        int rc = PyDict_SetItem(ntd, PyList_GET_ITEM(tkeys, row_ix[i]), cl);
        Py_DECREF(cl);
        if (rc < 0) goto fail_out;
      }
    }
    PyMem_Free(seg_cnt);
    PyMem_Free(row_ix);
    return out;
  fail_out:
    Py_DECREF(out);
  }
fail_ix:
  PyMem_Free(seg_cnt);
  PyMem_Free(row_ix);
  return nullptr;
}

/* ---- Resource slot access (shared by collect_pending + extractors) ------- */

constexpr int kSlotJob = 1;
constexpr int kSlotResreq = 4;
constexpr int kSlotInitResreq = 5;

/* Resource slots (api/resource_info.py). */
constexpr int kNumResSlots = 3;
const char* const kResSlotNames[kNumResSlots] = {"milli_cpu", "memory",
                                                 "scalars"};
struct ResSlotCache {
  PyTypeObject* type = nullptr;
  Py_ssize_t off[kNumResSlots];
};
ResSlotCache g_res_slots;

int resolve_res_slots(PyTypeObject* tp, ResSlotCache* cache) {
  ResSlotCache local;  // committed atomically; see resolve_slots
  for (int i = 0; i < kNumResSlots; i++) {
    PyObject* descr = PyObject_GetAttrString((PyObject*)tp, kResSlotNames[i]);
    if (descr == nullptr) return -1;
    if (Py_TYPE(descr) != &PyMemberDescr_Type) {
      Py_DECREF(descr);
      PyErr_Format(PyExc_TypeError, "%s.%s is not a slot member",
                   tp->tp_name, kResSlotNames[i]);
      return -1;
    }
    local.off[i] = ((PyMemberDescrObject*)descr)->d_member->offset;
    Py_DECREF(descr);
  }
  local.type = tp;
  *cache = local;
  return 0;
}

/* Read resource.milli_cpu / resource.memory as doubles; -1 on error. */
inline int res_cpu_mem(PyObject* res, const ResSlotCache& rc, double* cpu,
                       double* mem) {
  PyObject* c = get_slot(res, rc.off[0]);
  PyObject* m = get_slot(res, rc.off[1]);
  if (c == nullptr || m == nullptr) {
    PyErr_SetString(PyExc_AttributeError, "Resource slot unset");
    return -1;
  }
  *cpu = PyFloat_AsDouble(c);
  if (*cpu == -1.0 && PyErr_Occurred()) return -1;
  *mem = PyFloat_AsDouble(m);
  if (*mem == -1.0 && PyErr_Occurred()) return -1;
  return 0;
}

/* ---- collect_pending ------------------------------------------------------ */

struct SortKey {
  long prio;
  double ts;
  PyObject* uid;  // borrowed
  PyObject* task; // borrowed
  char plain;
};

/* collect_pending(jobs, PENDING, eps_cpu, eps_mem, eps_scalar)
 *
 * The encoder's per-job pending extraction (ops/encode.py
 * encode_session): for each JobInfo, take task_status_index[PENDING]
 * in insertion order, drop tasks whose resreq is empty (every
 * dimension under its epsilon — resource_info.py is_empty), sort the
 * rest by (priority desc, pod creation_timestamp, uid) — the serial
 * pop order (session_plugins.go:329-341) — and classify each task as
 * "plain": no node selector, no affinity, no tolerations, no volumes,
 * a single port-less container. Plain tasks skip every per-task
 * signature/ports/label-key pass on the Python side.
 *
 * Returns list[(sorted_task_list, plain_flags_bytes)] parallel to
 * `jobs`. */
/* Interned attribute names, resolved once at module init (same pattern
 * as g_volumes_name). */
PyObject* g_idx_name = nullptr;
PyObject* g_meta_name = nullptr;
PyObject* g_ts_name = nullptr;
PyObject* g_sel_name = nullptr;
PyObject* g_aff_name = nullptr;
PyObject* g_tol_name = nullptr;
PyObject* g_cont_name = nullptr;
PyObject* g_ports_name = nullptr;

PyObject* collect_pending(PyObject*, PyObject* args) {
  PyObject *jobs, *pending_key;
  double eps_cpu, eps_mem, eps_sc;
  if (!PyArg_ParseTuple(args, "O!Oddd", &PyList_Type, &jobs, &pending_key,
                        &eps_cpu, &eps_mem, &eps_sc))
    return nullptr;

  PyObject* idx_name = g_idx_name;
  PyObject* meta_name = g_meta_name;
  PyObject* ts_name = g_ts_name;
  PyObject* sel_name = g_sel_name;
  PyObject* aff_name = g_aff_name;
  PyObject* tol_name = g_tol_name;
  PyObject* cont_name = g_cont_name;
  PyObject* ports_name = g_ports_name;

  Py_ssize_t n_jobs = PyList_GET_SIZE(jobs);
  PyObject* out = PyList_New(n_jobs);
  if (out == nullptr) return nullptr;
  std::vector<SortKey> keys;

  for (Py_ssize_t ji = 0; ji < n_jobs; ji++) {
    PyObject* job = PyList_GET_ITEM(jobs, ji);
    PyObject* sidx = PyObject_GetAttr(job, idx_name);
    if (sidx == nullptr || !PyDict_Check(sidx)) {
      Py_XDECREF(sidx);
      if (!PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "task_status_index is not a dict");
      goto fail;
    }
    PyObject* pend = PyDict_GetItemWithError(sidx, pending_key);  // borrowed
    Py_DECREF(sidx);
    if (pend == nullptr && PyErr_Occurred()) goto fail;
    keys.clear();
    if (pend != nullptr) {
      if (!PyDict_Check(pend)) {
        PyErr_SetString(PyExc_TypeError, "status bucket is not a dict");
        goto fail;
      }
      Py_ssize_t pos = 0;
      PyObject *k, *task;
      while (PyDict_Next(pend, &pos, &k, &task)) {
        PyTypeObject* tp = Py_TYPE(task);
        if (g_task_slots.type != tp && resolve_slots(tp, &g_task_slots) < 0)
          goto fail;
        const SlotCache& sc = g_task_slots;
        PyObject* rr = get_slot(task, sc.off[kSlotResreq]);
        if (rr == nullptr) {
          PyErr_SetString(PyExc_AttributeError, "resreq slot unset");
          goto fail;
        }
        PyTypeObject* rtp = Py_TYPE(rr);
        if (g_res_slots.type != rtp &&
            resolve_res_slots(rtp, &g_res_slots) < 0)
          goto fail;
        const ResSlotCache& rc = g_res_slots;
        double cpu, mem;
        if (res_cpu_mem(rr, rc, &cpu, &mem) < 0) goto fail;
        // is_empty parity (resource_info.py): below-epsilon everywhere
        bool empty = cpu < eps_cpu && mem < eps_mem;
        PyObject* scal = get_slot(rr, rc.off[2]);
        if (empty && scal != nullptr && PyDict_Check(scal) &&
            PyDict_GET_SIZE(scal) > 0) {
          Py_ssize_t spos = 0;
          PyObject *sk, *sv;
          while (PyDict_Next(scal, &spos, &sk, &sv)) {
            double q = PyFloat_AsDouble(sv);
            if (q == -1.0 && PyErr_Occurred()) goto fail;
            if (!(q < eps_sc)) {
              empty = false;
              break;
            }
          }
        }
        if (empty) continue;  // backfill's business, not allocate's

        SortKey key;
        key.task = task;
        PyObject* pr = get_slot(task, sc.off[kPriority]);
        key.prio = pr ? PyLong_AsLong(pr) : 0;
        if (key.prio == -1 && PyErr_Occurred()) goto fail;
        key.uid = get_slot(task, sc.off[kUid]);
        if (key.uid == nullptr || !PyUnicode_Check(key.uid)) {
          PyErr_SetString(PyExc_TypeError, "task.uid is not a str");
          goto fail;
        }
        PyObject* pod = get_slot(task, sc.off[kPod]);
        if (pod == nullptr) {
          PyErr_SetString(PyExc_AttributeError, "task.pod slot unset");
          goto fail;
        }
        PyObject* meta = PyObject_GetAttr(pod, meta_name);
        PyObject* ts = meta ? PyObject_GetAttr(meta, ts_name) : nullptr;
        Py_XDECREF(meta);
        if (ts == nullptr) goto fail;
        key.ts = PyFloat_AsDouble(ts);
        Py_DECREF(ts);
        if (key.ts == -1.0 && PyErr_Occurred()) goto fail;

        // plain-ness: selector / affinity / tolerations / volumes /
        // single port-less container (mirrors _task_signature's and
        // _task_ports' fast paths)
        key.plain = 0;
        PyObject* v = PyObject_GetAttr(pod, sel_name);
        if (v == nullptr) goto fail;
        int truthy = PyObject_IsTrue(v);
        Py_DECREF(v);
        if (truthy < 0) goto fail;
        if (!truthy) {
          v = PyObject_GetAttr(pod, aff_name);
          if (v == nullptr) goto fail;
          bool aff_none = (v == Py_None);
          Py_DECREF(v);
          if (aff_none) {
            v = PyObject_GetAttr(pod, tol_name);
            if (v == nullptr) goto fail;
            truthy = PyObject_IsTrue(v);
            Py_DECREF(v);
            if (truthy < 0) goto fail;
            if (!truthy) {
              v = PyObject_GetAttr(pod, g_volumes_name);
              if (v == nullptr) goto fail;
              truthy = PyObject_IsTrue(v);
              Py_DECREF(v);
              if (truthy < 0) goto fail;
              if (!truthy) {
                PyObject* conts = PyObject_GetAttr(pod, cont_name);
                if (conts == nullptr) goto fail;
                if (PyList_Check(conts) && PyList_GET_SIZE(conts) == 1) {
                  PyObject* ports =
                      PyObject_GetAttr(PyList_GET_ITEM(conts, 0), ports_name);
                  if (ports == nullptr) {
                    Py_DECREF(conts);
                    goto fail;
                  }
                  truthy = PyObject_IsTrue(ports);
                  Py_DECREF(ports);
                  if (truthy < 0) {
                    Py_DECREF(conts);
                    goto fail;
                  }
                  key.plain = truthy ? 0 : 1;
                }
                Py_DECREF(conts);
              }
            }
          }
        }
        keys.push_back(key);
      }
    }
    // (priority desc, creation_timestamp, uid) — stable, uid last
    std::stable_sort(keys.begin(), keys.end(),
                     [](const SortKey& a, const SortKey& b) {
                       if (a.prio != b.prio) return a.prio > b.prio;
                       if (a.ts != b.ts) return a.ts < b.ts;
                       return PyUnicode_Compare(a.uid, b.uid) < 0;
                     });
    {
      Py_ssize_t m = (Py_ssize_t)keys.size();
      PyObject* tl = PyList_New(m);
      PyObject* flags = PyBytes_FromStringAndSize(nullptr, m);
      if (tl == nullptr || flags == nullptr) {
        Py_XDECREF(tl);
        Py_XDECREF(flags);
        goto fail;
      }
      char* fb = PyBytes_AS_STRING(flags);
      for (Py_ssize_t i = 0; i < m; i++) {
        Py_INCREF(keys[i].task);
        PyList_SET_ITEM(tl, i, keys[i].task);
        fb[i] = keys[i].plain;
      }
      PyObject* pair = PyTuple_Pack(2, tl, flags);
      Py_DECREF(tl);
      Py_DECREF(flags);
      if (pair == nullptr) goto fail;
      PyList_SET_ITEM(out, ji, pair);
    }
  }
  return out;
fail:
  Py_DECREF(out);
  return nullptr;
}

/* ---- encode-side extractors ---------------------------------------------- */

struct F32F64Buf {
  Py_buffer view{};
  bool is_f64 = false;
  bool ok = false;
};

/* Acquire a writable C-contiguous float32/float64 buffer. */
bool get_float_buf(PyObject* obj, F32F64Buf* b, int want_ndim) {
  if (PyObject_GetBuffer(obj, &b->view, PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE |
                                            PyBUF_FORMAT) < 0)
    return false;
  b->ok = true;
  const char* f = b->view.format;
  if (b->view.ndim != want_ndim || f == nullptr ||
      !((f[0] == 'f' || f[0] == 'd') && f[1] == '\0')) {
    PyErr_SetString(PyExc_TypeError,
                    "expected a C-contiguous float32/float64 buffer");
    return false;
  }
  b->is_f64 = b->view.format[0] == 'd';
  return true;
}

inline void put_f(const F32F64Buf& b, Py_ssize_t flat_ix, double v) {
  if (b.is_f64)
    ((double*)b.view.buf)[flat_ix] = v;
  else
    ((float*)b.view.buf)[flat_ix] = (float)v;
}

/* extract_task_columns(tasks, job_idx, req, res, job_out, has_sc,
 *                      res_has_sc)
 *
 * The scalar-less encoder fast path (ops/encode.py): for task i write
 *   req[i,0:2]  = init_resreq.{milli_cpu,memory}
 *   res[i,0:2]  = resreq.{milli_cpu,memory}
 *   job_out[i]  = job_idx[task.job]          (int32)
 *   has_sc[i]   = bool(init_resreq.scalars)  (uint8/bool)
 *   res_has_sc[i] = bool(resreq.scalars)
 * req/res are the [T,R] padded arrays (T >= len(tasks)); only the first
 * len(tasks) rows and two columns are touched. */
PyObject* extract_task_columns(PyObject*, PyObject* args) {
  PyObject *tasks, *job_idx, *req_o, *res_o, *job_o, *hs_o, *rhs_o;
  if (!PyArg_ParseTuple(args, "O!O!OOOOO", &PyList_Type, &tasks, &PyDict_Type,
                        &job_idx, &req_o, &res_o, &job_o, &hs_o, &rhs_o))
    return nullptr;

  F32F64Buf req, res;
  Py_buffer job_b{}, hs_b{}, rhs_b{};
  bool job_ok = false, hs_ok = false, rhs_ok = false;
  PyObject* ret = nullptr;
  Py_ssize_t n = PyList_GET_SIZE(tasks);

  if (!get_float_buf(req_o, &req, 2) || !get_float_buf(res_o, &res, 2))
    goto done;
  if (PyObject_GetBuffer(job_o, &job_b,
                         PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT) <
      0)
    goto done;
  job_ok = true;
  if (PyObject_GetBuffer(hs_o, &hs_b,
                         PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT) <
      0)
    goto done;
  hs_ok = true;
  if (PyObject_GetBuffer(rhs_o, &rhs_b,
                         PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT) <
      0)
    goto done;
  rhs_ok = true;

  if (job_b.itemsize != 4 || hs_b.itemsize != 1 || rhs_b.itemsize != 1 ||
      req.view.shape[0] < n || res.view.shape[0] < n || job_b.len < 4 * n ||
      hs_b.len < n || rhs_b.len < n || req.view.shape[1] < 2 ||
      res.view.shape[1] < 2) {
    PyErr_SetString(PyExc_ValueError, "output buffer shape/dtype mismatch");
    goto done;
  }

  {
    Py_ssize_t req_R = req.view.shape[1], res_R = res.view.shape[1];
    int32_t* job_out = (int32_t*)job_b.buf;
    char* hs = (char*)hs_b.buf;
    char* rhs = (char*)rhs_b.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* task = PyList_GET_ITEM(tasks, i);
      PyTypeObject* tp = Py_TYPE(task);
      if (g_task_slots.type != tp && resolve_slots(tp, &g_task_slots) < 0)
        goto done;
      const SlotCache& sc = g_task_slots;
      PyObject* rr = get_slot(task, sc.off[kSlotResreq]);
      PyObject* ir = get_slot(task, sc.off[kSlotInitResreq]);
      if (rr == nullptr || ir == nullptr) {
        PyErr_SetString(PyExc_AttributeError, "task resource slot unset");
        goto done;
      }
      PyTypeObject* rtp = Py_TYPE(rr);
      if (g_res_slots.type != rtp && resolve_res_slots(rtp, &g_res_slots) < 0)
        goto done;
      if (Py_TYPE(ir) != g_res_slots.type) {
        PyErr_SetString(PyExc_TypeError, "mixed Resource types");
        goto done;
      }
      const ResSlotCache& rc = g_res_slots;
      double cpu, mem;
      if (res_cpu_mem(ir, rc, &cpu, &mem) < 0) goto done;
      put_f(req, i * req_R + 0, cpu);
      put_f(req, i * req_R + 1, mem);
      if (res_cpu_mem(rr, rc, &cpu, &mem) < 0) goto done;
      put_f(res, i * res_R + 0, cpu);
      put_f(res, i * res_R + 1, mem);
      PyObject* jid = get_slot(task, sc.off[kSlotJob]);
      PyObject* jrow = jid ? PyDict_GetItemWithError(job_idx, jid) : nullptr;
      if (jrow == nullptr) {
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_KeyError, "task.job not in job_idx");
        goto done;
      }
      long j = PyLong_AsLong(jrow);
      if (j == -1 && PyErr_Occurred()) goto done;
      job_out[i] = (int32_t)j;
      PyObject* ir_sc = get_slot(ir, rc.off[2]);
      PyObject* rr_sc = get_slot(rr, rc.off[2]);
      if (ir_sc == nullptr || rr_sc == nullptr) {
        PyErr_SetString(PyExc_AttributeError, "Resource scalars slot unset");
        goto done;
      }
      int t1 = PyObject_IsTrue(ir_sc);
      int t2 = PyObject_IsTrue(rr_sc);
      if (t1 < 0 || t2 < 0) goto done;
      hs[i] = (char)t1;
      rhs[i] = (char)t2;
    }
  }
  ret = Py_NewRef(Py_None);

done:
  if (req.ok) PyBuffer_Release(&req.view);
  if (res.ok) PyBuffer_Release(&res.view);
  if (job_ok) PyBuffer_Release(&job_b);
  if (hs_ok) PyBuffer_Release(&hs_b);
  if (rhs_ok) PyBuffer_Release(&rhs_b);
  return ret;
}

/* extract_node_columns(nodes, names, out) — the node-side scalar-less
 * fast path: nodes is list[NodeInfo], names a tuple of attribute names
 * (e.g. ("idle","releasing","used","allocatable")), out a writable
 * [len(names), N, R] float buffer; writes out[a, i, 0:2] =
 * node.<names[a]>.{milli_cpu,memory}. */
PyObject* extract_node_columns(PyObject*, PyObject* args) {
  PyObject *nodes, *names, *out_o;
  if (!PyArg_ParseTuple(args, "O!O!O", &PyList_Type, &nodes, &PyTuple_Type,
                        &names, &out_o))
    return nullptr;
  F32F64Buf out;
  PyObject* ret = nullptr;
  Py_ssize_t n = PyList_GET_SIZE(nodes);
  Py_ssize_t na = PyTuple_GET_SIZE(names);
  if (PyObject_GetBuffer(out_o, &out.view, PyBUF_C_CONTIGUOUS |
                                               PyBUF_WRITABLE | PyBUF_FORMAT) <
      0)
    return nullptr;
  out.ok = true;
  {
    const char* f = out.view.format;
    if (out.view.ndim != 3 || f == nullptr ||
        !((f[0] == 'f' || f[0] == 'd') && f[1] == '\0') ||
        out.view.shape[0] != na || out.view.shape[1] < n ||
        out.view.shape[2] < 2) {
      PyErr_SetString(PyExc_ValueError, "output buffer shape/dtype mismatch");
      goto done;
    }
    out.is_f64 = f[0] == 'd';
    Py_ssize_t N = out.view.shape[1], R = out.view.shape[2];
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* node = PyList_GET_ITEM(nodes, i);
      for (Py_ssize_t a = 0; a < na; a++) {
        PyObject* res = PyObject_GetAttr(node, PyTuple_GET_ITEM(names, a));
        if (res == nullptr) goto done;
        PyTypeObject* rtp = Py_TYPE(res);
        if (g_res_slots.type != rtp &&
            resolve_res_slots(rtp, &g_res_slots) < 0) {
          Py_DECREF(res);
          goto done;
        }
        double cpu, mem;
        int rc = res_cpu_mem(res, g_res_slots, &cpu, &mem);
        Py_DECREF(res);
        if (rc < 0) goto done;
        put_f(out, (a * N + i) * R + 0, cpu);
        put_f(out, (a * N + i) * R + 1, mem);
      }
    }
  }
  ret = Py_NewRef(Py_None);
done:
  PyBuffer_Release(&out.view);
  return ret;
}

/* ---- bulk_set_slot ------------------------------------------------------- */

/* bulk_set_slot(objs, name, value): obj.<name> = value for every obj —
 * the gang-dispatch status flip (finish()) without 100k interpreter
 * stores. Objects must share one __slots__ type. */
PyObject* bulk_set_slot(PyObject*, PyObject* args) {
  PyObject *objs, *name, *value;
  if (!PyArg_ParseTuple(args, "O!UO", &PyList_Type, &objs, &name, &value))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(objs);
  if (n == 0) Py_RETURN_NONE;
  PyTypeObject* tp = Py_TYPE(PyList_GET_ITEM(objs, 0));
  PyObject* descr = PyObject_GetAttr((PyObject*)tp, name);
  if (descr == nullptr) return nullptr;
  if (Py_TYPE(descr) != &PyMemberDescr_Type) {
    Py_DECREF(descr);
    PyErr_Format(PyExc_TypeError, "%s.%U is not a slot member", tp->tp_name,
                 name);
    return nullptr;
  }
  Py_ssize_t off = ((PyMemberDescrObject*)descr)->d_member->offset;
  Py_DECREF(descr);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* o = PyList_GET_ITEM(objs, i);
    if (Py_TYPE(o) != tp) {
      PyErr_SetString(PyExc_TypeError, "mixed object types in batch");
      return nullptr;
    }
    set_slot(o, off, value);
  }
  Py_RETURN_NONE;
}

/* ---- bulk_dispatch ------------------------------------------------------- */

/* bulk_dispatch(jobs, mask, ALLOCATED, BINDING) -> list[task]
 *
 * The gang dispatch barrier's pure-bulk half (xla_allocate finish()):
 * for each job whose mask byte is 1, move task_status_index[ALLOCATED]
 * wholesale under [BINDING] and append the moved tasks (index insertion
 * order) to the returned flat list. When no BINDING bucket exists the
 * dict itself moves — one setitem+delitem per GANG, not per task. The
 * caller owns the readiness/purity decisions baked into mask and flips
 * the returned tasks' status afterwards (bulk_set_slot). */
PyObject* bulk_dispatch(PyObject*, PyObject* args) {
  PyObject *jobs, *mask_b, *alloc_key, *binding_key;
  if (!PyArg_ParseTuple(args, "O!SOO", &PyList_Type, &jobs, &mask_b,
                        &alloc_key, &binding_key))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(jobs);
  if (PyBytes_GET_SIZE(mask_b) != n) {
    PyErr_SetString(PyExc_ValueError, "mask length mismatch");
    return nullptr;
  }
  const char* mask = PyBytes_AS_STRING(mask_b);
  /* Mutation-free prepass: every masked job must expose a dict status
   * index BEFORE any bucket moves — the caller's Python fallback
   * re-walks all jobs assuming nothing was dispatched yet; a mid-loop
   * failure after partial moves would strand those gangs unbound. */
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!mask[i]) continue;
    PyObject* sidx = PyObject_GetAttr(PyList_GET_ITEM(jobs, i), g_idx_name);
    if (sidx == nullptr) return nullptr;
    int ok = PyDict_Check(sidx);
    Py_DECREF(sidx);
    if (!ok) {
      PyErr_SetString(PyExc_TypeError, "task_status_index is not a dict");
      return nullptr;
    }
  }
  PyObject* out = PyList_New(0);
  if (out == nullptr) return nullptr;
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!mask[i]) continue;
    PyObject* sidx = PyObject_GetAttr(PyList_GET_ITEM(jobs, i), g_idx_name);
    if (sidx == nullptr) goto fail;
    if (!PyDict_Check(sidx)) {
      Py_DECREF(sidx);
      PyErr_SetString(PyExc_TypeError, "task_status_index is not a dict");
      goto fail;
    }
    {
      PyObject* allocated = PyDict_GetItemWithError(sidx, alloc_key);
      if (allocated == nullptr) {
        Py_DECREF(sidx);
        if (PyErr_Occurred()) goto fail;
        continue;  // nothing Allocated on this job
      }
      if (!PyDict_Check(allocated) || PyDict_GET_SIZE(allocated) == 0) {
        Py_DECREF(sidx);
        continue;
      }
      Py_ssize_t pos = 0;
      PyObject *k, *task;
      while (PyDict_Next(allocated, &pos, &k, &task)) {
        if (PyList_Append(out, task) < 0) {
          Py_DECREF(sidx);
          goto fail;
        }
      }
      PyObject* binding = PyDict_GetItemWithError(sidx, binding_key);
      int rc;
      if (binding == nullptr) {
        if (PyErr_Occurred()) {
          Py_DECREF(sidx);
          goto fail;
        }
        rc = PyDict_SetItem(sidx, binding_key, allocated);  // dict moves
      } else {
        rc = PyDict_Merge(binding, allocated, 1);
      }
      if (rc == 0) rc = PyDict_DelItem(sidx, alloc_key);
      Py_DECREF(sidx);
      if (rc < 0) goto fail;
    }
  }
  return out;
fail:
  Py_DECREF(out);
  return nullptr;
}

/* ---- finish_columns ------------------------------------------------------ */

/* finish_columns(tasks, row_of, task_keys, new_status) ->
 *     (rows_bytes int64, created_bytes f64, keys list, hostnames list)
 *
 * One C pass over the dispatch list building everything finish() needs:
 * per task its encoder row (-1 if this encode never saw it), its pod
 * creation timestamp, its "ns/name" bind key (borrowed from task_keys
 * when encoded, built fresh otherwise) and its node_name — replacing
 * four separate 200k-iteration Python comprehensions on the replay's
 * critical path. When ``new_status`` is not None every task's status
 * slot is set to it in the same pass (the gang-dispatch flip; nothing
 * observes status between the dispatch loop and the bind). */
PyObject* finish_columns(PyObject*, PyObject* args) {
  PyObject *tasks, *row_of, *task_keys, *new_status;
  if (!PyArg_ParseTuple(args, "O!O!O!O", &PyList_Type, &tasks, &PyDict_Type,
                        &row_of, &PyList_Type, &task_keys, &new_status))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(tasks);
  Py_ssize_t n_keys = PyList_GET_SIZE(task_keys);
  PyObject *rows_b = nullptr, *created_b = nullptr, *keys = nullptr,
           *hostnames = nullptr, *out = nullptr;
  rows_b = PyBytes_FromStringAndSize(nullptr, n * (Py_ssize_t)sizeof(int64_t));
  created_b = PyBytes_FromStringAndSize(nullptr, n * (Py_ssize_t)sizeof(double));
  keys = PyList_New(n);
  hostnames = PyList_New(n);
  if (!rows_b || !created_b || !keys || !hostnames) goto fail;
  {
    int64_t* rows = (int64_t*)PyBytes_AS_STRING(rows_b);
    double* created = (double*)PyBytes_AS_STRING(created_b);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* task = PyList_GET_ITEM(tasks, i);
      PyTypeObject* tp = Py_TYPE(task);
      if (g_task_slots.type != tp && resolve_slots(tp, &g_task_slots) < 0)
        goto fail;
      const SlotCache& sc = g_task_slots;
      PyObject* uid = get_slot(task, sc.off[kUid]);
      if (uid == nullptr) {
        PyErr_SetString(PyExc_AttributeError, "task.uid slot unset");
        goto fail;
      }
      PyObject* row_o = PyDict_GetItemWithError(row_of, uid);
      if (row_o == nullptr && PyErr_Occurred()) goto fail;
      Py_ssize_t row = -1;
      if (row_o != nullptr) {
        row = PyLong_AsSsize_t(row_o);
        if (row == -1 && PyErr_Occurred()) goto fail;
      }
      rows[i] = (int64_t)row;
      PyObject* pod = get_slot(task, sc.off[kPod]);
      if (pod == nullptr) {
        PyErr_SetString(PyExc_AttributeError, "task.pod slot unset");
        goto fail;
      }
      PyObject* meta = PyObject_GetAttr(pod, g_meta_name);
      PyObject* ts = meta ? PyObject_GetAttr(meta, g_ts_name) : nullptr;
      Py_XDECREF(meta);
      if (ts == nullptr) goto fail;
      created[i] = PyFloat_AsDouble(ts);
      Py_DECREF(ts);
      if (created[i] == -1.0 && PyErr_Occurred()) goto fail;
      PyObject* key;
      if (row >= 0 && row < n_keys) {
        key = Py_NewRef(PyList_GET_ITEM(task_keys, row));
      } else {
        PyObject* ns = get_slot(task, sc.off[kNamespace]);
        PyObject* nm = get_slot(task, sc.off[kName]);
        if (ns == nullptr || nm == nullptr) {
          PyErr_SetString(PyExc_AttributeError, "task ns/name slot unset");
          goto fail;
        }
        key = PyUnicode_FromFormat("%U/%U", ns, nm);
        if (key == nullptr) goto fail;
      }
      PyList_SET_ITEM(keys, i, key);
      PyObject* node_name = get_slot(task, sc.off[kNodeName]);
      if (node_name == nullptr) {
        PyErr_SetString(PyExc_AttributeError, "task.node_name slot unset");
        goto fail;
      }
      PyList_SET_ITEM(hostnames, i, Py_NewRef(node_name));
      if (new_status != Py_None) set_slot(task, sc.off[kStatus], new_status);
    }
  }
  out = PyTuple_Pack(4, rows_b, created_b, keys, hostnames);
fail:
  Py_XDECREF(rows_b);
  Py_XDECREF(created_b);
  Py_XDECREF(keys);
  Py_XDECREF(hostnames);
  return out;
}

/* ---- bulk_res_axpy ------------------------------------------------------- */

/* bulk_res_axpy(res_objs, deltas, sign): for each Resource object,
 *   milli_cpu += sign * deltas[i,0];  memory += sign * deltas[i,1]
 * (deltas a C-contiguous [n,>=2] float64 buffer). The scalar-map
 * dimensions keep their Go nil-map semantics on the Python side — this
 * covers only the two dense dimensions every node/job touches. */
PyObject* bulk_res_axpy(PyObject*, PyObject* args) {
  PyObject *objs, *buf_o;
  int sign;
  if (!PyArg_ParseTuple(args, "O!Oi", &PyList_Type, &objs, &buf_o, &sign))
    return nullptr;
  Py_buffer view;
  if (PyObject_GetBuffer(buf_o, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
    return nullptr;
  PyObject* ret = nullptr;
  {
    Py_ssize_t n = PyList_GET_SIZE(objs);
    if (view.ndim != 2 || view.shape[0] < n || view.shape[1] < 2 ||
        view.itemsize != 8 || view.format == nullptr ||
        view.format[0] != 'd') {
      PyErr_SetString(PyExc_TypeError, "deltas must be [n,>=2] float64");
      goto done;
    }
    Py_ssize_t R = view.shape[1];
    const double* d = (const double*)view.buf;
    /* Mutation-free prepass: one homogeneous slot type, both dense
     * slots set on every element — a heterogeneous Resource variant
     * raises BEFORE any pool is touched. The caller's per-pool Python
     * fallback relies on failures being pre-mutation (a half-applied
     * delta would double-count under the fallback). */
    if (n > 0) {
      PyTypeObject* rtp = Py_TYPE(PyList_GET_ITEM(objs, 0));
      if (g_res_slots.type != rtp && resolve_res_slots(rtp, &g_res_slots) < 0)
        goto done;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* res = PyList_GET_ITEM(objs, i);
      if (Py_TYPE(res) != g_res_slots.type) {
        PyErr_SetString(PyExc_TypeError, "mixed Resource types in batch");
        goto done;
      }
      double cpu, mem;  // also proves float-convertibility pre-mutation
      if (res_cpu_mem(res, g_res_slots, &cpu, &mem) < 0) goto done;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* res = PyList_GET_ITEM(objs, i);
      const ResSlotCache& rc = g_res_slots;
      double cpu, mem;
      if (res_cpu_mem(res, rc, &cpu, &mem) < 0) goto done;
      PyObject* nc = PyFloat_FromDouble(cpu + sign * d[i * R + 0]);
      if (nc == nullptr) goto done;
      set_slot(res, rc.off[0], nc);
      Py_DECREF(nc);
      PyObject* nm = PyFloat_FromDouble(mem + sign * d[i * R + 1]);
      if (nm == nullptr) goto done;
      set_slot(res, rc.off[1], nm);
      Py_DECREF(nm);
    }
    ret = Py_NewRef(Py_None);
  }
done:
  PyBuffer_Release(&view);
  return ret;
}

/* ---- class_dedup --------------------------------------------------------- */

/* Shared hash pass over T rows of row_bytes each at base (C-contiguous
 * concatenated key matrix): classes numbered in FIRST-OCCURRENCE order.
 * Returns PyTuple (first int64 bytes, inverse int32 bytes) or nullptr. */
static PyObject* dedup_pass(const char* base, Py_ssize_t T,
                            Py_ssize_t row_bytes) {
  PyObject *first_b = nullptr, *inv_b = nullptr, *out = nullptr;
  inv_b = PyBytes_FromStringAndSize(nullptr, T * (Py_ssize_t)sizeof(int32_t));
  if (inv_b == nullptr) return nullptr;
  int32_t* inv = (int32_t*)PyBytes_AS_STRING(inv_b);
  std::vector<int64_t> first;
  first.reserve(256);
  {
    std::unordered_map<std::string_view, int32_t> seen;
    seen.reserve((size_t)T * 2);
    for (Py_ssize_t i = 0; i < T; i++) {
      std::string_view row(base + i * row_bytes, (size_t)row_bytes);
      auto [it, inserted] = seen.emplace(row, (int32_t)first.size());
      if (inserted) first.push_back((int64_t)i);
      inv[i] = it->second;
    }
  }
  first_b = PyBytes_FromStringAndSize((const char*)first.data(),
                                      first.size() * sizeof(int64_t));
  if (first_b != nullptr) out = PyTuple_Pack(2, first_b, inv_b);
  Py_XDECREF(first_b);
  Py_DECREF(inv_b);
  return out;
}

/* class_dedup(keys) -> (first_bytes, inverse_bytes)
 *
 * Row-dedup of a C-contiguous 2-D buffer (any fixed-size dtype): one
 * O(T) hash pass over row byte-spans, classes numbered in
 * FIRST-OCCURRENCE order. Replaces np.unique's O(T log T) void-sort in
 * the encoder's task-class dedup (ops/pallas_solve._class_inverse) —
 * the difference is ~0.3 s at 400k tasks. Returns two bytes objects the
 * caller np.frombuffer's: first (int64 row index per class) and inverse
 * (int32 class id per row). Any consistent (first, inverse) pairing is
 * valid for the kernel packing; class order itself carries no meaning.
 *
 * Arbitrary-width keys: a tuple/list of 2-D buffers sharing shape[0]
 * dedups over their per-row byte concatenation — the class-solve node
 * key spans several dtype-mixed slabs (ops/class_solve.dedup_rows), and
 * concatenating them byte-wise here (one scratch fill, no numpy
 * round-trip) keeps the multi-slab form one O(N * key_bytes) pass. */
PyObject* class_dedup(PyObject*, PyObject* arg) {
  if (PyTuple_Check(arg) || PyList_Check(arg)) {
    Py_ssize_t nbuf = PySequence_Fast_GET_SIZE(arg);
    if (nbuf == 0) {
      PyErr_SetString(PyExc_TypeError,
                      "class_dedup needs at least one 2-D buffer");
      return nullptr;
    }
    std::vector<Py_buffer> views((size_t)nbuf);
    Py_ssize_t got = 0;
    PyObject* out = nullptr;
    Py_ssize_t T = 0, row_bytes = 0;
    for (; got < nbuf; got++) {
      PyObject* item = PySequence_Fast_GET_ITEM(arg, got);
      if (PyObject_GetBuffer(item, &views[got],
                             PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
        goto multi_done;
      if (views[got].ndim != 2) {
        PyErr_SetString(PyExc_TypeError,
                        "class_dedup needs 2-D buffers in the key tuple");
        got++;
        goto multi_done;
      }
      if (got == 0) {
        T = views[0].shape[0];
      } else if (views[got].shape[0] != T) {
        PyErr_SetString(PyExc_ValueError,
                        "class_dedup key buffers disagree on row count");
        got++;
        goto multi_done;
      }
      row_bytes += views[got].shape[1] * views[got].itemsize;
    }
    {
      /* per-row byte concat into one scratch matrix, then the same pass */
      std::vector<char> scratch((size_t)(T * row_bytes));
      Py_ssize_t col = 0;
      for (Py_ssize_t b = 0; b < nbuf; b++) {
        Py_ssize_t seg = views[b].shape[1] * views[b].itemsize;
        const char* src = (const char*)views[b].buf;
        char* dst = scratch.data() + col;
        for (Py_ssize_t i = 0; i < T; i++)
          std::memcpy(dst + i * row_bytes, src + i * seg, (size_t)seg);
        col += seg;
      }
      out = dedup_pass(scratch.data(), T, row_bytes);
    }
  multi_done:
    for (Py_ssize_t b = 0; b < got; b++) PyBuffer_Release(&views[b]);
    return out;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT) < 0)
    return nullptr;
  PyObject* out = nullptr;
  if (view.ndim != 2) {
    PyErr_SetString(PyExc_TypeError, "class_dedup needs a 2-D buffer");
  } else {
    out = dedup_pass((const char*)view.buf, view.shape[0],
                     view.shape[1] * view.itemsize);
  }
  PyBuffer_Release(&view);
  return out;
}

/* ---- module -------------------------------------------------------------- */

PyMethodDef methods[] = {
    {"bulk_assign", bulk_assign, METH_VARARGS,
     "Apply kernel assignment events to session TaskInfo/node state."},
    {"bulk_set_slot", bulk_set_slot, METH_VARARGS,
     "Set one __slots__ attribute on every object in a list."},
    {"collect_pending", collect_pending, METH_VARARGS,
     "Per-job pending extraction: filter empties, pop-order sort, "
     "plain-task classification."},
    {"extract_task_columns", extract_task_columns, METH_VARARGS,
     "Fill SoA request/limit/job/scalar-flag columns from TaskInfos."},
    {"extract_node_columns", extract_node_columns, METH_VARARGS,
     "Fill [A,N,R] cpu/mem columns from NodeInfo resource attributes."},
    {"class_dedup", class_dedup, METH_O,
     "Row-dedup a 2-D buffer, or a tuple/list of 2-D buffers sharing "
     "shape[0] (byte-concatenated per row): (first int64 bytes, "
     "inverse int32 bytes)."},
    {"bulk_dispatch", bulk_dispatch, METH_VARARGS,
     "Move masked jobs' ALLOCATED buckets under BINDING; return the tasks."},
    {"finish_columns", finish_columns, METH_VARARGS,
     "Rows/created/keys/pairs for the dispatch list in one pass."},
    {"bulk_res_axpy", bulk_res_axpy, METH_VARARGS,
     "Resource.milli_cpu/memory += sign*deltas[i] over a list."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hotloops",
    "Native bulk session-mutation loops (see module docstring in source).",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__hotloops(void) {
  g_volumes_name = PyUnicode_InternFromString("volumes");
  g_idx_name = PyUnicode_InternFromString("task_status_index");
  g_meta_name = PyUnicode_InternFromString("metadata");
  g_ts_name = PyUnicode_InternFromString("creation_timestamp");
  g_sel_name = PyUnicode_InternFromString("node_selector");
  g_aff_name = PyUnicode_InternFromString("affinity");
  g_tol_name = PyUnicode_InternFromString("tolerations");
  g_cont_name = PyUnicode_InternFromString("containers");
  g_ports_name = PyUnicode_InternFromString("ports");
  if (!g_volumes_name || !g_idx_name || !g_meta_name || !g_ts_name ||
      !g_sel_name || !g_aff_name || !g_tol_name || !g_cont_name ||
      !g_ports_name)
    return nullptr;
  return PyModule_Create(&moduledef);
}
