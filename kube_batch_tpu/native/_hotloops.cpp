/* Native hot loops for the replay path (CPython extension).
 *
 * The reference scheduler's runtime is compiled Go end to end; here the
 * TPU solve is compiled XLA/Mosaic, and this module compiles the one
 * remaining interpreter-bound stretch: the bulk session-mutation loop
 * that replays kernel assignments into Python session objects
 * (actions/xla_allocate._Replayer.apply_upto — the net state mutations
 * of ssn.allocate/pipeline, session.go:198-296, at 50k-100k events per
 * cycle).
 *
 * Approach: TaskInfo (api/job_info.py) is a __slots__ class, so its
 * attributes live at fixed byte offsets published by the class's
 * member descriptors (PyMemberDescrObject.d_member->offset). We cache
 * the offsets per type and do the per-event work — status flip,
 * node_name set, residency clone (clone_for_residency parity: shares
 * Resource objects, copies every slot), node task-map insert, status-
 * index dict build — as direct pointer stores + PyDict_SetItem calls,
 * with no interpreter frames. Everything is plain public CPython API
 * (descrobject.h, PyType_GenericAlloc via tp_alloc); a type without
 * the expected slots raises and the caller falls back to the pure-
 * Python loop.
 *
 * Build: kube_batch_tpu/native/build.py (g++ -O2 -shared -fPIC);
 * loaded lazily by kube_batch_tpu/native/__init__.py with a pure-
 * Python fallback when the toolchain is absent (KBT_NATIVE=0 disables).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <structmember.h>

#include <cstring>

namespace {

/* ---- slot offset cache --------------------------------------------------- */

constexpr int kNumSlots = 11;
/* Order matches TaskInfo.__slots__ (api/job_info.py); the clone copies
 * all of them, the surgery writes a subset. */
const char* const kSlotNames[kNumSlots] = {
    "uid",     "job",    "name",     "namespace", "resreq", "init_resreq",
    "node_name", "status", "priority", "volume_ready", "pod",
};
constexpr int kUid = 0;
constexpr int kNodeName = 6;
constexpr int kStatus = 7;
constexpr int kVolumeReady = 9;
constexpr int kPod = 10;

struct SlotCache {
  PyTypeObject* type = nullptr;  // borrowed; identity-checked per call
  Py_ssize_t off[kNumSlots];
};

SlotCache g_task_slots;

/* Resolve the byte offset of each __slots__ member descriptor on `tp`.
 * Returns 0 on success, -1 (with a Python error set) when any name is
 * not a plain member slot — the caller then uses the Python path. */
int resolve_slots(PyTypeObject* tp, SlotCache* cache) {
  for (int i = 0; i < kNumSlots; i++) {
    PyObject* descr = PyObject_GetAttrString((PyObject*)tp, kSlotNames[i]);
    if (descr == nullptr) return -1;
    if (Py_TYPE(descr) != &PyMemberDescr_Type) {
      Py_DECREF(descr);
      PyErr_Format(PyExc_TypeError, "%s.%s is not a slot member",
                   tp->tp_name, kSlotNames[i]);
      return -1;
    }
    cache->off[i] = ((PyMemberDescrObject*)descr)->d_member->offset;
    Py_DECREF(descr);
  }
  cache->type = tp;
  return 0;
}

inline PyObject* get_slot(PyObject* o, Py_ssize_t off) {
  return *(PyObject**)((char*)o + off);  // borrowed
}

inline void set_slot(PyObject* o, Py_ssize_t off, PyObject* v) {
  PyObject** p = (PyObject**)((char*)o + off);
  Py_INCREF(v);
  PyObject* old = *p;
  *p = v;
  Py_XDECREF(old);
}

/* clone_for_residency parity: new instance of the same type, every slot
 * shared by reference (Resource objects included — they are never
 * mutated on a TaskInfo after construction; see job_info.py docstring).
 *
 * The clone is removed from cycle-GC tracking: nothing a TaskInfo
 * references (strings, Resource, Pod, TaskStatus) can reach the clone
 * back — the clone lives only in NodeInfo.tasks, never in the job
 * indexes — so it cannot participate in a cycle and plain refcounting
 * frees it. Untracking keeps 50k-100k fresh clones out of every gen-0
 * collection during the replay. */
PyObject* clone_slots(PyObject* task, const SlotCache& sc) {
  PyTypeObject* tp = Py_TYPE(task);
  PyObject* cl = tp->tp_alloc(tp, 0);
  if (cl == nullptr) return nullptr;
  for (int i = 0; i < kNumSlots; i++) {
    PyObject* v = get_slot(task, sc.off[i]);
    Py_XINCREF(v);
    *(PyObject**)((char*)cl + sc.off[i]) = v;
  }
  if (PyObject_GC_IsTracked(cl)) PyObject_GC_UnTrack(cl);
  return cl;
}

/* ---- bulk_assign --------------------------------------------------------- */

PyObject* g_volumes_name = nullptr;  // interned "volumes"

/* bulk_assign(tasks, tkeys, node_tasks, node_names, rows, nrows,
 *             allocs, counts, ALLOCATED, PIPELINED)
 *
 *   tasks      list[TaskInfo]  row-indexed (encoder order)
 *   tkeys      list[str]       row-indexed "ns/name" node-map keys
 *   node_tasks list[dict]      per node row: NodeInfo.tasks
 *   node_names list[str]       per node row: node name
 *   rows       list[int]       event rows, kernel order grouped per job
 *   nrows      list[int]       event node rows (parallel to rows)
 *   allocs     bytes           1 = Allocated, 0 = Pipelined (parallel)
 *   counts     list[int]       events per job segment (sum = len(rows))
 *   ALLOCATED / PIPELINED      TaskStatus members
 *
 * Per event, exactly the Python loop's mutations in its order:
 *   volume_ready=True (Allocated, volume-less), status flip, uid->task
 *   into the segment's alloc/pipe dict, node_name set, residency clone
 *   into node_tasks[nrow][tkeys[row]].
 * Returns list[(alloc_d, pipe_d)] per segment.
 *
 * A task with pod.volumes on an Allocated event needs the volume
 * binder (host-side assume) — detected in a mutation-free prepass and
 * raised as ValueError so the caller falls back cleanly. */
PyObject* bulk_assign(PyObject*, PyObject* args) {
  PyObject *tasks, *tkeys, *node_tasks, *node_names, *rows, *nrows;
  PyObject *allocs, *counts, *st_alloc, *st_pipe;
  if (!PyArg_ParseTuple(args, "O!O!O!O!O!O!SO!OO", &PyList_Type, &tasks,
                        &PyList_Type, &tkeys, &PyList_Type, &node_tasks,
                        &PyList_Type, &node_names, &PyList_Type, &rows,
                        &PyList_Type, &nrows, &allocs, &PyList_Type, &counts,
                        &st_alloc, &st_pipe))
    return nullptr;

  Py_ssize_t n = PyList_GET_SIZE(rows);
  if (PyList_GET_SIZE(nrows) != n || PyBytes_GET_SIZE(allocs) != n) {
    PyErr_SetString(PyExc_ValueError, "rows/nrows/allocs length mismatch");
    return nullptr;
  }
  const char* is_alloc = PyBytes_AS_STRING(allocs);
  Py_ssize_t n_tasks = PyList_GET_SIZE(tasks);
  Py_ssize_t n_nodes = PyList_GET_SIZE(node_tasks);
  if (PyList_GET_SIZE(tkeys) != n_tasks ||
      PyList_GET_SIZE(node_names) != n_nodes) {
    PyErr_SetString(PyExc_ValueError, "tkeys/node_names length mismatch");
    return nullptr;
  }

  /* Decode row/nrow indices once, bounds-checked. */
  Py_ssize_t* row_ix = (Py_ssize_t*)PyMem_Malloc(2 * n * sizeof(Py_ssize_t));
  if (row_ix == nullptr && n > 0) return PyErr_NoMemory();
  Py_ssize_t* nrow_ix = row_ix + n;
  for (Py_ssize_t i = 0; i < n; i++) {
    Py_ssize_t r = PyLong_AsSsize_t(PyList_GET_ITEM(rows, i));
    Py_ssize_t nr = PyLong_AsSsize_t(PyList_GET_ITEM(nrows, i));
    if ((r == -1 || nr == -1) && PyErr_Occurred()) goto fail_ix;
    if (r < 0 || r >= n_tasks || nr < 0 || nr >= n_nodes) {
      PyErr_SetString(PyExc_IndexError, "row index out of range");
      goto fail_ix;
    }
    row_ix[i] = r;
    nrow_ix[i] = nr;
  }

  {
    /* Slot offsets for this TaskInfo type (cached across calls). */
    if (n > 0) {
      PyTypeObject* tp = Py_TYPE(PyList_GET_ITEM(tasks, row_ix[0]));
      if (g_task_slots.type != tp && resolve_slots(tp, &g_task_slots) < 0)
        goto fail_ix;
    }
    const SlotCache& sc = g_task_slots;

    /* Mutation-free prepass: homogeneous types + the volume guard. */
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* task = PyList_GET_ITEM(tasks, row_ix[i]);
      if (Py_TYPE(task) != sc.type) {
        PyErr_SetString(PyExc_TypeError, "mixed TaskInfo types in batch");
        goto fail_ix;
      }
      if (is_alloc[i]) {
        PyObject* pod = get_slot(task, sc.off[kPod]);
        PyObject* vols =
            pod ? PyObject_GetAttr(pod, g_volumes_name) : nullptr;
        if (vols == nullptr) goto fail_ix;
        int truthy = PyObject_IsTrue(vols);
        Py_DECREF(vols);
        if (truthy < 0) goto fail_ix;
        if (truthy) {
          PyErr_SetString(PyExc_ValueError,
                          "bulk row carries volume claims (needs host-side "
                          "assume); use the Python path");
          goto fail_ix;
        }
      }
    }

    Py_ssize_t n_seg = PyList_GET_SIZE(counts);
    PyObject* out = PyList_New(n_seg);
    if (out == nullptr) goto fail_ix;
    Py_ssize_t i = 0;
    for (Py_ssize_t s = 0; s < n_seg; s++) {
      Py_ssize_t cnt = PyLong_AsSsize_t(PyList_GET_ITEM(counts, s));
      if (cnt == -1 && PyErr_Occurred()) goto fail_out;
      PyObject* alloc_d = PyDict_New();
      PyObject* pipe_d = PyDict_New();
      PyObject* pair = (alloc_d && pipe_d) ? PyTuple_Pack(2, alloc_d, pipe_d)
                                           : nullptr;
      Py_XDECREF(alloc_d);
      Py_XDECREF(pipe_d);
      if (pair == nullptr) goto fail_out;
      PyList_SET_ITEM(out, s, pair);
      Py_ssize_t end = i + cnt;
      if (end > n) {
        PyErr_SetString(PyExc_ValueError, "counts exceed event total");
        goto fail_out;
      }
      for (; i < end; i++) {
        PyObject* task = PyList_GET_ITEM(tasks, row_ix[i]);
        PyObject* uid = get_slot(task, sc.off[kUid]);
        if (is_alloc[i]) {
          set_slot(task, sc.off[kVolumeReady], Py_True);
          set_slot(task, sc.off[kStatus], st_alloc);
          if (PyDict_SetItem(alloc_d, uid, task) < 0) goto fail_out;
        } else {
          set_slot(task, sc.off[kStatus], st_pipe);
          if (PyDict_SetItem(pipe_d, uid, task) < 0) goto fail_out;
        }
        set_slot(task, sc.off[kNodeName],
                 PyList_GET_ITEM(node_names, nrow_ix[i]));
        PyObject* cl = clone_slots(task, sc);
        if (cl == nullptr) goto fail_out;
        PyObject* ntd = PyList_GET_ITEM(node_tasks, nrow_ix[i]);
        int rc = PyDict_SetItem(ntd, PyList_GET_ITEM(tkeys, row_ix[i]), cl);
        Py_DECREF(cl);
        if (rc < 0) goto fail_out;
      }
    }
    if (i != n) {
      PyErr_SetString(PyExc_ValueError, "counts do not cover all events");
      goto fail_out;
    }
    PyMem_Free(row_ix);
    return out;
  fail_out:
    Py_DECREF(out);
  }
fail_ix:
  PyMem_Free(row_ix);
  return nullptr;
}

/* ---- bulk_set_slot ------------------------------------------------------- */

/* bulk_set_slot(objs, name, value): obj.<name> = value for every obj —
 * the gang-dispatch status flip (finish()) without 100k interpreter
 * stores. Objects must share one __slots__ type. */
PyObject* bulk_set_slot(PyObject*, PyObject* args) {
  PyObject *objs, *name, *value;
  if (!PyArg_ParseTuple(args, "O!UO", &PyList_Type, &objs, &name, &value))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(objs);
  if (n == 0) Py_RETURN_NONE;
  PyTypeObject* tp = Py_TYPE(PyList_GET_ITEM(objs, 0));
  PyObject* descr = PyObject_GetAttr((PyObject*)tp, name);
  if (descr == nullptr) return nullptr;
  if (Py_TYPE(descr) != &PyMemberDescr_Type) {
    Py_DECREF(descr);
    PyErr_Format(PyExc_TypeError, "%s.%U is not a slot member", tp->tp_name,
                 name);
    return nullptr;
  }
  Py_ssize_t off = ((PyMemberDescrObject*)descr)->d_member->offset;
  Py_DECREF(descr);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* o = PyList_GET_ITEM(objs, i);
    if (Py_TYPE(o) != tp) {
      PyErr_SetString(PyExc_TypeError, "mixed object types in batch");
      return nullptr;
    }
    set_slot(o, off, value);
  }
  Py_RETURN_NONE;
}

/* ---- module -------------------------------------------------------------- */

PyMethodDef methods[] = {
    {"bulk_assign", bulk_assign, METH_VARARGS,
     "Apply kernel assignment events to session TaskInfo/node state."},
    {"bulk_set_slot", bulk_set_slot, METH_VARARGS,
     "Set one __slots__ attribute on every object in a list."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hotloops",
    "Native bulk session-mutation loops (see module docstring in source).",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__hotloops(void) {
  g_volumes_name = PyUnicode_InternFromString("volumes");
  if (g_volumes_name == nullptr) return nullptr;
  return PyModule_Create(&moduledef);
}
