"""Fleet observatory: cross-shard SLO aggregation (``KBT_FLEET``).

PR 14 tentpole (ISSUE.md). Every metric surface before this module is
per-process; the headline production number (ROADMAP item 1) is an
*aggregate* p99 across N federated shards — and percentiles do not
average. This module is the composable path: each shard's
``SLOAccountant`` keeps mergeable :class:`~kube_batch_tpu.obs.QuantileSketch`
rings and serves them serialized on ``/debug/slo?raw=1``; a
:class:`FleetAggregator` (running inside any scheduler, or standalone
via ``server.py --fleet``) scrapes its peers, merges the sketches —
cell-for-cell equivalent to sketching the pooled samples — and
publishes cluster-wide gauges:

- ``kube_batch_tpu_fleet_slo_{time_to_bind,queue_wait}_seconds``
  (labels: queue, quantile) — the merged sliding-window percentiles;
- ``kube_batch_tpu_fleet_node_conflicts`` — a top-K heatmap of
  contended nodes from ``federation_node_conflicts_total`` deltas
  between scrapes (the conflict-aware-scoring input, ROADMAP item 2);
- ``kube_batch_tpu_fleet_backlog_pods`` / ``..._pods_per_second`` /
  ``..._shards_scraped`` — aggregate backlog, bind throughput from
  bind-count deltas, and scrape reachability;
- ``kube_batch_tpu_fleet_shard_up{shard}`` /
  ``..._fleet_shard_last_scrape_age_seconds{shard}`` — per-peer
  reachability and staleness (the dead-shard signal the resharding
  runbook's triage ladder starts from).

Off by default, same single-branch discipline as ``KBT_TRACE``: when
``KBT_FLEET`` is empty/off, :func:`refresh` is one bool check returning
the shared :data:`NOOP_PAYLOAD`. Arm it with ``KBT_FLEET`` set to a
comma-separated list of peer base URLs (``http://host:port``), or the
hot-reloadable conf ``fleet:`` key.

Self-check: ``python -m kube_batch_tpu.obs.fleet --json`` runs N live
loopback shards (real ``LoopbackBackend`` wire path against a store
arbiter), feeds per-shard accountants from store bind events, scrapes
them over real HTTP, and asserts the merged p50/p90/p99 agree with
pooled-raw-sample ground truth within the sketch's declared relative
error — plus exactly-once binds and a clean fsck. Wired into
``hack/verify.py`` as the default ``fleet_obs_smoke`` gate.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from kube_batch_tpu import log, metrics
from kube_batch_tpu.obs import _OFF_WORDS, _QUANTILES, QuantileSketch, SLOAccountant

__all__ = [
    "ENV",
    "NOOP_PAYLOAD",
    "enabled",
    "peers",
    "configure",
    "raw_slo_payload",
    "FleetAggregator",
    "aggregator",
    "refresh",
    "smoke",
    "main",
]

ENV = "KBT_FLEET"
TIMEOUT_ENV = "KBT_FLEET_SCRAPE_TIMEOUT_S"
STALE_ENV = "KBT_FLEET_STALE_S"

_enabled = False
_peers: tuple[str, ...] = ()


def scrape_timeout_s() -> float:
    """Per-peer scrape timeout. Scrapes run concurrently, so one hung
    peer delays a refresh by at most this bound — not N x this bound."""
    try:
        return max(0.05, float(os.environ.get(TIMEOUT_ENV, "") or 3.0))
    except ValueError:
        return 3.0


def stale_cap_s() -> float:
    """Age cap on reusing a dark peer's last good payload in the merge.
    Within the cap a transient scrape miss does not yank that shard's
    samples out of the merged gauges; past it the shard's contribution
    ages out entirely (the conservative read for a dead shard)."""
    try:
        return max(0.0, float(os.environ.get(STALE_ENV, "") or 30.0))
    except ValueError:
        return 30.0

# The shared disabled result: refresh() returns this singleton when
# KBT_FLEET is off — identity-testable, same contract as obs.NOOP_SPAN.
NOOP_PAYLOAD: dict = {"enabled": False}


def enabled() -> bool:
    return _enabled


def peers() -> tuple[str, ...]:
    return _peers


def configure(spec=None) -> bool:
    """(Re)resolve the fleet switch. ``spec`` is the conf ``fleet:``
    value — empty/None defers to ``KBT_FLEET``. The value is a
    comma-separated list of peer base URLs; any off-word disables.
    Hot-reloadable: the scheduler calls this from its conf-reload path
    every cycle, same as obs.configure/explain.configure."""
    global _enabled, _peers
    if spec is None or str(spec).strip() == "":
        raw = os.environ.get(ENV, "").strip()
    else:
        raw = str(spec).strip()
    if raw.lower() in _OFF_WORDS:
        on, peer_list = False, ()
    else:
        peer_list = tuple(p.strip() for p in raw.split(",") if p.strip())
        on = bool(peer_list)
    if on != _enabled:
        log.infof(
            "fleet aggregation %s (%d peers)",
            "enabled" if on else "disabled", len(peer_list),
        )
    _enabled = on
    _peers = peer_list
    return on


# -- the wire form ------------------------------------------------------------


def _counters_snapshot() -> dict:
    """The key counters a fleet aggregator needs alongside the
    sketches, from this process's metric registry."""
    return {
        "federation_conflicts": {
            dict(key).get("outcome", ""): value
            for key, value in metrics.federation_conflicts.samples().items()
        },
        "node_conflicts": {
            dict(key).get("node", ""): value
            for key, value in metrics.federation_node_conflicts.samples().items()
        },
        "streaming_backlog": metrics.streaming_backlog.value(),
        "binds_total": metrics.task_scheduling_latency.snapshot()["count"],
    }


def raw_slo_payload(accountant: SLOAccountant | None = None,
                    counters: dict | None = None) -> dict:
    """The ``/debug/slo?raw=1`` body: this process's serialized SLO
    sketches plus the counters the fleet aggregator rolls up. The
    smoke's loopback observatories serve per-shard accountants through
    the same builder, so the wire form is literally shared code."""
    from kube_batch_tpu import obs as _obs

    acct = accountant if accountant is not None else _obs.slo
    payload = acct.raw()
    payload["counters"] = counters if counters is not None else _counters_snapshot()
    payload["pid"] = os.getpid()
    return payload


# -- the aggregator -----------------------------------------------------------


class FleetAggregator:
    """Scrapes peer shards' ``/debug/slo?raw=1``, merges their sketches
    and counters, and publishes the cluster-wide ``fleet_*`` gauges.
    Scrape-on-demand (no thread): the server's /metrics handler calls
    :func:`refresh`, internally rate-limited to ``min_interval_s``."""

    TOPK = 8
    MIN_INTERVAL_S = 1.0

    def __init__(self, topk: int | None = None,
                 min_interval_s: float | None = None) -> None:
        self.topk = int(topk if topk is not None else self.TOPK)
        self.min_interval_s = float(
            min_interval_s if min_interval_s is not None else self.MIN_INTERVAL_S
        )
        self._lock = threading.Lock()
        self._last_mono = 0.0  #: guarded_by _lock
        self._prev_nodes: dict[str, float] = {}  #: guarded_by _lock
        self._prev_binds: float | None = None  #: guarded_by _lock
        self._prev_binds_mono = 0.0  #: guarded_by _lock
        self._last_seen: dict[str, float] = {}  #: guarded_by _lock (peer url -> last good scrape)
        self._payload_cache: dict[str, tuple[float, dict]] = {}  #: guarded_by _lock
        self.last: dict = {}  #: guarded_by _lock

    def scrape(self, base_url: str, timeout: float | None = None) -> dict | None:
        url = base_url.rstrip("/") + "/debug/slo?raw=1"
        if timeout is None:
            timeout = scrape_timeout_s()
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except (OSError, ValueError) as e:
            log.errorf("fleet: scrape of %s failed: %s", url, e)
            return None

    def reset(self) -> None:
        with self._lock:
            self._last_mono = 0.0
            self._prev_nodes = {}
            self._prev_binds = None
            self._prev_binds_mono = 0.0
            self._last_seen = {}
            self._payload_cache = {}
            self.last = {}
        metrics.fleet_shard_up.clear()
        metrics.fleet_shard_scrape_age.clear()

    def refresh(self, force: bool = False) -> dict:
        if not _enabled:
            return NOOP_PAYLOAD
        with self._lock:
            now = time.monotonic()
            if not force and self.last and now - self._last_mono < self.min_interval_s:
                return self.last
            self._last_mono = now
        peer_list = _peers
        # Scrape OUTSIDE the lock (blocking I/O) and CONCURRENTLY: one
        # hung peer bounds the refresh by the per-peer timeout, not by
        # peers x timeout — the publish loop and the admission
        # controller's input snapshot must not stall on a dark shard.
        timeout = scrape_timeout_s()
        results: dict[str, dict | None] = {}
        workers = [
            threading.Thread(
                target=lambda p=peer: results.__setitem__(p, self.scrape(p, timeout)),
                name="kb-fleet-scrape", daemon=True,
            )
            for peer in peer_list
        ]
        for worker in workers:
            worker.start()
        deadline = time.monotonic() + timeout + 1.0
        for worker in workers:
            worker.join(timeout=max(0.0, deadline - time.monotonic()))
        reached: list[str] = []
        payloads: list[dict] = []
        now = time.monotonic()
        cap = stale_cap_s()
        with self._lock:
            for peer in peer_list:
                data = results.get(peer)
                if data is not None:
                    reached.append(peer)
                    payloads.append(data)
                    self._payload_cache[peer] = (now, data)
                    continue
                cached = self._payload_cache.get(peer)
                if cached is not None and now - cached[0] <= cap:
                    # transient miss: keep the last good payload in the
                    # merge (reachability gauges still flip to dark) so
                    # merged quantiles don't lurch on one missed scrape
                    payloads.append(cached[1])
        return self._merge(peer_list, reached, payloads)

    def _merge(self, peer_list, reached, payloads) -> dict:
        # 1. sketches: cell-wise merge per kind x queue — the result is
        # identical to one sketch fed every shard's samples.
        merged: dict[str, dict[str, QuantileSketch]] = {}
        for data in payloads:
            for kind, per_queue in (data.get("kinds") or {}).items():
                target = merged.setdefault(kind, {})
                for queue, wire in per_queue.items():
                    sk = QuantileSketch.from_wire(wire)
                    if queue in target:
                        target[queue].merge(sk)
                    else:
                        target[queue] = sk
        slo_out: dict[str, dict] = {}
        for kind, per_queue in merged.items():
            slo_out[kind] = {}
            for queue, sk in per_queue.items():
                sk.trim()
                n = sk.count()
                if n == 0:
                    continue
                stats: dict = {"n": n}
                for label, q in _QUANTILES:
                    stats[label] = sk.quantile(q)
                    metrics.set_fleet_slo_quantile(kind, queue, label, stats[label])
                slo_out[kind][queue] = stats
        # 2. counters: node-conflict deltas since the previous scrape
        # (top-K heatmap), backlog sum, bind-throughput from deltas.
        node_totals: dict[str, float] = {}
        backlog = 0.0
        binds = 0.0
        for data in payloads:
            counters = data.get("counters") or {}
            for node, value in (counters.get("node_conflicts") or {}).items():
                node_totals[node] = node_totals.get(node, 0.0) + float(value)
            backlog += float(counters.get("streaming_backlog") or 0.0)
            binds += float(counters.get("binds_total") or 0.0)
        now = time.monotonic()
        with self._lock:
            # per-shard reachability: up 0/1 plus seconds since the last
            # good scrape (-1 = never reached) — the fleet-level "is that
            # shard dead" signal the resharding runbook's triage starts
            # from (a shard can be down while its slot lease is still
            # ticking out)
            reached_set = set(reached)
            shard_up: dict[str, bool] = {}
            scrape_age: dict[str, float] = {}
            for peer in peer_list:
                up = peer in reached_set
                shard_up[peer] = up
                if up:
                    self._last_seen[peer] = now
                seen = self._last_seen.get(peer)
                scrape_age[peer] = (now - seen) if seen is not None else -1.0
            deltas = {
                node: value - self._prev_nodes.get(node, 0.0)
                for node, value in node_totals.items()
            }
            top = dict(sorted(
                ((node, d) for node, d in deltas.items() if d > 0),
                key=lambda kv: (-kv[1], kv[0]),
            )[: self.topk])
            pods_per_s = 0.0
            if self._prev_binds is not None and now > self._prev_binds_mono:
                pods_per_s = max(
                    0.0, (binds - self._prev_binds) / (now - self._prev_binds_mono)
                )
            self._prev_nodes = node_totals
            self._prev_binds = binds
            self._prev_binds_mono = now
            payload = {
                "enabled": True,
                "peers": list(peer_list),
                "shards_scraped": len(reached),
                "shard_up": shard_up,
                "shard_scrape_age_s": scrape_age,
                "slo": slo_out,
                "node_conflict_topk": top,
                "backlog_pods": backlog,
                "pods_per_second": pods_per_s,
            }
            self.last = payload
        metrics.set_fleet_node_heatmap(top)
        metrics.set_fleet_backlog(backlog)
        metrics.set_fleet_pods_per_second(pods_per_s)
        metrics.set_fleet_shards_scraped(len(reached))
        for peer in peer_list:
            metrics.set_fleet_shard_up(peer, shard_up[peer])
            metrics.set_fleet_shard_scrape_age(peer, scrape_age[peer])
        return payload


aggregator = FleetAggregator()


def refresh(force: bool = False) -> dict:
    """The one fleet entry point hot paths call (server /metrics and
    /debug/fleet). One branch when off."""
    if not _enabled:
        return NOOP_PAYLOAD
    return aggregator.refresh(force=force)


# -- smoke --------------------------------------------------------------------


def _serve_observatory(accountant: SLOAccountant, counters_fn):
    """A loopback HTTP server exposing one accountant through the SAME
    raw_slo_payload builder server.py uses — the smoke's stand-in for a
    peer shard's /debug/slo?raw=1 (in-process shards share the module
    global obs.slo, so each needs its own accountant to be a distinct
    scrape target)."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path.startswith("/debug/slo"):
                body = json.dumps(
                    raw_slo_payload(accountant=accountant, counters=counters_fn()),
                    sort_keys=True,
                ).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    return srv, thread


def smoke(shards: int = 2, gangs: int = 8, members: int = 3,
          nodes: int = 10) -> dict:
    """Fleet aggregation end-to-end proof, runnable standalone
    (``python -m kube_batch_tpu.obs.fleet``) and from hack/verify.py:

    1. run a seeded ``shards``-way federated world over live
       LoopbackBackends against a real SchedulerServer store arbiter;
    2. feed one SLOAccountant PER SHARD from store bind events (routed
       by the same crc32 gang-shard rule the schedulers use), keeping
       every raw sample as pooled ground truth;
    3. serve each accountant on its own loopback observatory, arm
       ``KBT_FLEET`` with those URLs, and drive the real scrape→
       deserialize→merge path twice (baseline + final);
    4. assert merged cluster-wide p50/p90/p99 agree with pooled-raw
       nearest-rank ground truth within the sketch's declared relative
       error, exact sample counts match, every pod bound exactly once,
       fsck is clean, and the throughput gauge moved;
    5. kill one observatory and re-scrape: ``fleet_shard_up`` must flip
       to 0 for exactly the killed peer (survivors stay up) and its
       last-scrape age must start growing.
    """
    import threading as _threading

    from kube_batch_tpu.cache import EventHandler, LoopbackBackend
    from kube_batch_tpu.cache.store import PODS
    from kube_batch_tpu.federation import (
        FederatedCache,
        _seed_world,
        _wait_all_bound,
        fsck,
        shard_index,
        shard_key_of,
    )
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer

    total = gangs * members
    alpha = QuantileSketch.DEFAULT_ALPHA
    server = SchedulerServer(
        scheduler_name="fleet-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()

    accountants = [SLOAccountant(window_s=300.0) for _ in range(shards)]
    pooled: dict[str, list[float]] = {}
    shard_binds = [0] * shards
    bind_counts: dict[str, int] = {}
    t0: dict[str, float] = {}
    state_lock = _threading.Lock()

    def _queue_of(pod_name: str) -> str:
        # fg{g}-p{m} -> two synthetic tenants, so the merge is checked
        # across queues, not just on one label set
        try:
            g = int(pod_name.split("-")[0][2:])
        except ValueError:
            g = 0
        return f"tenant{g % 2}"

    def _on_bind(old, new) -> None:
        if old.node_name or not new.node_name:
            return
        key = f"{new.namespace}/{new.name}"
        now = time.perf_counter()
        with state_lock:
            bind_counts[key] = bind_counts.get(key, 0) + 1
            seconds = now - t0.get(key, now)
            queue = _queue_of(new.name)
            # mode "gang" never touches the store — safe inside a store
            # event callback
            sh = shard_index(shard_key_of(new, None, "gang"), shards)
            accountants[sh].observe("time_to_bind", queue, seconds)
            accountants[sh].observe("queue_wait", queue, seconds)
            pooled.setdefault(queue, []).append(seconds)
            shard_binds[sh] += 1

    server.store.add_event_handler(PODS, EventHandler(on_update=_on_bind))

    observatories = []
    urls = []
    for i in range(shards):
        def _counters(i=i) -> dict:
            with state_lock:
                mine = shard_binds[i]
            # the process-global conflict counters are served once (from
            # shard 0) — every in-process scheduler shares one registry,
            # and double-counting them would corrupt the rollup
            node_conflicts = {
                dict(key).get("node", ""): value
                for key, value in
                metrics.federation_node_conflicts.samples().items()
            } if i == 0 else {}
            return {
                "federation_conflicts": {},
                "node_conflicts": node_conflicts,
                "streaming_backlog": 0,
                "binds_total": mine,
            }

        srv, thread = _serve_observatory(accountants[i], _counters)
        observatories.append((srv, thread))
        urls.append(f"http://127.0.0.1:{srv.server_address[1]}")

    prev_env = os.environ.get(ENV)
    os.environ[ENV] = ",".join(urls)
    configure()
    aggregator.reset()

    backends: list = []
    scheds: list = []
    stop = _threading.Event()
    try:
        _seed_world(server.store, gangs, members, nodes)
        arrival = time.perf_counter()
        with state_lock:
            for pod in server.store.list(PODS):
                t0[f"{pod.namespace}/{pod.name}"] = arrival
        # baseline scrape before any bind, so the final refresh's
        # pods-per-second delta covers the whole run
        aggregator.refresh(force=True)
        base = f"http://127.0.0.1:{server.listen_port}"
        for i in range(shards):
            backend = LoopbackBackend(base)
            cache = FederatedCache(
                backend, shard=i, shards=shards, shard_key="gang",
                staleness_fn=backend.snapshot_age,
            )
            cache.run()
            backend.start(period=0.02)
            backends.append(backend)
            sched = Scheduler(cache, schedule_period=0.05)
            thread = _threading.Thread(
                target=sched.run, args=(stop,), name=f"kb-fleet-{i}", daemon=True
            )
            thread.start()
            scheds.append((sched, thread))
        all_bound = _wait_all_bound(server.store, total, deadline_s=60.0)
        payload = aggregator.refresh(force=True)
        # kill one shard's observatory and re-scrape: the per-shard
        # reachability gauges must flip (up -> 0, scrape age starts
        # growing) while the survivors stay up
        killed_url = urls[-1]
        srv_k, thread_k = observatories.pop()
        srv_k.shutdown()
        srv_k.server_close()
        thread_k.join(timeout=5.0)
        down_payload = aggregator.refresh(force=True)
    finally:
        stop.set()
        for _, thread in scheds:
            thread.join(timeout=10.0)
        for backend in backends:
            backend.stop()
        for sched, _ in scheds:
            sched.cache.stop()
        server.stop()
        for srv, thread in observatories:
            srv.shutdown()
            srv.server_close()
            thread.join(timeout=5.0)
        if prev_env is None:
            os.environ.pop(ENV, None)
        else:
            os.environ[ENV] = prev_env
        configure()

    # merged vs pooled-raw ground truth, per queue, nearest-rank rule
    import math as _math

    compare: dict[str, dict] = {}
    max_rel_err = 0.0
    counts_match = True
    merged_slo = payload.get("slo", {}).get("time_to_bind", {})
    with state_lock:
        pooled_now = {q: sorted(vals) for q, vals in pooled.items()}
    for queue, values in pooled_now.items():
        n = len(values)
        got = merged_slo.get(queue)
        if got is None or got.get("n") != n:
            counts_match = False
            continue
        compare[queue] = {}
        for label, q in _QUANTILES:
            exact = values[min(n - 1, max(0, _math.ceil(q * n) - 1))]
            merged_v = got[label]
            rel = abs(merged_v - exact) / exact if exact > 0 else 0.0
            compare[queue][label] = {
                "merged": merged_v, "pooled": exact, "rel_err": rel,
            }
            max_rel_err = max(max_rel_err, rel)

    exactly_once = all_bound and sorted(bind_counts.values()) == [1] * total
    violations = fsck(server.store)
    within_bound = bool(compare) and max_rel_err <= alpha * 1.05 + 1e-9

    # killed-shard detection: every shard up before the kill; after it,
    # exactly the killed one reports down — in the payload AND in the
    # published fleet_shard_up gauge — with its scrape age now growing
    up_before = payload.get("shard_up", {})
    up_after = down_payload.get("shard_up", {})
    age_after = down_payload.get("shard_scrape_age_s", {})
    gauge_up = {
        dict(key).get("shard", ""): value
        for key, value in metrics.fleet_shard_up.samples().items()
    }
    killed_shard_detected = bool(
        all(up_before.get(u) for u in urls)
        and up_after.get(killed_url) is False
        and all(up_after.get(u) for u in urls if u != killed_url)
        and gauge_up.get(killed_url) == 0.0
        and age_after.get(killed_url, -1.0) >= 0.0
    )

    out = {
        "shards": shards,
        "pods": total,
        "bound": sum(bind_counts.values()),
        "exactly_once": exactly_once,
        "fsck_violations": violations,
        "shards_scraped": payload.get("shards_scraped", 0),
        "queues": sorted(pooled_now),
        "alpha": alpha,
        "max_rel_err": max_rel_err,
        "rel_err_bound": alpha * 1.05,
        "within_bound": within_bound,
        "counts_match": counts_match,
        "slo_compare": compare,
        "pods_per_second": payload.get("pods_per_second", 0.0),
        "backlog_pods": payload.get("backlog_pods", 0.0),
        "node_conflict_topk": payload.get("node_conflict_topk", {}),
        "scraped_after_kill": down_payload.get("shards_scraped", 0),
        "killed_shard_detected": killed_shard_detected,
    }
    out["ok"] = bool(
        all_bound
        and exactly_once
        and not violations
        and out["shards_scraped"] == shards
        and out["scraped_after_kill"] == shards - 1
        and killed_shard_detected
        and counts_match
        and within_bound
        and out["pods_per_second"] > 0.0
    )
    return out


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="fleet observability smoke: N loopback shards scraped "
        "and merged, cluster-wide quantiles checked against pooled raw "
        "samples within the sketch's relative-error bound"
    )
    parser.add_argument("--shards", type=int, default=2)
    parser.add_argument("--gangs", type=int, default=8)
    parser.add_argument("--members", type=int, default=3)
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    result = smoke(shards=args.shards, gangs=args.gangs, members=args.members)
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"fleet smoke: {status} ({result['bound']}/{result['pods']} pods "
            f"across {result['shards']} shards, scraped="
            f"{result['shards_scraped']}, max_rel_err="
            f"{result['max_rel_err']:.4f} (alpha={result['alpha']}), "
            f"pods_per_second={result['pods_per_second']:.1f}, "
            f"fsck={'clean' if not result['fsck_violations'] else result['fsck_violations']})"
        )
    return 0 if result["ok"] else 1


configure()


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level state would otherwise be
    # distinct from the one other modules import
    from kube_batch_tpu.obs.fleet import main as _canonical_main

    raise SystemExit(_canonical_main())
