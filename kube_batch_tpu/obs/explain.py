"""Decision observability: unschedulability forensics + placement provenance.

PR 12 tentpole (ISSUE.md). Tracing (obs/__init__) answers "where did the
cycle's time go"; this module answers the operator's *first* question —
"why didn't gang X bind, and what single constraint relaxation would fix
it?" — plus the training-data question ROADMAP item 5 asks: every
decision leaves a labeled (state, decision, reason) record.

After every solve, each gang that still has a pending task gets a
forensics record computed from the final arena tensors by the batched
kernel in ops/explain (plane elimination counts, leave-one-plane-out
would-fit-if verdicts, top-k near-miss nodes); gangs that bound with no
pending remainder get a light provenance record derived from the
session. The serial allocate action computes byte-identical records
task-by-task through a post-action re-encode (`explain_session`), so
explain parity is pinned serial = XLA = mesh exactly like placement
parity. Records flow out through every existing channel:

- ``/debug/explain?gang=...`` on server.py (registry snapshot + aggregate);
- an ``explain`` span on the cycle/micro-cycle trace carrying the
  summary, so forensics ride the flight recorder;
- ``kube_batch_tpu_unschedulable_total{reason}`` and
  ``kube_batch_tpu_would_fit_if_total{plane}`` counters;
- PodGroup Unschedulable conditions (the gang plugin swaps its generic
  reason/message for the explain record's at session close), which is
  also the federation cross-shard aggregation channel — shard commits
  push conditions through ``/backend/v1/`` into the arbiter store, and
  :func:`aggregate_conditions` folds them back together;
- an ``explain`` field on journal intent records (replay ignores
  unknown keys), giving the bind-intent journal labeled decision tuples.

Off by default. Armed with ``KBT_EXPLAIN=1`` or the hot-reloadable conf
``explain:`` key; when off, every entry point is one module-bool check
(same no-op discipline as ``KBT_TRACE``, pinned by the overhead guard
test). ``python -m kube_batch_tpu.obs.explain --json`` runs the seeded
self-check: one forced-unschedulable gang per plane class, serial/XLA
record parity, reason-per-plane verdicts, and flight-recorder presence.
"""

from __future__ import annotations

import collections
import json
import os
import threading

from kube_batch_tpu import log

__all__ = [
    "ENV",
    "TOP_K",
    "PLANES",
    "REASON_STARVED",
    "REASON_BOUND",
    "enabled",
    "configure",
    "records",
    "explain_post_solve",
    "explain_session",
    "publish",
    "summary",
    "condition_message",
    "intent_payload",
    "aggregate",
    "aggregate_conditions",
    "debug_payload",
    "smoke",
    "main",
]

ENV = "KBT_EXPLAIN"
TOP_K = 3

# Re-exported lazily from ops.explain (importing jax here would put it
# on the no-explain import path of every obs consumer).
PLANES = ("static", "room", "ports", "resources")

# A gang with feasible nodes that still did not reach min_available was
# starved (queue overused, gang barrier, or another gang took the room
# first) — no single plane eliminated it.
REASON_STARVED = "starved"
REASON_BOUND = "bound"

_OFF_WORDS = ("", "0", "false", "off", "no")
_enabled = False


def enabled() -> bool:
    return _enabled


def configure(spec=None) -> bool:
    """(Re)resolve the explain switch. ``spec`` is the conf ``explain:``
    value — empty/None defers to ``KBT_EXPLAIN``. Hot-reloadable: the
    scheduler calls this from its conf-reload path every cycle."""
    global _enabled
    if spec is None or str(spec).strip() == "":
        on = os.environ.get(ENV, "").strip().lower() not in _OFF_WORDS
    else:
        on = str(spec).strip().lower() not in _OFF_WORDS
    if on != _enabled:
        log.infof("explain %s", "enabled" if on else "disabled")
    _enabled = on
    return on


class _Registry:
    """Bounded per-process record store keyed by gang uid (insertion
    order = recency; re-publishing a gang moves it to the back). Serves
    /debug/explain and the journal intent payload lookup."""

    def __init__(self, max_records: int = 4096) -> None:
        self._lock = threading.Lock()
        self._records: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
        self.max_records = max_records

    def update(self, recs: dict) -> None:
        with self._lock:
            for uid, rec in recs.items():
                self._records.pop(uid, None)
                self._records[uid] = rec
            while len(self._records) > self.max_records:
                self._records.popitem(last=False)

    def get(self, uid: str) -> dict | None:
        with self._lock:
            return self._records.get(uid)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._records.values())

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


records = _Registry()


# -- record construction ------------------------------------------------------


def _eligible(job, queues) -> bool:
    """The encode shortlist's job eligibility, verbatim (ops/encode):
    Pending-phase PodGroups wait for enqueue, unknown queues are
    skipped — gangs the allocate actions never considered get no
    record."""
    from kube_batch_tpu.apis.types import PodGroupPhase

    if job.pod_group is not None and job.pod_group.status.phase == PodGroupPhase.PENDING:
        return False
    return job.queue in queues


def _forensics_record(
    job, ready, minm, task, node_names, valid_cnt, elim, feasible, would,
    nm_idx, nm_score, nm_planes, topk,
) -> dict:
    """One gang's full forensics record from the kernel outputs. Shared
    by the batched and serial paths so the records are byte-identical
    by construction — only the plane/score numbers differ per path, and
    those are parity-pinned in ops/explain."""
    verdict = REASON_BOUND if ready >= minm else "unschedulable"
    feasible = int(feasible)
    if verdict == REASON_BOUND:
        reason = REASON_BOUND
    elif feasible > 0:
        reason = REASON_STARVED
    else:
        # Dominant reason = the cheapest single fix: among planes whose
        # solo relaxation makes some node feasible, the one eliminating
        # the fewest nodes (a selector-confined gang blocked on a port
        # reads "ports", not "static"). No single-plane fix -> the
        # largest eliminator. Ties break on plane order; both paths run
        # this same host code on parity-pinned numbers.
        fixes = [p for p in range(len(PLANES)) if would[p]]
        if fixes:
            reason = PLANES[min(fixes, key=lambda p: (int(elim[p]), p))]
        else:
            reason = PLANES[max(range(len(PLANES)), key=lambda p: int(elim[p]))]
    near = []
    for j in range(min(int(topk), int(valid_cnt))):
        near.append(
            {
                "node": node_names[int(nm_idx[j])],
                "score": float(nm_score[j]),
                "planes": {p: bool(nm_planes[j][i]) for i, p in enumerate(PLANES)},
            }
        )
    return {
        "gang": job.uid,
        "name": f"{job.namespace}/{job.name}",
        "verdict": verdict,
        "ready": int(ready),
        "min": int(minm),
        "reason": reason,
        "task": f"{task.namespace}/{task.name}",
        "nodes": int(valid_cnt),
        "feasible": feasible,
        "eliminated": {p: int(elim[i]) for i, p in enumerate(PLANES)},
        "would_fit_if": {p: bool(would[i]) for i, p in enumerate(PLANES)},
        "near_miss": near,
    }


def _bound_record(job) -> dict:
    return {
        "gang": job.uid,
        "name": f"{job.namespace}/{job.name}",
        "verdict": REASON_BOUND,
        "ready": int(job.ready_task_num()),
        "min": int(job.min_available),
        "reason": REASON_BOUND,
    }


def _light_bound_records(ssn, skip) -> dict:
    """Provenance records for gangs with no pending remainder, derived
    from the (parity-pinned) session end state — identical on both
    paths by construction. Gangs below min_available with no pending
    task left (fully pipelined remainders) get no record: neither
    path's encode can see them, and skipping is the parity-safe
    choice."""
    out: dict = {}
    for job in ssn.jobs.values():
        if job.uid in skip or not _eligible(job, ssn.queues):
            continue
        if job.ready_task_num() >= job.min_available:
            out[job.uid] = _bound_record(job)
    return out


def explain_post_solve(ssn, enc, arrays, state, result, topk: int = TOP_K) -> dict:
    """The device path: one batched forensics kernel over every gang
    with a pending representative row in the *pre-solve* encode (a row
    the solve left unassigned is still pending), evaluated against the
    final SolveState tensors. Called by xla_allocate between the gang
    replay and dispatch so journal intents can carry the records."""
    import numpy as np

    from kube_batch_tpu.ops import explain as ops_explain

    kinds = np.asarray(result.assigned_kind)
    ready = np.asarray(result.ready_cnt)
    a = arrays
    job_rows: list[tuple[int, int]] = []
    for j in range(len(enc.jobs)):
        if not a["job_valid"][j]:
            continue
        js, je = int(a["job_start"][j]), int(a["job_end"][j])
        pend = np.flatnonzero(kinds[js:je] == 0)
        if pend.size:
            job_rows.append((j, js + int(pend[0])))

    out: dict = {}
    if job_rows:
        from kube_batch_tpu.ops import class_solve

        # Under KBT_CLASS_COMPRESS forensics fold the node axis the same
        # way the solver does: one evaluated row per equivalence class,
        # expanded back to per-node records by membership. Byte-identical
        # outputs either way (ops/explain parity test), so records never
        # change shape when the flag flips.
        explain_fn = (
            ops_explain.explain_batch_classes
            if class_solve.enabled()
            else ops_explain.explain_batch
        )
        rep_rows = ops_explain.pad_rows([r for _, r in job_rows])
        elim, feasible, would, nm_idx, nm_score, nm_planes = explain_fn(
            a,
            np.asarray(state.idle),
            np.asarray(state.rel),
            np.asarray(state.used),
            np.asarray(state.ntasks),
            np.asarray(state.nports),
            rep_rows,
            topk=topk,
        )
        valid_cnt = int(np.asarray(a["node_valid"]).sum())
        for g, (j, rep) in enumerate(job_rows):
            job = enc.jobs[j]
            out[job.uid] = _forensics_record(
                job, int(ready[j]), int(a["job_min"][j]), enc.tasks[rep],
                enc.node_names, valid_cnt, elim[g], feasible[g], would[g],
                nm_idx[g], nm_score[g], nm_planes[g], topk,
            )
    out.update(_light_bound_records(ssn, out))
    return out


def explain_session(ssn, topk: int = TOP_K) -> dict:
    """The serial twin: re-encode the post-action session (node state
    parity is exactly what the segmented-hybrid resume path already
    relies on) and compute the identical records task-by-task with host
    numpy. Called by the serial allocate action at the end of its
    execute, covering both direct serial confs and every degradation
    fallback."""
    import numpy as np

    from kube_batch_tpu.actions.xla_allocate import _nodeorder_weights
    from kube_batch_tpu.ops import explain as ops_explain
    from kube_batch_tpu.ops.encode import encode_session

    # Mirror the device path's dtype selection so score floats agree
    # bit-for-bit whichever path ran (f32 worlds stay f32 here).
    try:
        import jax.numpy as jnp

        dtype = np.float64 if jnp.zeros(0).dtype == np.float64 else np.float32
    except Exception:  # noqa: BLE001 - explain must not require jax
        dtype = np.float64
    # session=None: the post-action encode must not churn the
    # cross-cycle encode cache keyed to pre-action snapshots.
    enc = encode_session(
        ssn.jobs, ssn.nodes, ssn.queues, dtype=dtype, pad=False, session=None
    )
    out: dict = {}
    if enc.tasks:
        a = dict(enc.arrays)
        w_least, w_balanced, w_aff, _w_podaff = _nodeorder_weights(ssn)
        a["w_least"] = dtype(w_least)
        a["w_balanced"] = dtype(w_balanced)
        a["w_aff"] = dtype(w_aff)
        job_rows = [
            (j, int(a["job_start"][j]))
            for j in range(len(enc.jobs))
            if a["job_valid"][j]
        ]
        elim, feasible, would, nm_idx, nm_score, nm_planes = (
            ops_explain.explain_rows_np(
                a, a["node_idle"], a["node_rel"], a["node_used"],
                a["node_ntasks"], a["node_ports"],
                [r for _, r in job_rows], topk=topk,
            )
        )
        valid_cnt = int(np.asarray(a["node_valid"]).sum())
        for g, (j, rep) in enumerate(job_rows):
            job = enc.jobs[j]
            out[job.uid] = _forensics_record(
                job, int(a["job_ready0"][j]), int(a["job_min"][j]),
                enc.tasks[rep], enc.node_names, valid_cnt, elim[g],
                feasible[g], would[g], nm_idx[g], nm_score[g], nm_planes[g],
                topk,
            )
    out.update(_light_bound_records(ssn, out))
    return out


# -- publication --------------------------------------------------------------


def condition_message(rec: dict) -> str:
    """The PodGroup condition message: kube-scheduler's one-line idiom
    over the dense counts ("0/40 nodes feasible: 12 static, 28
    resources; would fit if: resources")."""
    parts = [
        f"{rec['eliminated'][p]} {p}" for p in PLANES if rec["eliminated"].get(p)
    ]
    fixes = [p for p in PLANES if rec["would_fit_if"].get(p)]
    msg = (
        f"{rec['feasible']}/{rec['nodes']} nodes feasible for task "
        f"{rec['task']} ({rec['ready']}/{rec['min']} ready)"
    )
    if parts:
        msg += ": " + ", ".join(parts)
    if rec["reason"] == REASON_STARVED:
        msg += "; feasible nodes existed but the gang was starved"
    elif fixes:
        msg += "; would fit if: " + ", ".join(fixes)
    return msg


def summary(recs: dict) -> dict:
    """Flat span-attribute summary of one cycle's records (lands on the
    ``explain`` span, hence the flight recorder)."""
    reasons = collections.Counter(
        r["reason"] for r in recs.values() if r["verdict"] == "unschedulable"
    )
    return {
        "gangs": len(recs),
        "bound": sum(1 for r in recs.values() if r["verdict"] == REASON_BOUND),
        "unschedulable": sum(reasons.values()),
        "reasons": ",".join(f"{k}:{v}" for k, v in sorted(reasons.items())),
    }


def publish(ssn, recs: dict) -> None:
    """Fan one cycle's records out: session attribute (the gang plugin
    and journal read it), process registry (/debug/explain), reason
    counters. Condition writes stay with the gang plugin at session
    close so explain never fights it over the Unschedulable slot."""
    from kube_batch_tpu import metrics

    ssn.explain_records = recs
    records.update(recs)
    for rec in recs.values():
        if rec["verdict"] != "unschedulable":
            continue
        metrics.register_unschedulable(rec["reason"])
        if rec.get("feasible") == 0:
            for plane, flip in rec.get("would_fit_if", {}).items():
                if flip:
                    metrics.register_would_fit_if(plane)


def intent_payload(gang: str) -> dict | None:
    """The journal-intent ``explain`` payload for one gang: the compact
    decision label (replay ignores it; learned-scoring pipelines join
    the full record from the registry/debug surface by gang uid)."""
    rec = records.get(gang)
    if rec is None:
        return None
    return {
        "verdict": rec["verdict"],
        "reason": rec["reason"],
        "ready": rec["ready"],
        "min": rec["min"],
    }


# -- aggregation (shard-local and cross-shard) --------------------------------


def aggregate(recs) -> dict:
    """Reason/plane histogram over an iterable of records (the
    shard-local half of the federation story)."""
    out = {
        "gangs": 0,
        "bound": 0,
        "unschedulable": 0,
        "reasons": collections.Counter(),
        "would_fit_if": collections.Counter(),
    }
    for rec in recs:
        out["gangs"] += 1
        if rec["verdict"] == REASON_BOUND:
            out["bound"] += 1
            continue
        out["unschedulable"] += 1
        out["reasons"][rec["reason"]] += 1
        if rec.get("feasible") == 0:
            for plane, flip in rec.get("would_fit_if", {}).items():
                if flip:
                    out["would_fit_if"][plane] += 1
    out["reasons"] = dict(sorted(out["reasons"].items()))
    out["would_fit_if"] = dict(sorted(out["would_fit_if"].items()))
    return out


def aggregate_conditions(pod_groups) -> dict:
    """Cross-shard aggregate over PodGroup Unschedulable conditions —
    the one surface every shard already pushes through ``/backend/v1/``
    into the arbiter store, so the arbiter can fold N shards' explain
    verdicts without a new wire format. Counts the latest Unschedulable
    condition per group whose reason is an explain reason."""
    from kube_batch_tpu.apis.types import POD_GROUP_UNSCHEDULABLE_TYPE

    known = set(PLANES) | {REASON_STARVED}
    reasons: collections.Counter = collections.Counter()
    for pg in pod_groups:
        conds = [
            c
            for c in getattr(pg.status, "conditions", [])
            if c.type == POD_GROUP_UNSCHEDULABLE_TYPE and c.status == "True"
        ]
        if conds and conds[-1].reason in known:
            reasons[conds[-1].reason] += 1
    return {"unschedulable": sum(reasons.values()), "reasons": dict(sorted(reasons.items()))}


def debug_payload(gang: str | None = None) -> dict:
    """The /debug/explain response body. ``gang`` filters by uid,
    PodGroup name, or namespace/name."""
    recs = records.snapshot()
    if gang:
        recs = [
            r
            for r in recs
            if gang in (r["gang"], r["name"], r["name"].split("/", 1)[-1])
        ]
    return {
        "enabled": _enabled,
        "records": recs,
        "aggregate": aggregate(recs),
    }


# -- seeded self-check --------------------------------------------------------


def _smoke_world():
    """One forced-unschedulable gang per feasibility plane class, plus a
    bindable gang, on zone-partitioned nodes (node_selector confines
    each gang to its zone so the designed plane is the only obstacle):

    - ``g-static``: selector matches no zone -> static elimination;
    - ``g-resources``: wants 64 CPU on 16-CPU nodes -> resources;
    - ``g-ports``: wants host port 8080, zone-c residents hold it -> ports;
    - ``g-room``: zone-d nodes have zero pod headroom left -> room;
    - ``g-bound``: fits zone-e -> bound provenance record.
    """
    from kube_batch_tpu.testing import (
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )
    from kube_batch_tpu.apis.types import PodPhase

    nodes, pods, groups = [], [], []
    for zone in ("a", "b", "c", "d", "e"):
        for i in range(2):
            alloc = build_resource_list(cpu="16", memory="32Gi", pods="8")
            if zone == "d":
                alloc = build_resource_list(cpu="16", memory="32Gi", pods="1")
            nodes.append(build_node(f"n-{zone}-{i}", alloc, labels={"zone": zone}))

    def gang(name, zone, cpu="1", members=2, ports=None):
        groups.append(build_pod_group(name, min_member=members))
        for m in range(members):
            p = build_pod(
                name=f"{name}-{m}",
                req=build_resource_list(cpu=cpu, memory="1Gi"),
                group_name=name,
                node_selector={"zone": zone},
            )
            if ports:
                p.containers[0].ports = list(ports)
            pods.append(p)

    gang("g-static", "nowhere")
    gang("g-resources", "b", cpu="64")
    gang("g-ports", "c", ports=[8080])
    gang("g-room", "d")
    gang("g-bound", "e")
    # residents: port-8080 daemons on zone-c nodes, headroom-eaters on
    # zone-d (pods capacity 1, one resident -> zero room)
    for i in range(2):
        for zone, port in (("c", 8080), ("d", None)):
            p = build_pod(
                name=f"daemon-{zone}-{i}",
                node_name=f"n-{zone}-{i}",
                phase=PodPhase.RUNNING,
                req=build_resource_list(cpu="1", memory="1Gi"),
            )
            if port:
                p.containers[0].ports = [port]
            pods.append(p)
    return build_cluster(pods, nodes, groups, [build_queue("default")])


_SMOKE_TIERS = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""


def _smoke_run(action) -> tuple[dict, dict]:
    """Open a session over a fresh smoke world, run ``action``, return
    (records, ssn job uid -> condition reason after close)."""
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, open_session
    from kube_batch_tpu.testing import FakeCache

    tiers = parse_scheduler_conf(_SMOKE_TIERS).tiers
    cache = FakeCache(_smoke_world())
    ssn = open_session(cache, tiers)
    try:
        action.execute(ssn)
    finally:
        jobs = dict(ssn.jobs)  # close_session clears ssn.jobs
        close_session(ssn)
    recs = dict(getattr(ssn, "explain_records", {}) or {})
    conds = {}
    for uid, job in jobs.items():
        if job.pod_group is not None and job.pod_group.status.conditions:
            conds[uid] = job.pod_group.status.conditions[-1].reason
    return recs, conds


def smoke(out_dir: str | None = None) -> dict:
    """The seeded explain self-check (``python -m
    kube_batch_tpu.obs.explain --json``, hack/verify.py gate, Dockerfile
    build): serial and XLA runs over the per-plane world must produce
    byte-identical records, every designed gang must carry its designed
    reason with a consistent would-fit-if verdict, and the forensics
    must ride the flight recorder as an ``explain`` span."""
    import tempfile

    from kube_batch_tpu import obs
    from kube_batch_tpu.actions.allocate import AllocateAction
    from kube_batch_tpu.actions.xla_allocate import XlaAllocateAction

    saved = {}
    for env, value in (
        (ENV, "1"),
        (obs.ENV, "1"),
        ("KBT_MIN_DEVICE_PAIRS", "0"),
    ):
        saved[env] = os.environ.get(env)
        os.environ[env] = value
    configure()
    obs.configure()
    obs.recorder.clear()
    records.clear()
    try:
        serial_recs, serial_conds = _smoke_run(AllocateAction())
        xla_recs, xla_conds = _smoke_run(XlaAllocateAction())
    finally:
        for env, value in saved.items():
            if value is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = value
        configure()
        obs.configure()

    def canon(recs):
        return json.dumps(recs, sort_keys=True)

    parity = canon(serial_recs) == canon(xla_recs)
    expected = {
        "default/g-static": "static",
        "default/g-resources": "resources",
        "default/g-ports": "ports",
        "default/g-room": "room",
        "default/g-bound": REASON_BOUND,
    }
    reasons = {uid: rec["reason"] for uid, rec in xla_recs.items()}
    reasons_ok = all(reasons.get(uid) == want for uid, want in expected.items())
    would_ok = all(
        xla_recs[uid]["feasible"] == 0 and xla_recs[uid]["would_fit_if"][plane]
        for uid, plane in expected.items()
        if plane in PLANES and uid in xla_recs
    )
    conds_ok = all(
        serial_conds.get(uid) == want and xla_conds.get(uid) == want
        for uid, want in expected.items()
        if want != REASON_BOUND
    )
    spans = obs.recorder.spans()
    explain_spans = [s for s in spans if s["name"] == "explain"]
    recorded = any(s["attrs"].get("unschedulable", 0) > 0 for s in explain_spans)

    out_dir = out_dir or os.path.join(tempfile.gettempdir(), "kbt-explain-smoke")
    os.makedirs(out_dir, exist_ok=True)
    dump = os.path.join(out_dir, "explain.json")
    with open(dump, "w", encoding="utf-8") as f:
        json.dump({"serial": serial_recs, "xla": xla_recs}, f, sort_keys=True, indent=1)

    result = {
        "gangs": len(xla_recs),
        "parity": parity,
        "reasons": dict(sorted(reasons.items())),
        "reasons_ok": reasons_ok,
        "would_fit_if_ok": would_ok,
        "conditions_ok": conds_ok,
        "explain_spans": len(explain_spans),
        "recorded": recorded,
        "aggregate": aggregate(xla_recs.values()),
        "dump": dump,
        "ok": bool(
            parity and reasons_ok and would_ok and conds_ok and recorded
        ),
    }
    return result


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="explain smoke: one forced-unschedulable gang per "
        "feasibility plane, serial/XLA record parity asserted"
    )
    parser.add_argument("--out", default=None, help="record dump directory")
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    result = smoke(out_dir=args.out)
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"explain smoke: {status} ({result['gangs']} gangs, "
            f"parity={result['parity']}, reasons={result['reasons']})"
        )
    return 0 if result["ok"] else 1


configure()


if __name__ == "__main__":
    raise SystemExit(main())
