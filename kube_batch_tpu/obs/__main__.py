"""``python -m kube_batch_tpu.obs`` — tracing smoke (see obs.main)."""

from kube_batch_tpu.obs import main

raise SystemExit(main())
