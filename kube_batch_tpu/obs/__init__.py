"""Cycle-level distributed tracing, flight recorder, and SLO accounting.

PR 11 tentpole (ISSUE.md). The aggregate histograms in ``metrics/``
answer "how slow"; this package answers "where did gang X's 40 ms go,
on which shard, at which solver tier, behind which conflict retry":

- **Spans.** A scheduling cycle opens a root span (``cycle`` /
  ``micro_cycle``) with children for snapshot, encode (cache hit/warm
  stats as attrs), solve (tier + mesh size, compile events), statement
  commit, journal append, and store dispatch — each gang bind a span of
  its own carrying every conflict retry as a span event. Trace context
  crosses process boundaries as two ``/backend/v1/`` HTTP headers
  (:data:`HDR_TRACE`/:data:`HDR_SPAN`), so a federated bind's
  conflict-retry loop is ONE trace spanning N schedulers and the store
  arbiter. Streaming bind echoes synthesize per-pod ``time_to_bind``
  spans on the same tree.

- **Flight recorder.** Finished spans land in a bounded in-memory ring
  (last ``KBT_FLIGHT_RECORDER_CYCLES`` traces, default 256 ≈ 256
  cycles) that is dumped to disk — JSON-lines plus Chrome trace-event
  format loadable in Perfetto — on fault-point fire, cycle
  hard-deadline abort, SIGTERM, and on demand via ``/debug/trace``.

- **SLO accountant.** Sliding-window (``KBT_SLO_WINDOW_S``, default
  300 s) p50/p90/p99 time-to-bind and queue-wait *per queue*, kept in
  mergeable DDSketch-style :class:`QuantileSketch` rings (relative
  error ``alpha``, LRU-bounded queue cardinality), exposed on
  ``/metrics`` (``kbt..._slo_*`` gauges) and ``/debug/slo`` (append
  ``?raw=1`` for the serialized sketches) — the front-door input for
  ROADMAP item 1's admission lanes and the merge unit obs/fleet rolls
  up cluster-wide.

Tracing is off by default and zero-allocation-cheap when off: every
entry point checks one module bool and returns the shared no-op span
singleton (identity-testable — see tests/test_obs.py). Arm it with
``KBT_TRACE=1`` or the hot-reloadable conf ``trace:`` key.

The registries :data:`SPAN_NAMES` and :data:`DEBUG_ENDPOINTS` are the
single source of truth the KBT-R analyzer checks both directions
against call sites, server routes, and the runbook (R007-R010), same
contract as metrics/env/faults.
"""

from __future__ import annotations

import collections
import contextvars
import json
import math
import os
import signal
import tempfile
import threading
import time

from kube_batch_tpu import log, metrics

__all__ = [
    "ENV",
    "RECORDER_ENV",
    "RECORDER_CYCLES_ENV",
    "SLO_WINDOW_ENV",
    "HDR_TRACE",
    "HDR_SPAN",
    "SPAN_NAMES",
    "DEBUG_ENDPOINTS",
    "Span",
    "NOOP_SPAN",
    "enabled",
    "configure",
    "span",
    "emit",
    "event",
    "current",
    "current_headers",
    "from_headers",
    "annotate",
    "FlightRecorder",
    "recorder",
    "QuantileSketch",
    "SLOAccountant",
    "slo",
    "current_trace_id",
    "chrome_events",
    "export_jsonl",
    "export_chrome",
    "install_signal_dump",
    "smoke",
    "main",
]

ENV = "KBT_TRACE"
RECORDER_ENV = "KBT_FLIGHT_RECORDER"  # dump dir; "0" disables dumping
RECORDER_CYCLES_ENV = "KBT_FLIGHT_RECORDER_CYCLES"  # ring size in traces
SLO_WINDOW_ENV = "KBT_SLO_WINDOW_S"  # SLO sliding window, seconds

HDR_TRACE = "X-KBT-Trace-Id"
HDR_SPAN = "X-KBT-Span-Id"

# Every span name any call site may open. The KBT-R analyzer checks
# this tuple both directions (R007: literal span name used but not
# declared here; R008: declared but no call site uses it) — a typo'd
# span name would otherwise silently fork the trace tree.
SPAN_NAMES = (
    "cycle",          # scheduler.run_once root
    "micro_cycle",    # scheduler.run_micro root (streaming)
    "snapshot",       # session open: cache snapshot/clone
    "encode",         # SoA encode (cache hit/warm stats as attrs)
    "solve",          # solver entry (tier, mesh size, compile events)
    "gang.assign",    # one solved gang's host-side assignment/replay
    "commit",         # statement commit at session close
    "journal.append", # write-intent journal append (seqs as attr)
    "dispatch",       # cache.bind_many host side: resolve+journal+submit
    "gang.bind",      # one gang's store write, conflict retries as events
    "txn.batch",      # coalesced multi-gang conditional-write round trip
    "store.bind",     # store-arbiter side of a conditional bind (remote)
    "store.txn",      # store-arbiter side of a coalesced txn batch (remote)
    "time_to_bind",   # synthetic: streaming arrival -> bind echo, per pod
    "explain",        # post-solve unschedulability forensics (obs/explain)
)

# Every /debug/* route server.py serves. Checked both directions by the
# KBT-R analyzer (R009/R010/R012) against server.py literals and the
# runbook endpoint table.
DEBUG_ENDPOINTS = (
    "/debug/trace",
    "/debug/slo",
    "/debug/explain",
    "/debug/fleet",
    "/debug/admission",
)

# Wall/perf anchor pair: spans are stamped with the monotonic clock (so
# durations survive NTP steps) and exported in wall-clock microseconds
# via this one anchor (so Perfetto timelines from N processes line up).
_WALL0 = time.time()
_PERF0 = time.perf_counter()


def _now_us(perf_t: float) -> int:
    return int((_WALL0 + (perf_t - _PERF0)) * 1e6)


def _new_id() -> str:
    return os.urandom(8).hex()


_enabled = False
_current: contextvars.ContextVar = contextvars.ContextVar("kbt_span", default=None)


def enabled() -> bool:
    return _enabled


class _NoopSpan:
    """The shared do-nothing span. Every tracing entry point returns
    this singleton when tracing is off — no allocation, no contextvar
    touch; tests assert ``span(...) is NOOP_SPAN`` to pin the cost."""

    __slots__ = ()
    trace_id = ""
    span_id = ""
    parent_id = ""

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False

    def set_attr(self, *a, **kw) -> None:
        pass

    def event(self, *a, **kw) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class Span:
    """One timed, attributed node of a trace tree; a context manager
    that makes itself the thread/task-current span for its extent."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "start", "end", "attrs", "events", "tid", "_token",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: str = "",
        **attrs,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.end = 0.0
        self.attrs = attrs
        self.events: list[tuple[str, float, dict]] = []
        self.tid = threading.get_ident() & 0x7FFFFFFF
        self._token = None

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def event(self, name: str, **attrs) -> None:
        self.events.append((name, time.perf_counter(), attrs))

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self.attrs["error"] = f"{exc_type.__name__}: {exc}"
        self.finish()
        return False

    def finish(self) -> None:
        if self.end:
            return
        self.end = time.perf_counter()
        recorder.add(self)

    def to_dict(self) -> dict:
        end = self.end or time.perf_counter()
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": _now_us(self.start),
            "dur_us": max(1, int((end - self.start) * 1e6)),
            "pid": os.getpid(),
            "tid": self.tid,
            "attrs": dict(self.attrs),
            "events": [
                {"name": n, "ts_us": _now_us(t), "attrs": a}
                for n, t, a in self.events
            ],
        }


def span(name: str, parent=None, **attrs):
    """Open a span. Returns :data:`NOOP_SPAN` when tracing is off.

    ``parent`` overrides the ambient current span — pass the captured
    :func:`current` when crossing an executor boundary (contextvars do
    NOT propagate into pool threads), or a ``(trace_id, span_id)`` pair
    reconstructed from wire headers."""
    if not _enabled:
        return NOOP_SPAN
    if parent is None:
        parent = _current.get()
    if isinstance(parent, Span):
        return Span(name, parent.trace_id, parent.span_id, **attrs)
    if isinstance(parent, tuple) and len(parent) == 2 and parent[0]:
        return Span(name, parent[0], parent[1], **attrs)
    return Span(name, _new_id(), "", **attrs)


def emit(name: str, start: float, end: float, parent=None, **attrs) -> None:
    """Record an already-elapsed interval as a finished span (e.g. a
    streaming time-to-bind measured between two watch events).
    ``start``/``end`` are ``time.perf_counter()`` stamps."""
    if not _enabled:
        return
    s = span(name, parent=parent, **attrs)
    if s is NOOP_SPAN:
        return
    s.start = start
    s.end = end
    recorder.add(s)


def event(name: str, **attrs) -> None:
    """Attach an event to the current span, if any (cheap no-op off)."""
    if not _enabled:
        return
    cur = _current.get()
    if cur is not None:
        cur.event(name, **attrs)


def current():
    """The thread/task-current span, or None. Capture this before
    handing work to a pool thread and pass it as ``parent=``."""
    if not _enabled:
        return None
    return _current.get()


def current_trace_id() -> str:
    """The current span's trace id, or "" — the metric-exemplar hook
    (metrics attach it to observations under KBT_METRICS_EXEMPLARS)."""
    if not _enabled:
        return ""
    cur = _current.get()
    return cur.trace_id if cur is not None else ""


def current_headers() -> dict:
    """Wire headers propagating the current trace context, or {}."""
    if not _enabled:
        return {}
    cur = _current.get()
    if cur is None:
        return {}
    return {HDR_TRACE: cur.trace_id, HDR_SPAN: cur.span_id}


def from_headers(headers) -> tuple[str, str] | None:
    """Parse the propagation headers of an incoming request into a
    ``parent=`` value for :func:`span`, or None when absent/off."""
    if not _enabled:
        return None
    try:
        tid = headers.get(HDR_TRACE)
        sid = headers.get(HDR_SPAN)
    except AttributeError:
        return None
    if not tid:
        return None
    return (str(tid), str(sid or ""))


def annotate(label: str):
    """A ``jax.profiler`` trace annotation for a solver entry, so
    device profiles line up with scheduler spans; no-op when tracing is
    off or the profiler is unavailable."""
    if not _enabled:
        return NOOP_SPAN
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(label)
    except Exception:  # noqa: BLE001 - profiler is best-effort
        return NOOP_SPAN


# -- flight recorder ---------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent traces (insertion-ordered by trace id;
    one trace ≈ one scheduling cycle). Dump snapshots under the lock
    and writes files OUTSIDE it (KBT-D002: no blocking I/O under a
    lock the hot span path takes)."""

    def __init__(self, max_traces: int = 256) -> None:
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, list[dict]]" = (
            collections.OrderedDict()
        )
        self.max_traces = max_traces
        self._dumps = 0
        self._last_dump_mono = 0.0
        self.last_dump_path: str | None = None

    def add(self, sp: Span) -> None:
        d = sp.to_dict()
        with self._lock:
            bucket = self._traces.get(sp.trace_id)
            if bucket is None:
                self._traces[sp.trace_id] = bucket = []
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            bucket.append(d)

    def resize(self, max_traces: int) -> None:
        with self._lock:
            self.max_traces = max(1, int(max_traces))
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()

    def spans(self) -> list[dict]:
        with self._lock:
            return [s for bucket in self._traces.values() for s in bucket]

    def trace_count(self) -> int:
        with self._lock:
            return len(self._traces)

    def dump_dir(self) -> str | None:
        raw = os.environ.get(RECORDER_ENV, "")
        if raw == "0":
            return None
        return raw or os.path.join(tempfile.gettempdir(), "kbt-flight")

    def dump(self, reason: str = "on_demand", min_interval_s: float = 0.0) -> str | None:
        """Write the ring to ``<dir>/flight-<pid>-<n>-<reason>.jsonl``
        plus a sibling ``.trace.json`` (Chrome trace-event format).
        Returns the JSONL path, or None when disabled/empty/throttled.
        ``min_interval_s`` rate-limits dump storms (a fault point firing
        every cycle must not turn the dump dir into a firehose)."""
        directory = self.dump_dir()
        if directory is None:
            return None
        with self._lock:
            now = time.monotonic()
            if min_interval_s and now - self._last_dump_mono < min_interval_s:
                return None
            snapshot = [s for bucket in self._traces.values() for s in bucket]
            if not snapshot:
                return None
            self._last_dump_mono = now
            self._dumps += 1
            seq = self._dumps
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in reason)
        base = os.path.join(directory, f"flight-{os.getpid()}-{seq}-{safe}")
        path = base + ".jsonl"
        try:
            os.makedirs(directory, exist_ok=True)
            export_jsonl(snapshot, path)
            export_chrome(snapshot, base + ".trace.json")
        except OSError as e:
            log.errorf("flight recorder dump to %s failed: %s", path, e)
            return None
        with self._lock:
            self.last_dump_path = path
        log.infof("flight recorder: %d spans dumped to %s (%s)", len(snapshot), path, reason)
        return path


recorder = FlightRecorder()


# -- exporters ---------------------------------------------------------------


def export_jsonl(spans: list[dict], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        for s in spans:
            f.write(json.dumps(s, sort_keys=True, default=str))
            f.write("\n")
    return path


def chrome_events(spans: list[dict]) -> list[dict]:
    """Chrome trace-event records (Perfetto-loadable): one complete
    ("X") event per span, instant events for span events, and flow
    ("s"/"f") arrows stitching parent->child edges that cross a
    process or thread — a federated conflict then renders as one
    connected picture across N scheduler tracks."""
    evs: list[dict] = []
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        args = dict(s["attrs"])
        args["trace_id"] = s["trace_id"]
        args["span_id"] = s["span_id"]
        if s["parent_id"]:
            args["parent_id"] = s["parent_id"]
        evs.append({
            "name": s["name"], "cat": "kbt", "ph": "X",
            "ts": s["start_us"], "dur": s["dur_us"],
            "pid": s["pid"], "tid": s["tid"], "args": args,
        })
        for ev in s["events"]:
            evs.append({
                "name": ev["name"], "cat": "kbt", "ph": "i", "s": "t",
                "ts": ev["ts_us"], "pid": s["pid"], "tid": s["tid"],
                "args": dict(ev["attrs"]),
            })
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None and (
            parent["pid"] != s["pid"] or parent["tid"] != s["tid"]
        ):
            flow_id = int(s["span_id"][:8], 16)
            evs.append({
                "name": "link", "cat": "kbt.flow", "ph": "s", "id": flow_id,
                "ts": parent["start_us"], "pid": parent["pid"],
                "tid": parent["tid"],
            })
            evs.append({
                "name": "link", "cat": "kbt.flow", "ph": "f", "bp": "e",
                "id": flow_id, "ts": s["start_us"], "pid": s["pid"],
                "tid": s["tid"],
            })
    return evs


def export_chrome(spans: list[dict], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": chrome_events(spans)}, f, default=str)
    return path


# -- SLO accountant ----------------------------------------------------------


_QUANTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))

# Values at or below this collapse into the sketch's zero bucket (a
# latency of < 1 ns is measurement noise, not signal).
_SKETCH_MIN = 1e-9


class QuantileSketch:
    """DDSketch-style relative-error quantile sketch over a sliding
    time window, built to MERGE: two shards' sketches combined with
    :meth:`merge` are cell-for-cell identical to one sketch fed the
    pooled sample stream (cell assignment is a pure function of the
    observation's wall-clock time and value, given equal ``alpha`` and
    ``slice_s`` — which :meth:`merge` asserts).

    Geometry: bucket ``i = ceil(ln(v) / ln(gamma))`` with
    ``gamma = (1 + alpha) / (1 - alpha)``; the bucket midpoint
    ``2 * gamma^i / (gamma + 1)`` reconstructs any member value within
    relative error ``alpha``. The window is a ring of ``slices`` time
    buckets keyed by absolute wall-clock epoch (``int(t // slice_s)``)
    so expiry drops whole slices and epochs line up across processes.
    Not thread-safe; callers (SLOAccountant) hold their own lock."""

    DEFAULT_ALPHA = 0.01
    DEFAULT_SLICES = 12

    __slots__ = ("alpha", "window_s", "slice_s", "_gamma", "_log_gamma", "_slices")

    def __init__(
        self,
        alpha: float = DEFAULT_ALPHA,
        window_s: float = 300.0,
        slices: int = DEFAULT_SLICES,
    ) -> None:
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.window_s = float(window_s)
        self.slice_s = self.window_s / max(1, int(slices))
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self._gamma)
        # epoch -> [bucket -> count, zero_count, n, sum]
        self._slices: dict[int, list] = {}

    def bucket_of(self, v: float) -> int:
        return math.ceil(math.log(v) / self._log_gamma)

    def value_of(self, bucket: int) -> float:
        return 2.0 * self._gamma ** bucket / (self._gamma + 1.0)

    def add(self, v: float, t: float | None = None) -> None:
        t = time.time() if t is None else t
        epoch = int(t // self.slice_s)
        sl = self._slices.get(epoch)
        if sl is None:
            sl = self._slices[epoch] = [{}, 0, 0, 0.0]
        if v <= _SKETCH_MIN:
            sl[1] += 1
        else:
            b = self.bucket_of(v)
            sl[0][b] = sl[0].get(b, 0) + 1
        sl[2] += 1
        sl[3] += v

    def trim(self, now: float | None = None) -> None:
        """Drop slices whose entire span precedes the window horizon
        (expiry slack: at most one slice length)."""
        now = time.time() if now is None else now
        horizon = now - self.window_s
        for epoch in [
            e for e in self._slices if (e + 1) * self.slice_s <= horizon
        ]:
            del self._slices[epoch]

    def count(self) -> int:
        return sum(sl[2] for sl in self._slices.values())

    def total(self) -> float:
        return sum(sl[3] for sl in self._slices.values())

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile (target rank ``ceil(q*n)``, the same
        rule the repo's bench percentile uses) within relative error
        ``alpha``; 0.0 for an empty sketch."""
        n = self.count()
        if n == 0:
            return 0.0
        target = min(n, max(1, math.ceil(q * n)))
        zeros = sum(sl[1] for sl in self._slices.values())
        if target <= zeros:
            return 0.0
        seen = zeros
        merged: dict[int, int] = {}
        for sl in self._slices.values():
            for b, c in sl[0].items():
                merged[b] = merged.get(b, 0) + c
        for b in sorted(merged):
            seen += merged[b]
            if seen >= target:
                return self.value_of(b)
        return self.value_of(max(merged)) if merged else 0.0

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (cell-wise count sums). Requires
        identical geometry — merging sketches with different ``alpha``
        or ``slice_s`` would mix incompatible bucket meanings."""
        if not math.isclose(other.alpha, self.alpha, rel_tol=1e-9):
            raise ValueError(
                f"cannot merge sketches with alpha {other.alpha} into {self.alpha}"
            )
        if not math.isclose(other.slice_s, self.slice_s, rel_tol=1e-9):
            raise ValueError(
                f"cannot merge sketches with slice_s {other.slice_s} into {self.slice_s}"
            )
        for epoch, osl in other._slices.items():
            sl = self._slices.get(epoch)
            if sl is None:
                sl = self._slices[epoch] = [{}, 0, 0, 0.0]
            for b, c in osl[0].items():
                sl[0][b] = sl[0].get(b, 0) + c
            sl[1] += osl[1]
            sl[2] += osl[2]
            sl[3] += osl[3]
        return self

    def to_wire(self) -> dict:
        """JSON-safe wire form (the /debug/slo?raw=1 payload unit)."""
        return {
            "alpha": self.alpha,
            "window_s": self.window_s,
            "slice_s": self.slice_s,
            "slices": {
                str(epoch): {
                    "b": {str(b): c for b, c in sl[0].items()},
                    "z": sl[1],
                    "n": sl[2],
                    "s": sl[3],
                }
                for epoch, sl in self._slices.items()
            },
        }

    @classmethod
    def from_wire(cls, data: dict) -> "QuantileSketch":
        window_s = float(data["window_s"])
        slice_s = float(data.get("slice_s") or window_s / cls.DEFAULT_SLICES)
        sk = cls(
            alpha=float(data["alpha"]),
            window_s=window_s,
            slices=max(1, round(window_s / slice_s)),
        )
        for epoch, sl in (data.get("slices") or {}).items():
            sk._slices[int(epoch)] = [
                {int(b): int(c) for b, c in (sl.get("b") or {}).items()},
                int(sl.get("z", 0)),
                int(sl.get("n", 0)),
                float(sl.get("s", 0.0)),
            ]
        return sk


class SLOAccountant:
    """Per-queue sliding-window latency percentiles. Two kinds:
    ``time_to_bind`` (streaming arrival -> bind echo) and
    ``queue_wait`` (pod creation -> dispatch). Unlike the cumulative
    histograms in metrics/, these windows answer "is queue Q meeting
    its SLO *right now*" — the admission-lane input (ROADMAP item 1).

    Backed by mergeable :class:`QuantileSketch` rings (one per
    kind × queue) rather than raw sample windows, so N federated
    shards' accountants compose into one cluster-wide percentile
    (obs/fleet); quantiles carry the sketch's declared relative error
    ``alpha`` (default 1%). Queue cardinality is LRU-bounded at
    ``max_queues`` (default 256): a tenant-name churn storm evicts the
    coldest queue, metered on ``slo_evicted_queues_total``, and drops
    its label sets from the slo gauges.

    Always on (a sketch increment is cheap and the SLO surface must
    not go dark when tracing is off); the window length comes from
    ``KBT_SLO_WINDOW_S`` (seconds, default 300)."""

    KINDS = ("time_to_bind", "queue_wait")
    MAX_QUEUES = 256

    def __init__(
        self,
        window_s: float | None = None,
        max_queues: int | None = None,
        alpha: float = QuantileSketch.DEFAULT_ALPHA,
    ) -> None:
        if window_s is None:
            try:
                window_s = float(os.environ.get(SLO_WINDOW_ENV, "") or 300.0)
            except ValueError:
                window_s = 300.0
        self.window_s = window_s
        self.alpha = float(alpha)
        self.max_queues = int(
            max_queues if max_queues is not None else self.MAX_QUEUES
        )
        self._lock = threading.Lock()
        # kind -> queue -> sketch, LRU-ordered (oldest-touched first)
        self._sketches: dict[str, "collections.OrderedDict[str, QuantileSketch]"] = {
            k: collections.OrderedDict() for k in self.KINDS
        }

    def observe(self, kind: str, queue: str, seconds: float) -> None:
        if kind not in self._sketches:
            return
        queue = queue or "default"
        with self._lock:
            per_queue = self._sketches[kind]
            sk = per_queue.get(queue)
            if sk is None:
                sk = per_queue[queue] = QuantileSketch(
                    alpha=self.alpha, window_s=self.window_s
                )
                while len(per_queue) > self.max_queues:
                    evicted, _ = per_queue.popitem(last=False)
                    metrics.register_slo_evicted_queue()
                    metrics.drop_slo_queue(evicted)
            else:
                per_queue.move_to_end(queue)
            sk.add(seconds)

    def reset(self) -> None:
        with self._lock:
            for per_queue in self._sketches.values():
                per_queue.clear()

    def snapshot(self) -> dict:
        """``{kind: {queue: {p50, p90, p99, n, window_s}}}`` over the
        currently in-window observations (n is exact; quantiles within
        relative error ``alpha``)."""
        now = time.time()
        out: dict[str, dict] = {}
        with self._lock:
            for kind, per_queue in self._sketches.items():
                out[kind] = {}
                for queue, sk in per_queue.items():
                    sk.trim(now)
                    n = sk.count()
                    if n == 0:
                        continue
                    stats = {"n": n, "window_s": self.window_s}
                    for label, q in _QUANTILES:
                        stats[label] = sk.quantile(q)
                    out[kind][queue] = stats
        return out

    def raw(self) -> dict:
        """The mergeable wire form (``/debug/slo?raw=1``): serialized
        per-kind × per-queue sketches a fleet aggregator deserializes
        with :meth:`QuantileSketch.from_wire` and merges."""
        now = time.time()
        out: dict = {"alpha": self.alpha, "window_s": self.window_s, "kinds": {}}
        with self._lock:
            for kind, per_queue in self._sketches.items():
                out["kinds"][kind] = {}
                for queue, sk in per_queue.items():
                    sk.trim(now)
                    if sk.count() == 0:
                        continue
                    out["kinds"][kind][queue] = sk.to_wire()
        return out

    def publish(self) -> dict:
        """Push the current window percentiles into the /metrics gauge
        families (kbt..._slo_*) and return the snapshot."""
        snap = self.snapshot()
        for kind, per_queue in snap.items():
            for queue, stats in per_queue.items():
                for label, _ in _QUANTILES:
                    metrics.set_slo_quantile(kind, queue, label, stats[label])
        return snap


slo = SLOAccountant()


# -- configuration -----------------------------------------------------------

_OFF_WORDS = ("", "0", "false", "off", "no")


def configure(spec=None) -> bool:
    """(Re)resolve the tracing switch. ``spec`` is the conf ``trace:``
    value — empty/None defers to ``KBT_TRACE``. Hot-reloadable: the
    scheduler calls this from its conf-reload path every cycle. Also
    re-reads the flight-recorder ring size so a conf push can deepen
    the ring on a live process."""
    global _enabled
    if spec is None or str(spec).strip() == "":
        on = os.environ.get(ENV, "").strip().lower() not in _OFF_WORDS
    else:
        on = str(spec).strip().lower() not in _OFF_WORDS
    try:
        cycles = int(os.environ.get(RECORDER_CYCLES_ENV, "") or recorder.max_traces)
    except ValueError:
        cycles = recorder.max_traces
    if cycles != recorder.max_traces:
        recorder.resize(cycles)
    if on != _enabled:
        log.infof("tracing %s", "enabled" if on else "disabled")
    _enabled = on
    return on


def install_signal_dump() -> bool:
    """Chain a SIGTERM handler that dumps the flight recorder before
    the previous disposition runs. Main-thread only (signal module
    restriction); returns False where it cannot install."""
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _dump_then_chain(signum, frame):
            try:
                recorder.dump(reason="sigterm")
            except Exception:  # noqa: BLE001 - dying anyway; don't mask SIGTERM
                pass
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _dump_then_chain)
        return True
    except (ValueError, OSError, RuntimeError):
        return False


# -- smoke -------------------------------------------------------------------


# The vectorized pipeline, so the smoke exercises the full span tree:
# encode/solve/gang.assign come from xla_allocate, and dispatch goes
# through bind_many -> _do_bind_gang (the conditional per-gang
# transaction whose conflict retries the smoke asserts on). The classic
# `allocate` action binds per task and never takes that path. No
# `trace:` key on purpose — every scheduler (shards AND the arbiter's
# idle loop) defers to the KBT_TRACE env the smoke arms, so their conf
# reloads cannot fight over the module-global switch.
SMOKE_CONF = """
actions: "enqueue, xla_allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""


def check_tree(spans: list[dict]) -> list[str]:
    """Structural violations of a span set (empty = complete tree):
    every non-root parent id resolves inside the same trace, every
    span name is declared, every trace has exactly the roots it
    claims."""
    out: list[str] = []
    by_trace: dict[str, dict[str, dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], {})[s["span_id"]] = s
        if s["name"] not in SPAN_NAMES:
            out.append(f"undeclared span name {s['name']!r}")
    for trace_id, members in by_trace.items():
        for s in members.values():
            if s["parent_id"] and s["parent_id"] not in members:
                out.append(
                    f"span {s['name']} ({s['span_id']}) in trace {trace_id} "
                    f"has dangling parent {s['parent_id']}"
                )
    return out


def smoke(
    shards: int = 2,
    gangs: int = 4,
    members: int = 3,
    nodes: int = 6,
    out_dir: str | None = None,
) -> dict:
    """Tracing end-to-end proof, runnable standalone
    (``python -m kube_batch_tpu.obs``) and from hack/verify.py --obs:

    1. arm tracing plus a one-shot ``federation.stale_assign`` fault
       (the dispatched gang carries snapshot version 0, guaranteeing a
       409 conflict and a winning retry);
    2. run a seeded two-shard federated run over live LoopbackBackends
       against a real SchedulerServer store arbiter — the full wire
       path, headers and all;
    3. assert the collected spans form a complete parent-child tree,
       that a ``gang.bind`` span carries a conflict event, and that a
       ``store.bind`` span recorded on the arbiter side joined a
       scheduler-originated trace (cross-process propagation);
    4. seed one deliberately unfittable gang and assert its explain
       record (obs/explain, armed alongside tracing) lands in the
       forensics registry, rides an ``explain`` span in the flight
       recorder, and that dispatched gangs' journal intents carry
       ``explain`` payloads;
    5. export the Chrome trace-event file + JSONL and return the paths.
    """
    import json as _json
    import threading as _threading

    from kube_batch_tpu import faults
    from kube_batch_tpu.cache import LoopbackBackend
    from kube_batch_tpu.federation import FederatedCache, _seed_world, fsck
    from kube_batch_tpu.obs import explain as _explain
    from kube_batch_tpu.recovery.journal import WriteIntentJournal
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer
    from kube_batch_tpu.testing import build_pod, build_pod_group, build_resource_list

    # Arm through the env var, not configure() directly: every
    # scheduler cycle re-resolves the switch from conf/env (hot
    # reload), so a bare configure("on") would be undone by the first
    # _load_conf of a conf whose trace: key is empty.
    prev_env = os.environ.get(ENV)
    os.environ[ENV] = "1"
    prev_explain = os.environ.get(_explain.ENV)
    os.environ[_explain.ENV] = "1"
    # a 12-pod world is far below xla_allocate's device-size floor;
    # force the device path or the smoke would fall back to serial
    # allocate and never take the traced encode/solve/bind_many pipeline
    prev_floor = os.environ.get("KBT_MIN_DEVICE_PAIRS")
    os.environ["KBT_MIN_DEVICE_PAIRS"] = "0"
    configure()
    _explain.configure()
    recorder.clear()
    slo.reset()
    _explain.records.clear()
    faults.registry.configure("federation.stale_assign:1:1")

    total = gangs * members
    out_dir = out_dir or os.path.join(tempfile.gettempdir(), "kbt-obs-smoke")
    os.makedirs(out_dir, exist_ok=True)
    server = SchedulerServer(
        scheduler_name="obs-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()
    backends: list = []
    scheds: list = []
    journal_paths: list[str] = []
    stop = _threading.Event()
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as fh:
        fh.write(SMOKE_CONF)
        conf_path = fh.name
    try:
        _seed_world(server.store, gangs, members, nodes)
        # One deliberately unfittable gang (cpu far beyond any node):
        # the run must leave it pending with an explain record whose
        # dominant reason is the resources plane.
        server.store.create_pod_group(build_pod_group("fg-stuck", min_member=1))
        server.store.create_pod(
            build_pod(
                name="fg-stuck-p0",
                group_name="fg-stuck",
                req=build_resource_list(cpu=999, memory="512Mi"),
            )
        )
        base = f"http://127.0.0.1:{server.listen_port}"
        for i in range(shards):
            backend = LoopbackBackend(base)
            jpath = os.path.join(out_dir, f"smoke-journal-{i}.jsonl")
            if os.path.exists(jpath):
                os.unlink(jpath)
            journal_paths.append(jpath)
            cache = FederatedCache(
                backend, shard=i, shards=shards, shard_key="gang",
                staleness_fn=backend.snapshot_age,
                journal=WriteIntentJournal(jpath),
            )
            cache.run()
            backend.start(period=0.02)
            backends.append(backend)
            sched = Scheduler(
                cache, scheduler_conf=conf_path, schedule_period=0.05
            )
            t = _threading.Thread(
                target=sched.run, args=(stop,), name=f"kb-obs-{i}", daemon=True
            )
            t.start()
            scheds.append((sched, t))
        # the stuck pod never binds, so wait on the bound COUNT, not on
        # every pod carrying a node (the federation helper's criterion)
        from kube_batch_tpu.cache.store import PODS as _PODS

        deadline = time.monotonic() + 60.0
        all_bound = False
        while time.monotonic() < deadline:
            pods = server.store.list(_PODS)
            if sum(1 for p in pods if p.node_name) >= total:
                all_bound = True
                break
            time.sleep(0.005)
    finally:
        stop.set()
        for _, t in scheds:
            t.join(timeout=10.0)
        for backend in backends:
            backend.stop()
        for sched, _ in scheds:
            sched.cache.stop()
        server.stop()
        faults.registry.disarm("federation.stale_assign")
        os.unlink(conf_path)

    spans = recorder.spans()
    violations = check_tree(spans)
    names = collections.Counter(s["name"] for s in spans)
    conflict_binds = [
        s for s in spans
        if s["name"] == "gang.bind"
        and any(ev["name"] == "conflict" for ev in s["events"])
    ]
    scheduler_traces = {s["trace_id"] for s in spans if s["name"] == "cycle"}
    joined_remote = [
        s for s in spans
        if s["name"] == "store.bind" and s["trace_id"] in scheduler_traces
    ]

    # Explain assertions (obs/explain): the unfittable gang's record is
    # in the registry with the designed dominant reason, an explain span
    # carrying unschedulable forensics rode the flight recorder, and at
    # least one dispatched gang's journal intent carries the explain
    # payload (the labeled-decision channel).
    stuck_rec = _explain.records.get("default/fg-stuck")
    explain_spans = [
        s for s in spans
        if s["name"] == "explain" and s["attrs"].get("unschedulable", 0) > 0
    ]
    journaled_explains = 0
    for jpath in journal_paths:
        try:
            with open(jpath, encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = _json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("rec") == "intent" and "explain" in rec:
                        journaled_explains += 1
        except OSError:
            pass

    jsonl_path = export_jsonl(spans, os.path.join(out_dir, "smoke.jsonl"))
    chrome_path = export_chrome(spans, os.path.join(out_dir, "smoke.trace.json"))

    if prev_env is None:
        os.environ.pop(ENV, None)
    else:
        os.environ[ENV] = prev_env
    if prev_explain is None:
        os.environ.pop(_explain.ENV, None)
    else:
        os.environ[_explain.ENV] = prev_explain
    if prev_floor is None:
        os.environ.pop("KBT_MIN_DEVICE_PAIRS", None)
    else:
        os.environ["KBT_MIN_DEVICE_PAIRS"] = prev_floor
    configure()
    _explain.configure()
    result = {
        "shards": shards,
        "pods": total,
        "all_bound": all_bound,
        "spans": len(spans),
        "span_names": dict(sorted(names.items())),
        "tree_violations": violations,
        "conflicted_gang_binds": len(conflict_binds),
        "remote_spans_joined": len(joined_remote),
        "fsck_violations": fsck(server.store),
        "slo": slo.snapshot(),
        "jsonl": jsonl_path,
        "chrome_trace": chrome_path,
        "stuck_gang_reason": stuck_rec["reason"] if stuck_rec else None,
        "explain_spans": len(explain_spans),
        "journaled_explains": journaled_explains,
    }
    result["ok"] = bool(
        all_bound
        and not violations
        and not result["fsck_violations"]
        and names.get("cycle", 0) > 0
        and names.get("solve", 0) > 0
        and names.get("gang.bind", 0) > 0
        and conflict_binds
        and joined_remote
        and stuck_rec is not None
        and stuck_rec["verdict"] == "unschedulable"
        and explain_spans
        and journaled_explains > 0
    )
    return result


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="tracing smoke: seeded two-shard federated run, span "
        "tree checked, Chrome trace exported"
    )
    parser.add_argument("--shards", type=int, default=2)
    parser.add_argument("--gangs", type=int, default=4)
    parser.add_argument("--members", type=int, default=3)
    parser.add_argument("--out", default=None, help="export directory")
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    result = smoke(
        shards=args.shards, gangs=args.gangs, members=args.members,
        out_dir=args.out,
    )
    if args.json:
        print(json.dumps(result, sort_keys=True, default=str))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"obs smoke: {status} ({result['spans']} spans, "
            f"{result['conflicted_gang_binds']} conflicted binds, "
            f"{result['remote_spans_joined']} remote spans joined, "
            f"tree={'complete' if not result['tree_violations'] else result['tree_violations']}, "
            f"chrome={result['chrome_trace']})"
        )
    return 0 if result["ok"] else 1


configure()
