"""CLI for the domain-aware static analysis suite.

Usage::

    python -m kube_batch_tpu.analysis [--json] [--strict]
                                      [--baseline PATH] [--no-baseline]
                                      [--repo PATH] [--explain CODE]
                                      [--prune]

Exit codes: 0 clean (every finding suppressed with a reason), 1 findings
or baseline problems, 2 usage error. ``--strict`` additionally fails on
stale baseline entries (KBT-B002), so the committed baseline can only
shrink. ``--explain CODE`` prints what a code protects and how to fix
it, then exits. ``--prune`` rewrites the baseline in place with the
stale entries removed (verbatim preamble/reasons/order preserved), the
mechanical half of the only-shrinks policy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import textwrap

from kube_batch_tpu.analysis import (
    CODES,
    apply_baseline,
    load_baseline,
    render_baseline,
    repo_root,
    run_suite,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.analysis",
        description="lock-discipline / JAX-hazard / registry-consistency / "
        "snapshot-escape analyzers (stdlib-only)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable summary")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries")
    p.add_argument("--baseline", default=None,
                   help="suppression file (default: <repo>/hack/lint-baseline.toml)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, apply no suppressions")
    p.add_argument("--repo", default=None, help="tree to analyze (default: auto)")
    p.add_argument("--explain", metavar="CODE", default=None,
                   help="describe a finding code and exit")
    p.add_argument("--prune", action="store_true",
                   help="rewrite the baseline dropping stale (KBT-B002) "
                   "entries; reasons, ordering and the preamble comment "
                   "block are preserved verbatim")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.explain:
        code = args.explain.upper()
        if code not in CODES:
            print(f"unknown code {code!r}; known: {', '.join(sorted(CODES))}")
            return 2
        title, body = CODES[code]
        print(f"{code}: {title}\n")
        print(textwrap.fill(body, width=78))
        return 0

    repo = os.path.abspath(args.repo) if args.repo else repo_root()
    findings = run_suite(repo)

    if args.no_baseline:
        kept, suppressed, stale, baseline_errors = findings, [], [], []
        bl_path = None
    else:
        bl_path = args.baseline or os.path.join(repo, "hack", "lint-baseline.toml")
        bl = load_baseline(bl_path, repo)
        kept, suppressed, stale = apply_baseline(findings, bl)
        baseline_errors = bl.errors

    if args.prune:
        if args.no_baseline:
            print("--prune is meaningless with --no-baseline")
            return 2
        # Keep every entry that matched a finding this run, plus
        # incomplete entries (they fail as KBT-B001 — deleting them would
        # hide the error instead of fixing it). Drop exactly the stale set.
        keep = [s for s in bl.suppressions
                if s.hits > 0 or not (s.code and s.path)]
        dropped = [s for s in bl.suppressions if s not in keep]
        if dropped:
            with open(bl_path, "w", encoding="utf-8") as fh:
                fh.write(render_baseline(bl, keep))
        for s in dropped:
            print(f"pruned: {s.code} at {s.path}"
                  + (f" ({s.symbol})" if s.symbol else ""))
        print(f"prune: {len(dropped)} stale entr{'y' if len(dropped) == 1 else 'ies'} "
              f"dropped, {len(keep)} kept")
        stale = []  # just removed; don't also fail on them

    failing = list(kept) + list(baseline_errors)
    if args.strict:
        failing += stale

    if args.json:
        print(json.dumps({
            "ok": not failing,
            "repo": repo,
            "findings": [f.__dict__ for f in kept],
            "baseline_errors": [f.__dict__ for f in baseline_errors],
            "stale": [f.__dict__ for f in stale],
            "suppressed": len(suppressed),
            "counts": _counts(kept),
        }, sort_keys=True))
    else:
        for f in sorted(failing, key=lambda f: (f.path, f.line, f.code)):
            print(f.render())
        if stale and not args.strict:
            for f in stale:
                print(f"note: {f.render()}")
        tail = (
            f"analysis: {len(kept)} finding(s), "
            f"{len(baseline_errors)} baseline error(s), "
            f"{len(stale)} stale suppression(s), "
            f"{len(suppressed)} suppressed"
        )
        print(tail)
    return 1 if failing else 0


def _counts(findings) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.code] = out.get(f.code, 0) + 1
    return out


if __name__ == "__main__":
    sys.exit(main())
