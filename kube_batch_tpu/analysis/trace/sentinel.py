"""Compile-cache sentinel: count XLA compiles inside a region.

A silent recompile in the warm scheduling loop is the single most
expensive latent regression this repo can grow — one retrace of the
50k-task program costs more wall-clock than a thousand warm cycles —
and it is invisible to every source-level lint: the code that triggers
it (a host int that should have been a device scalar, a dict key added
per cycle, a shape bucket that stopped being stable) looks identical to
the code that doesn't. jax publishes a monitoring event per backend
compile; :class:`CompileSentinel` turns that into an assertable budget:

    with CompileSentinel("warm cycles", budget=0) as cs:
        for _ in range(3):
            solver.solve(arrays)
    # raises CompileBudgetExceeded if anything recompiled

Used three ways (ISSUE 7): tier-1 pins zero recompiles across 3 warm
cycles of the XLA twin and the mesh rungs; ``bench.py`` asserts per-row
budgets (the measured repeats of a warmed row must not compile); and
the seeded recompile-storm fixture in the tests proves the counter
actually sees shape-keyed jit churn.

The listener is global and lazily registered (jax keeps listeners for
the process lifetime; there is no unregister API), so sentinels can
nest and interleave — each one reads deltas of one shared counter.
Counts are process-wide: don't run device work on side threads inside
a sentinel region you want to be exact.
"""

from __future__ import annotations

import threading

__all__ = ["CompileBudgetExceeded", "CompileSentinel", "compile_count"]

# The monitoring key jax records once per backend_compile (cache misses
# only — warm cache hits never reach the backend).
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_mu = threading.Lock()
_count = 0  # compiles seen since the listener registered; guarded by _mu
_registered = False  # guarded by _mu


def _on_event(event: str, duration: float, **kw) -> None:
    global _count
    if event == _COMPILE_EVENT:
        with _mu:
            _count += 1


def _ensure_listener() -> None:
    global _registered
    with _mu:
        if _registered:
            return
        _registered = True
    # Import inside: the analysis package proper stays stdlib-only; only
    # the trace half may pull jax in.
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_event)


def compile_count() -> int:
    """Process-wide compile count since the first sentinel was armed."""
    _ensure_listener()
    with _mu:
        return _count


class CompileBudgetExceeded(AssertionError):
    """A sentinel region compiled more programs than its budget allows."""


class CompileSentinel:
    """Context manager counting jit cache misses in its region.

    ``budget=None`` observes only (read ``.compiles`` after exit);
    ``budget=N`` raises :class:`CompileBudgetExceeded` on exit when the
    region compiled more than N programs. An exception already in
    flight wins — the sentinel never masks it.
    """

    def __init__(self, label: str = "", budget: int | None = None) -> None:
        self.label = label
        self.budget = budget
        self.compiles = 0
        self._start = 0

    def __enter__(self) -> "CompileSentinel":
        _ensure_listener()
        self._start = compile_count()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.compiles = compile_count() - self._start
        if exc_type is None and self.budget is not None and self.compiles > self.budget:
            what = f" [{self.label}]" if self.label else ""
            raise CompileBudgetExceeded(
                f"compile sentinel{what}: {self.compiles} compiles in a "
                f"region budgeted for {self.budget} — a warm path is "
                "retracing (new shape bucket, dict key churn, or a "
                "python value that should be a device scalar)"
            )
        return False
