"""CLI for the trace-level program auditor (KBT-P0xx).

Usage::

    JAX_PLATFORMS=cpu python -m kube_batch_tpu.analysis.trace \
        [--json] [--strict] [--baseline PATH] [--no-baseline]
        [--explain CODE] [--mesh 1,2,4,8] [--const-bytes N]
        [--no-transfer-check]

Same exit-code contract and baseline machinery as the AST suite
(``python -m kube_batch_tpu.analysis``), but a separate baseline file
(default ``<repo>/hack/trace-baseline.toml``) — the two gates run
independently, so sharing one file would mark each other's suppressions
stale. Unlike the AST suite this imports jax and traces the real solver
programs; run it under ``JAX_PLATFORMS=cpu`` in CI. The process forces
``--xla_force_host_platform_device_count=8`` so the mesh rungs have
devices to trace against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import textwrap

from kube_batch_tpu.analysis import (
    CODES,
    apply_baseline,
    load_baseline,
    repo_root,
)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.analysis.trace",
        description="jaxpr-level auditor for the solver entry points "
        "(callbacks, f64 leaks, captured constants, donation, "
        "cross-tier signature drift)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable summary")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale baseline entries")
    p.add_argument("--baseline", default=None,
                   help="suppression file (default: <repo>/hack/trace-baseline.toml)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, apply no suppressions")
    p.add_argument("--repo", default=None,
                   help="repo root for the baseline path (default: auto)")
    p.add_argument("--explain", metavar="CODE", default=None,
                   help="describe a finding code and exit")
    p.add_argument("--mesh", default="1,2,4,8",
                   help="comma-separated mesh sizes to trace (default: 1,2,4,8)")
    p.add_argument("--const-bytes", type=int, default=None,
                   help="KBT-P003 captured-constant threshold (default: 1 MiB)")
    p.add_argument("--no-transfer-check", action="store_true",
                   help="skip the runtime transfer_guard warm-cycle check "
                   "(no compile, trace-only)")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.explain:
        code = args.explain.upper()
        if code not in CODES:
            print(f"unknown code {code!r}; known: {', '.join(sorted(CODES))}")
            return 2
        title, body = CODES[code]
        print(f"{code}: {title}\n")
        print(textwrap.fill(body, width=78))
        return 0

    # The mesh rungs need 8 host devices; set before jax loads (jax is
    # imported lazily inside run_trace_audit).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    from kube_batch_tpu.analysis.trace import (
        CONST_BYTES_DEFAULT,
        run_trace_audit,
    )

    try:
        mesh_sizes = tuple(int(x) for x in args.mesh.split(",") if x.strip())
    except ValueError:
        print(f"bad --mesh value {args.mesh!r}")
        return 2

    findings, info = run_trace_audit(
        mesh_sizes=mesh_sizes,
        const_bytes=args.const_bytes or CONST_BYTES_DEFAULT,
        transfer_check=not args.no_transfer_check,
    )

    repo = os.path.abspath(args.repo) if args.repo else repo_root()
    if args.no_baseline:
        kept, suppressed, stale, baseline_errors = findings, [], [], []
    else:
        bl_path = args.baseline or os.path.join(repo, "hack", "trace-baseline.toml")
        bl = load_baseline(bl_path, repo)
        kept, suppressed, stale = apply_baseline(findings, bl)
        baseline_errors = bl.errors

    failing = list(kept) + list(baseline_errors)
    if args.strict:
        failing += stale

    if args.json:
        print(json.dumps({
            "ok": not failing,
            "repo": repo,
            "findings": [f.__dict__ for f in kept],
            "baseline_errors": [f.__dict__ for f in baseline_errors],
            "stale": [f.__dict__ for f in stale],
            "suppressed": len(suppressed),
            "counts": _counts(kept),
            "entries": info["entries"],
            "mesh_sizes": info["mesh_sizes"],
        }, sort_keys=True))
    else:
        for f in sorted(failing, key=lambda f: (f.path, f.line, f.code)):
            print(f.render())
        if stale and not args.strict:
            for f in stale:
                print(f"note: {f.render()}")
        print(
            f"trace audit: {len(info['entries'])} program(s) traced "
            f"(mesh {info['mesh_sizes']}), {len(kept)} finding(s), "
            f"{len(baseline_errors)} baseline error(s), "
            f"{len(stale)} stale suppression(s), {len(suppressed)} suppressed"
        )
    return 1 if failing else 0


def _counts(findings) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.code] = out.get(f.code, 0) + 1
    return out


if __name__ == "__main__":
    sys.exit(main())
