"""Trace-level program auditor: the KBT-P0xx code family.

The source-AST suite (``kube_batch_tpu.analysis``) sees what the code
*says*; this sibling sees what the compiler was *told*. It traces the
real solver entry points on abstract inputs — no FLOPs, no device
buffers, just jaxprs — and walks the resulting programs for the failure
modes that sink a warm scheduling loop but are invisible syntactically:

- **KBT-P001** — a host callback / transfer primitive inside a traced
  solver program (``pure_callback``, ``io_callback``, debug prints...),
  plus a runtime half: one warm cycle of the XLA twin is replayed under
  ``jax.transfer_guard("disallow")`` to catch implicit host->device
  transfers that only exist at run time (a numpy array smuggled in per
  cycle).
- **KBT-P002** — f64 avals appearing in a program whose inputs are all
  <= f32. Traced under scoped x64 (``testing.x64_enabled``) so
  the default-config dtype demotion cannot mask the leak; this is the
  trace-level closure of the syntactic KBT-J002.
- **KBT-P003** — large host constants captured into the program (the
  embedded 400k-row table footgun): any const above ``const_bytes``
  (default 1 MiB) rides every compile and lives in every executable.
- **KBT-P004** — donation declared but not honored: the arena's
  row-scatter declares ``donate_argnums`` so warm updates are in-place;
  if XLA cannot alias (shape/dtype mismatch, or a host array slipped
  in) it silently copies and device memory doubles. Detected by
  lowering+compiling the donated program and catching jax's
  "donated buffers were not usable" warning.
- **KBT-P005** — cross-tier program-signature drift: the XLA twin, the
  GSPMD sharded rung, and the blocked mesh-Pallas rung all speak the
  SolveState resume protocol; their output shapes+dtypes must be
  field-for-field identical or the action's pause/resume hybrid
  diverges structurally between tiers.

Entry points traced (mirroring ``actions/xla_allocate`` dispatch):
``ops.kernels`` fresh+resume (the XLA twin), ``parallel.sharded`` at
mesh {1,2,4,8}, ``parallel.sharded_pallas`` at mesh {1,2,4,8} (jnp
block backend — same program geometry as the mosaic one) plus its
K-deep batched-exchange variant at the largest mesh, the fused
``ops.pallas_solve`` program, and the encode-cache arena row-scatter
(donation checked for both ping-pong banks).

Findings flow through the same ``Finding``/baseline machinery as the
AST suite (own CLI: ``python -m kube_batch_tpu.analysis.trace``, own
baseline ``hack/trace-baseline.toml`` so the two gates never mark each
other's suppressions stale). The runtime sibling
(:mod:`kube_batch_tpu.analysis.trace.sentinel`) pins compile budgets
on the same entry points in tier-1 and bench.

jax is imported lazily inside functions — importing this module (e.g.
for the CLI's ``--explain``) stays cheap and device-free.
"""

from __future__ import annotations

import warnings
from typing import Optional

import numpy as np

from kube_batch_tpu.analysis import Finding

__all__ = [
    "CONST_BYTES_DEFAULT",
    "MESH_SIZES_DEFAULT",
    "build_snapshot",
    "check_callbacks",
    "check_donation",
    "check_f64",
    "check_large_consts",
    "check_signature_drift",
    "iter_eqns",
    "run_trace_audit",
    "state_signature",
]

CONST_BYTES_DEFAULT = 1 << 20  # 1 MiB of captured host data per program
MESH_SIZES_DEFAULT = (1, 2, 4, 8)

# Primitives that round-trip to the host from inside a traced program.
# Anything here inside the solve loop serializes the device pipeline on
# the python thread — the exact cost the always-warm loop exists to
# avoid.
_CALLBACK_PRIMS = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "outside_call",  # legacy host_callback
        "host_callback_call",
    }
)

# Entry-point anchor paths (repo-relative) for findings.
_PATHS = {
    "xla_twin": "kube_batch_tpu/ops/kernels.py",
    "sharded": "kube_batch_tpu/parallel/sharded.py",
    "mesh_pallas": "kube_batch_tpu/parallel/sharded_pallas.py",
    "mesh_pallas_batched": "kube_batch_tpu/parallel/sharded_pallas.py",
    "pallas_solve": "kube_batch_tpu/ops/pallas_solve.py",
    "arena_scatter": "kube_batch_tpu/ops/encode_cache.py",
}


# -- jaxpr plumbing ----------------------------------------------------------


def _inner_jaxprs(value):
    """Jaxpr objects hiding in one eqn param value (ClosedJaxpr, Jaxpr,
    or lists of either — cond branches, scan bodies, pjit calls)."""
    out = []
    vals = value if isinstance(value, (list, tuple)) else [value]
    for v in vals:
        inner = getattr(v, "jaxpr", v)  # ClosedJaxpr -> Jaxpr
        if hasattr(inner, "eqns"):
            out.append(v)
    return out


def iter_eqns(closed):
    """Every eqn in a ClosedJaxpr, recursing into sub-jaxprs (pjit
    bodies, while/cond/scan branches) — depth-first, deduplicated."""
    seen: set[int] = set()
    stack = [closed.jaxpr if hasattr(closed, "jaxpr") else closed]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            yield eqn
            for param in eqn.params.values():
                for sub in _inner_jaxprs(param):
                    stack.append(getattr(sub, "jaxpr", sub))


def _all_consts(closed):
    """Constants captured anywhere in the program: the top ClosedJaxpr's
    consts plus every nested ClosedJaxpr's (pjit bodies carry their
    own)."""
    out = list(getattr(closed, "consts", ()))
    seen: set[int] = set()
    stack = [closed]
    while stack:
        c = stack.pop()
        j = getattr(c, "jaxpr", c)
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            for param in eqn.params.values():
                for sub in _inner_jaxprs(param):
                    if hasattr(sub, "consts"):
                        out.extend(sub.consts)
                    stack.append(sub)
    return out


def _avals_of(tree) -> list:
    import jax

    return [x for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "dtype")]


# -- the five checks (fixture tests call these directly on tiny jaxprs) ------


def check_callbacks(closed, entry: str, path: str) -> list[Finding]:
    """KBT-P001 (static half): callback primitives inside the program."""
    findings = []
    seen: set[str] = set()
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in _CALLBACK_PRIMS and name not in seen:
            seen.add(name)
            findings.append(
                Finding(
                    path=path,
                    line=1,
                    code="KBT-P001",
                    message=(
                        f"traced program for {entry!r} contains host "
                        f"callback primitive '{name}' — every loop "
                        "iteration round-trips to python"
                    ),
                    symbol=f"{entry}.callback.{name}",
                )
            )
    return findings


def check_f64(closed, entry: str, path: str) -> list[Finding]:
    """KBT-P002: f64 values computed by a program whose inputs are all
    <= f32 (run on a trace taken under scoped x64, where nothing demotes
    the leak away)."""
    f64 = np.dtype(np.float64)
    for v in getattr(closed.jaxpr, "invars", ()):
        if getattr(v.aval, "dtype", None) == f64:
            return []  # deliberate f64 inputs: the whole program is f64
    for const in _all_consts(closed):
        if getattr(const, "dtype", None) == f64:
            return []
    hits: dict[str, int] = {}
    for eqn in iter_eqns(closed):
        for v in eqn.outvars:
            if getattr(v.aval, "dtype", None) == f64:
                hits[eqn.primitive.name] = hits.get(eqn.primitive.name, 0) + 1
    if not hits:
        return []
    prims = ", ".join(f"{k}×{n}" for k, n in sorted(hits.items()))
    return [
        Finding(
            path=path,
            line=1,
            code="KBT-P002",
            message=(
                f"traced program for {entry!r} upcasts to f64 with f32 "
                f"inputs ({prims}) — pin the dtype at the leak site "
                "(python float literals and default-dtype factories take "
                "the x64 default)"
            ),
            symbol=f"{entry}.f64",
        )
    ]


def check_large_consts(
    closed, entry: str, path: str, const_bytes: int = CONST_BYTES_DEFAULT
) -> list[Finding]:
    """KBT-P003: host constants baked into the program above the size
    threshold."""
    findings = []
    for const in _all_consts(closed):
        nbytes = getattr(const, "nbytes", 0)
        if nbytes > const_bytes:
            shape = tuple(getattr(const, "shape", ()))
            dtype = getattr(const, "dtype", "?")
            findings.append(
                Finding(
                    path=path,
                    line=1,
                    code="KBT-P003",
                    message=(
                        f"traced program for {entry!r} captures a "
                        f"{nbytes >> 10} KiB host constant "
                        f"(shape {shape}, {dtype}) — pass it as an "
                        "argument so it is transferred once, not baked "
                        "into every compile"
                    ),
                    symbol=f"{entry}.const.{'x'.join(map(str, shape))}",
                )
            )
    return findings


def check_donation(fn, args, entry: str, path: str) -> list[Finding]:
    """KBT-P004: lower+compile a jit with declared donation and catch
    jax's 'donated buffers were not usable' warning. ``args`` are
    ShapeDtypeStructs (or concrete arrays), so nothing executes."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fn.lower(*args).compile()
    bad = [
        str(w.message)
        for w in caught
        if "donated buffers" in str(w.message).lower()
    ]
    if not bad:
        return []
    return [
        Finding(
            path=path,
            line=1,
            code="KBT-P004",
            message=(
                f"declared donation on {entry!r} is not honored "
                f"({bad[0].splitlines()[0]}) — XLA copies instead of "
                "aliasing and device memory for the buffer doubles"
            ),
            symbol=f"{entry}.donation",
        )
    ]


def state_signature(state) -> dict:
    """SolveState (of avals or arrays) -> {field: (shape, dtype)} for
    the cross-tier drift check."""
    sig = {}
    for field in state._fields:
        v = getattr(state, field)
        sig[field] = (tuple(np.shape(v)), str(np.asarray(v).dtype)
                      if not hasattr(v, "dtype") else str(v.dtype))
    return sig


def check_signature_drift(
    ref_sig: dict, sig: dict, ref_entry: str, entry: str, path: str
) -> list[Finding]:
    """KBT-P005: field-for-field shape+dtype equality of two tiers'
    SolveState outputs."""
    findings = []
    for field in sorted(set(ref_sig) | set(sig)):
        a, b = ref_sig.get(field), sig.get(field)
        if a != b:
            findings.append(
                Finding(
                    path=path,
                    line=1,
                    code="KBT-P005",
                    message=(
                        f"SolveState.{field} drifts between {ref_entry!r} "
                        f"{a} and {entry!r} {b} — the tiers no longer "
                        "speak the same resume protocol"
                    ),
                    symbol=f"{entry}.drift.{field}",
                )
            )
    return findings


# -- snapshot + entry-point registry -----------------------------------------


def build_snapshot(n_tasks: int = 64, n_nodes: int = 24) -> dict:
    """Encode a small seeded world into the exact solver input dict
    ``actions/xla_allocate`` builds: f32 arrays, nodeorder weight
    scalars folded in, host-only metadata dropped. The node bucket pads
    to 128, so every mesh size in {1,2,4,8} divides it."""
    from kube_batch_tpu import actions, plugins  # noqa: F401  (registries)
    from kube_batch_tpu.actions.xla_allocate import _nodeorder_weights
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, open_session
    from kube_batch_tpu.models import multi_queue
    from kube_batch_tpu.ops.encode import encode_session
    from kube_batch_tpu.testing import FakeCache

    conf = parse_scheduler_conf(
        "tiers:\n"
        "- plugins:\n"
        "  - name: priority\n"
        "  - name: gang\n"
        "  - name: conformance\n"
        "- plugins:\n"
        "  - name: drf\n"
        "  - name: predicates\n"
        "  - name: proportion\n"
        "  - name: nodeorder\n"
    )
    ssn = open_session(FakeCache(multi_queue(n_tasks, n_nodes)), conf.tiers)
    try:
        enc = encode_session(
            ssn.jobs,
            ssn.nodes,
            ssn.queues,
            dtype=np.float32,
            drf=ssn.plugins.get("drf"),
            proportion=ssn.plugins.get("proportion"),
            session=ssn,
        )
        w_least, w_balanced, w_aff, w_podaff = _nodeorder_weights(ssn)
    finally:
        close_session(ssn)
    arrays = {k: np.asarray(v) for k, v in enc.arrays.items()}
    arrays.pop("task_created", None)  # host-only replay metadata
    arrays["w_least"] = np.float32(w_least)
    arrays["w_balanced"] = np.float32(w_balanced)
    arrays["w_aff"] = np.float32(w_aff)
    arrays["w_podaff"] = np.float32(w_podaff)
    return arrays


def _audit_capture(findings, closed, entry, path, const_bytes):
    findings += check_callbacks(closed, entry, path)
    findings += check_large_consts(closed, entry, path, const_bytes)


def run_trace_audit(
    mesh_sizes: tuple = MESH_SIZES_DEFAULT,
    const_bytes: int = CONST_BYTES_DEFAULT,
    transfer_check: bool = True,
) -> tuple[list[Finding], dict]:
    """Trace every entry point and run the P001–P005 checks.

    Returns ``(findings, info)``; ``info`` carries the audited entry
    list and per-entry jaxpr sizes for the CLI's ``--json``.
    """
    import jax

    from kube_batch_tpu.ops.kernels import _solve_fresh, _solve_resume
    from kube_batch_tpu.testing import x64_enabled

    arrays = build_snapshot()
    avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in arrays.items()}
    findings: list[Finding] = []
    sigs: dict[str, dict] = {}
    entries: dict[str, int] = {}

    def capture(entry, path, trace_fn, *t_args, x64: bool = False):
        closed = jax.make_jaxpr(trace_fn)(*t_args)
        entries[entry] = sum(1 for _ in iter_eqns(closed))
        _audit_capture(findings, closed, entry, path, const_bytes)
        if x64:
            # the sanctioned x64 flip (jax.experimental.enable_x64 is
            # deprecated; see testing.x64_enabled)
            with x64_enabled():
                closed64 = jax.make_jaxpr(trace_fn)(*t_args)
            findings.extend(check_f64(closed64, entry, path))
        return closed

    # 1. XLA twin (fresh + resume): the single-chip reference program.
    twin_fresh = lambda a: _solve_fresh(a, True, True)  # noqa: E731
    capture("xla_twin", _PATHS["xla_twin"], twin_fresh, avals, x64=True)
    st_avals = jax.eval_shape(twin_fresh, avals)
    sigs["xla_twin"] = state_signature(st_avals)
    capture(
        "xla_twin.resume",
        _PATHS["xla_twin"],
        lambda a, s: _solve_resume(a, s, True, True),
        avals,
        st_avals,
        x64=True,
    )

    # 2. GSPMD sharded rung per mesh size.
    from kube_batch_tpu.parallel.sharded import AXIS_NAME, _sharded_programs

    devices = tuple(jax.devices())
    usable = [m for m in mesh_sizes if m <= len(devices)]
    for m in usable:
        fresh, _resume = _sharded_programs(
            devices[:m], AXIS_NAME, frozenset(arrays), True, True
        )
        capture(f"sharded@{m}", _PATHS["sharded"], fresh, avals, x64=(m == usable[0]))
        sigs[f"sharded@{m}"] = state_signature(jax.eval_shape(fresh, avals))

    # 3. Blocked mesh-Pallas rung per mesh size (jnp block backend: same
    # fold geometry and output protocol as the mosaic kernel, traceable
    # off-TPU).
    from kube_batch_tpu.parallel.sharded import make_mesh
    from kube_batch_tpu.parallel.sharded_pallas import ShardedPallasSolver

    for m in usable:
        sp = ShardedPallasSolver(arrays, make_mesh(m), True, True, block_impl="jnp")
        a_call = dict(sp.a)
        a_call["_tports"] = sp._tports
        a_avals = {
            k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
            for k, v in a_call.items()
        }
        s_avals = {
            k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
            for k, v in sp._statics.items()
        }
        capture(
            f"mesh_pallas@{m}",
            _PATHS["mesh_pallas"],
            sp._fresh,
            a_avals,
            s_avals,
            x64=(m == usable[0]),
        )
        sigs[f"mesh_pallas@{m}"] = state_signature(
            jax.eval_shape(sp._fresh, a_avals, s_avals)
        )

    # 3b. The K-deep batched-exchange program (KBT_EXCHANGE_BATCH under
    # KBT_PIPELINE): same SPMD geometry, but the gang loop speculates K
    # iterations per shard and ships one [K, record] all-gather per
    # round. Audited at the largest usable mesh — the size the batching
    # exists for. The program returns (SolveState, n_batched); the
    # drift check pins the state element field-for-field against the
    # twin, so the batched rung cannot fork the resume protocol.
    mb = usable[-1]
    spb = ShardedPallasSolver(
        arrays, make_mesh(mb), True, True, block_impl="jnp", exchange_batch=4
    )
    ab_call = dict(spb.a)
    ab_call["_tports"] = spb._tports
    ab_avals = {
        k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
        for k, v in ab_call.items()
    }
    sb_avals = {
        k: jax.ShapeDtypeStruct(np.asarray(v).shape, np.asarray(v).dtype)
        for k, v in spb._statics.items()
    }
    capture(
        f"mesh_pallas_batched@{mb}",
        _PATHS["mesh_pallas_batched"],
        spb._fresh,
        ab_avals,
        sb_avals,
    )
    sigs[f"mesh_pallas_batched@{mb}"] = state_signature(
        jax.eval_shape(spb._fresh, ab_avals, sb_avals)[0]
    )

    # 4. Fused single-chip Pallas program (interpret build traces the
    # same jaxpr structure the mosaic build lowers).
    from kube_batch_tpu.ops.pallas_solve import PallasSolver

    ps = PallasSolver(arrays, True, True, interpret=True)
    t_args = tuple(
        jax.ShapeDtypeStruct(np.asarray(x).shape, np.asarray(x).dtype)
        for x in ps.trace_args(None)
    )
    capture("pallas_solve", _PATHS["pallas_solve"], ps.fn, *t_args, x64=True)

    # 5. Arena row-scatter: the donated warm-update program.
    from kube_batch_tpu.ops.encode_cache import _scatter_jit

    scatter = _scatter_jit()
    buf = jax.ShapeDtypeStruct(arrays["node_idle"].shape, arrays["node_idle"].dtype)
    idx = jax.ShapeDtypeStruct((4,), np.int64)
    vals = jax.ShapeDtypeStruct((4,) + arrays["node_idle"].shape[1:],
                                arrays["node_idle"].dtype)
    capture("arena_scatter", _PATHS["arena_scatter"],
            lambda b, i, v: scatter(b, i, v), buf, idx, vals, x64=True)
    findings.extend(
        check_donation(scatter, (buf, idx, vals), "arena_scatter",
                       _PATHS["arena_scatter"])
    )
    # 5b. Pipelined mode ping-pongs the same donated scatter across two
    # live device slab sets (encode_cache bank 0/1); donation must hold
    # with a second live buffer in flight too, or double-buffering
    # silently copies and the arena's device footprint doubles per bank.
    findings.extend(
        check_donation(scatter, (buf, idx, vals), "arena_scatter.pingpong",
                       _PATHS["arena_scatter"])
    )
    entries.setdefault("arena_scatter.pingpong", entries["arena_scatter"])

    # 6. Cross-tier signature drift vs the twin.
    for entry, sig in sigs.items():
        if entry == "xla_twin":
            continue
        base = entry.split("@")[0]
        findings.extend(
            check_signature_drift(
                sigs["xla_twin"], sig, "xla_twin", entry,
                _PATHS.get(base, _PATHS["xla_twin"]),
            )
        )

    # 7. Runtime half of P001: one compiled warm cycle of the twin with
    # device-resident inputs must perform no implicit transfers.
    if transfer_check:
        dev = jax.device_put(arrays)
        jax.block_until_ready(twin_fresh(dev))  # compile + warm
        try:
            with jax.transfer_guard("disallow"):
                jax.block_until_ready(twin_fresh(dev))
        except Exception as e:  # noqa: BLE001 -- guard raises host-specific types
            findings.append(
                Finding(
                    path=_PATHS["xla_twin"],
                    line=1,
                    code="KBT-P001",
                    message=(
                        "warm cycle of the XLA twin performs an implicit "
                        f"host transfer under transfer_guard: {e}"
                    ),
                    symbol="xla_twin.transfer_guard",
                )
            )

    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    info = {
        "entries": entries,
        "mesh_sizes": usable,
        "snapshot": {
            k: (list(v.shape), str(v.dtype)) for k, v in sorted(arrays.items())
        },
    }
    return findings, info
