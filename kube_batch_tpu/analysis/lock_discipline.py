"""A1 — lock-discipline analyzer (KBT-L001/L002).

Model: a class owns locks (``threading.Lock/RLock/Condition`` attributes)
and *guarded* attributes. An access to ``self.<guarded>`` is legal when

- it is lexically inside a ``with self.<lock>:`` block for the guarding
  lock (aliases of the same lock are not tracked — one lock, one name);
- or the enclosing method is marked lock-held: name ends in ``_locked``
  or it carries an ``@assume_locked`` decorator
  (kube_batch_tpu.utils.locking);
- or the enclosing method is ``__init__`` / ``__del__`` (construction
  and teardown happen before/after the object is shared).

Guarded attributes come from two sources, merged:

- the committed **seed map** below for the threaded layers that predate
  the annotation convention (cache/cache.py, cache/store.py, server.py,
  recovery/journal.py, utils/workqueue.py);
- a ``#: guarded_by <lock>`` trailing comment anywhere a
  ``self.<attr>`` is assigned (conventionally the ``__init__``
  declaration line) — new code self-documents its discipline and the
  analyzer picks it up with zero configuration.

The check is lexical, not interprocedural: a helper that is only ever
called under the lock must *say so* (``_locked`` suffix or decorator) —
that promise is exactly the documentation the next reader needs, so the
analyzer treating silence as a violation is a feature.

Functions nested inside a ``with`` block inherit its lock context;
callbacks stashed for later execution on another thread are therefore
invisible to this analyzer (keep handlers out of critical sections).
"""

from __future__ import annotations

import ast
import re

from kube_batch_tpu.analysis import Finding, SourceFile

# file -> class -> {guarded attr -> lock attr}. Keep entries for
# attributes whose every post-construction touch must hold the lock;
# attributes that are write-once-at-init (executor handles, config
# ints) stay out.
SEED_GUARDED: dict[str, dict[str, dict[str, str]]] = {
    "kube_batch_tpu/cache/cache.py": {
        "SchedulerCache": {
            "jobs": "_mutex",
            "nodes": "_mutex",
            "queues": "_mutex",
            "priority_classes": "_mutex",
            "_default_priority_class": "_mutex",
            "_default_priority": "_mutex",
        },
        "StoreVolumeBinder": {
            "_pvs": "_lock",
            "_pvcs": "_lock",
            "_classes": "_lock",
            "_assumed": "_lock",
            "_reserved": "_lock",
        },
    },
    "kube_batch_tpu/cache/store.py": {
        "ClusterStore": {
            "_kinds": "_lock",
            "_events": "_lock",
        },
    },
    "kube_batch_tpu/server.py": {
        "WatchHub": {
            "_events": "_cond",
            "_seq": "_cond",
            "_dropped": "_cond",
            "_closed": "_cond",
            "_active": "_cond",
            "_journal_start": "_cond",
        },
    },
    "kube_batch_tpu/recovery/journal.py": {
        "WriteIntentJournal": {
            "_outstanding": "_lock",
            "_next_seq": "_lock",
            "_confirmed_since_compact": "_lock",
            "_fh": "_lock",
        },
    },
    "kube_batch_tpu/streaming.py": {
        # StreamTrigger also self-documents via `#: guarded_by`
        # annotations on its __init__ lines; the seed entry keeps the
        # streaming layer covered even if an annotation is dropped in a
        # refactor. _attached and StreamState stay out: both are
        # streaming-loop-thread-confined by design.
        "StreamTrigger": {
            "_gangs": "_lock",
            "_bound_patches": "_lock",
            "_node_patches": "_lock",
            "_arrivals": "_lock",
            "_queues": "_lock",
            "_stale": "_lock",
            "_stale_reason": "_lock",
        },
    },
    # Post-PR-4 threaded modules (PR 19): each also self-documents via
    # `#: guarded_by` annotations on its __init__ lines — the seed
    # entries below keep KBT-L and KBT-T anchored to one declaration
    # surface even if an annotation is dropped in a refactor.
    "kube_batch_tpu/admission.py": {
        "AdmissionGate": {
            "_last_tick": "_lock",
            "_inflight_keys": "_lock",
        },
    },
    "kube_batch_tpu/obs/fleet.py": {
        "FleetAggregator": {
            "_last_mono": "_lock",
            "_prev_nodes": "_lock",
            "_prev_binds": "_lock",
            "_prev_binds_mono": "_lock",
            "_last_seen": "_lock",
            "_payload_cache": "_lock",
            "last": "_lock",
        },
    },
    "kube_batch_tpu/pipeline.py": {
        "DispatchFence": {
            "_future": "_lock",
            "_dispatch_s": "_lock",
            "_dispatch_t0": "_lock",
            "_dispatch_t1": "_lock",
            "_overlap_fresh": "_lock",
            "last_overlap_fraction": "_lock",
            "degraded_reason": "_lock",
        },
    },
    "kube_batch_tpu/federation.py": {
        "ShardSlotManager": {
            "_owned": "_lock",
            "_adoption_order": "_lock",
            "_reclaiming": "_lock",
            "_last_conflicts": "_lock",
        },
    },
    "kube_batch_tpu/cache/backend.py": {
        "LoopbackBackend": {
            "_mirror": "_lock",
            "_cursor": "_lock",
            "_synced": "_lock",
            "_store_version": "_lock",
            "_last_pump_ok": "_lock",
        },
    },
    "kube_batch_tpu/recovery/watch_client.py": {
        "ResilientWatcher": {
            "mirror": "_lock",
            "_rv": "_lock",
            "_last_sync": "_lock",
            "_last_relist": "_lock",
        },
    },
    "kube_batch_tpu/utils/workqueue.py": {
        "RateLimitingQueue": {
            "_heap": "_cond",
            "_items": "_cond",
            "_pending": "_cond",
            "_processing": "_cond",
            "_dirty": "_cond",
            "_failures": "_cond",
            "_seq": "_cond",
            "_shutdown": "_cond",
        },
    },
}

_ANNOT_RE = re.compile(r"#:\s*guarded_by\s+(?P<lock>[A-Za-z_][A-Za-z0-9_]*)")
_SELF_ATTR_RE = re.compile(r"self\.(?P<attr>[A-Za-z_][A-Za-z0-9_]*)")

_EXEMPT_METHODS = ("__init__", "__del__")


def _annotated_guards(sf: SourceFile) -> dict[str, dict[str, str]]:
    """class -> {attr -> lock} from `#: guarded_by <lock>` comments."""
    line_guard: dict[int, str] = {}
    for i, line in enumerate(sf.lines, 1):
        m = _ANNOT_RE.search(line)
        if m:
            line_guard[i] = m.group("lock")
    if not line_guard:
        return {}
    out: dict[str, dict[str, str]] = {}
    for cls in ast.walk(sf.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                continue
            lock = line_guard.get(node.lineno)
            if lock is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.setdefault(cls.name, {})[t.attr] = lock
    return out


def _class_locks(cls: ast.ClassDef) -> set[str]:
    """Attrs assigned a threading lock/condition anywhere in the class."""
    locks: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name in ("Lock", "RLock", "Condition"):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        locks.add(t.attr)
    return locks


def _is_assume_locked(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else (
            d.id if isinstance(d, ast.Name) else ""
        )
        if name == "assume_locked":
            return True
    return False


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking the set of locks lexically held."""

    def __init__(
        self,
        sf: SourceFile,
        cls: str,
        method: str,
        guards: dict[str, str],
        findings: list[Finding],
    ) -> None:
        self.sf = sf
        self.cls = cls
        self.method = method
        self.guards = guards
        self.findings = findings
        self.held: list[str] = []
        self.reported: set[tuple[int, str]] = set()

    def _with_locks(self, node: ast.With) -> list[str]:
        acquired = []
        for item in node.items:
            e = item.context_expr
            if (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and e.attr in self.guards.values()
            ):
                acquired.append(e.attr)
        return acquired

    def visit_With(self, node: ast.With) -> None:
        # context expressions evaluate before the locks are held
        for item in node.items:
            self.visit(item.context_expr)
        acquired = self._with_locks(node)
        self.held.extend(acquired)
        for item in node.items:
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            lock = self.guards.get(node.attr)
            if lock is not None and lock not in self.held:
                key = (node.lineno, node.attr)
                if key not in self.reported and not self._noqa(node.lineno):
                    self.reported.add(key)
                    self.findings.append(
                        Finding(
                            self.sf.path,
                            node.lineno,
                            "KBT-L001",
                            f"self.{node.attr} is guarded by self.{lock} but "
                            f"accessed in {self.cls}.{self.method} without it "
                            "(wrap in `with`, or mark the method _locked/"
                            "@assume_locked if every caller holds it)",
                            symbol=f"{self.cls}.{self.method}.{node.attr}",
                        )
                    )
        self.generic_visit(node)

    def _noqa(self, lineno: int) -> bool:
        lines = self.sf.lines
        return 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]


def analyze(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        seed = SEED_GUARDED.get(sf.path, {})
        annotated = _annotated_guards(sf)
        if not seed and not annotated:
            continue
        for cls in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = dict(seed.get(cls.name, {}))
            guards.update(annotated.get(cls.name, {}))
            if not guards:
                continue
            locks = _class_locks(cls)
            for attr, lock in sorted(guards.items()):
                if lock not in locks:
                    findings.append(
                        Finding(
                            sf.path,
                            cls.lineno,
                            "KBT-L002",
                            f"{cls.name}.{attr} declared guarded by "
                            f"self.{lock}, but no threading.Lock/RLock/"
                            f"Condition is ever assigned to self.{lock} "
                            "in this class",
                            symbol=f"{cls.name}.{attr}",
                        )
                    )
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if meth.name in _EXEMPT_METHODS or meth.name.endswith("_locked"):
                    continue
                if _is_assume_locked(meth):
                    continue
                checker = _MethodChecker(sf, cls.name, meth.name, guards, findings)
                for stmt in meth.body:
                    checker.visit(stmt)
    return findings


def explain_convention() -> str:
    """One paragraph for docs/--explain surfaces."""
    return (
        "Declare guards with `#: guarded_by <lock>` on the attribute's "
        "__init__ assignment line (or the seed map for pre-existing "
        "layers). Access them only inside `with self.<lock>`; helpers "
        "called with the lock held are named *_locked or decorated "
        "@assume_locked (kube_batch_tpu.utils.locking)."
    )
