"""A3 — registry-consistency analyzer (KBT-R001..R012).

Three registries grew to dozens of names across PR 1-3, each previously
checked only by grep and luck:

- **fault points**: the literal first argument of every
  ``faults.should_fire(...)`` / ``registry.arm(...)`` call must exist in
  ``faults.POINTS`` (R001), and every ``POINTS`` entry must have a call
  site (R002) — an unfired point is a drill that silently injects
  nothing. Dynamic names built from f-strings with constant fragments
  (``f"{op}.write"``) are matched as wildcards: the pattern must match
  at least one registered point, and any point it matches counts as
  fired.
- **metrics**: every ``metrics.<name>`` attribute touched in package
  code must be defined at module level of ``metrics/__init__.py``
  (R003) — most metering sits in ``except`` blocks, so a typo is an
  AttributeError on exactly the path that only runs during an outage.
- **env knobs**: every ``KBT_*`` variable the package reads must have a
  row in the deployment runbook's environment table (R004), and every
  documented row must still be read somewhere (R005). Reads are
  collected from ``os.environ`` get/subscript/setdefault/pop calls,
  from ``*env*``-named helper calls with a literal ``KBT_*`` first
  argument (``_env_int("KBT_...", d)``), and from module-level
  ALL-CAPS constants bound to a ``KBT_*`` string (the
  ``ENV = "KBT_..."`` indirection in mutation_detector).
- **state_seq bumps**: every session mutation must advance the counter
  through ``Session.bump_state()`` (R006) — a raw ``state_seq += 1``
  (or assignment) outside that one hook is a mutation the streaming
  dirty tracker and state_seq-keyed score memos cannot observe.
- **span names**: the literal first argument of every ``obs.span(...)``
  / ``obs.emit(...)`` call must be declared in ``obs.SPAN_NAMES``
  (R007) — a typo'd name silently forks the trace tree — and every
  declared name must have a call site (R008).
- **debug endpoints**: every ``/debug/*`` route literal in server.py
  must be declared in ``obs.DEBUG_ENDPOINTS`` and vice versa (R009 —
  an undeclared route escapes the contract, a declared-but-unserved
  one 404s), and every declared endpoint needs a row in the deployment
  runbook's endpoint table, with no dead documented rows (R010).
- **metric help text**: every module-level Counter/Histogram/Gauge in
  ``metrics/__init__.py`` must carry non-empty help text and appear in
  ``render_prometheus_text``'s families list, and every families entry
  must be a declared metric (R011) — a helpless or unlisted metric is
  a series Prometheus scrapes without ``# HELP``/``# TYPE`` or never
  sees at all.
- **SLO kind registry**: every kind in ``obs.SLOAccountant.KINDS`` must
  have a gauge entry in BOTH ``metrics._SLO_GAUGES`` (per-shard publish)
  and ``metrics._FLEET_SLO_GAUGES`` (fleet aggregation), and every key
  of those dicts must be a declared kind (R012) — a kind without a
  gauge entry silently never publishes its quantiles, and a gauge keyed
  to no kind is a family the exposition carries but nothing ever sets.
"""

from __future__ import annotations

import ast
import os
import re
from fnmatch import fnmatchcase
from typing import Optional

from kube_batch_tpu.analysis import Finding, SourceFile

FAULTS_MODULE = "kube_batch_tpu/faults/__init__.py"
METRICS_MODULE = "kube_batch_tpu/metrics/__init__.py"
OBS_MODULE = "kube_batch_tpu/obs/__init__.py"
SERVER_MODULE = "kube_batch_tpu/server.py"
RUNBOOK = "deployment/README.md"

_ENV_RE = re.compile(r"^KBT_[A-Z0-9_]+$")
_DOC_ENV_RE = re.compile(r"`(KBT_[A-Z0-9_]+)`")
_DEBUG_PATH_RE = re.compile(r"^/debug/[a-z0-9_/-]+$")
_DOC_DEBUG_RE = re.compile(r"`(/debug/[a-z0-9_/-]+)`")


def _attr_root(node: ast.expr) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


# -- fault points ------------------------------------------------------------


def _declared_points(files: list[SourceFile]) -> dict[str, int]:
    """point -> lineno of its POINTS element."""
    for sf in files:
        if sf.path != FAULTS_MODULE:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "POINTS":
                        v = node.value
                        if isinstance(v, (ast.Tuple, ast.List)):
                            return {
                                e.value: e.lineno
                                for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                            }
    return {}


def _point_arg(call: ast.Call) -> Optional[tuple[str, bool]]:
    """(name-or-pattern, is_pattern) for the call's first argument."""
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value, False
    if isinstance(a, ast.JoinedStr):
        parts = []
        for v in a.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        pattern = "".join(parts)
        return pattern, True
    return None  # a variable — not statically checkable


def _check_fault_points(files: list[SourceFile], findings: list[Finding]) -> None:
    declared = _declared_points(files)
    if not declared:
        return
    fired: set[str] = set()
    for sf in files:
        if sf.path == FAULTS_MODULE:
            continue  # the registry's own wrapper/arm plumbing
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name not in ("should_fire", "arm"):
                continue
            got = _point_arg(node)
            if got is None:
                continue
            point, is_pattern = got
            if is_pattern:
                hits = [p for p in declared if fnmatchcase(p, point)]
                if hits:
                    fired.update(hits)
                else:
                    findings.append(
                        Finding(
                            sf.path, node.lineno, "KBT-R001",
                            f"dynamic fault point pattern {point!r} matches "
                            "no entry in faults.POINTS",
                            symbol=f"point:{point}",
                        )
                    )
            elif point in declared:
                fired.add(point)
            else:
                findings.append(
                    Finding(
                        sf.path, node.lineno, "KBT-R001",
                        f"fault point {point!r} is not registered in "
                        "faults.POINTS — arm() would reject it, the drill "
                        "can never fire",
                        symbol=f"point:{point}",
                    )
                )
    for point, lineno in sorted(declared.items()):
        if point not in fired:
            findings.append(
                Finding(
                    FAULTS_MODULE, lineno, "KBT-R002",
                    f"fault point {point!r} is registered but no "
                    "should_fire()/arm() call site fires it — drills "
                    "arming it inject nothing",
                    symbol=f"point:{point}",
                )
            )


# -- metrics -----------------------------------------------------------------


def _metrics_exports(files: list[SourceFile]) -> set[str]:
    names: set[str] = set()
    for sf in files:
        if sf.path != METRICS_MODULE:
            continue
        mod = sf.tree
        assert isinstance(mod, ast.Module)
        for node in mod.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                names.add(node.target.id)
    return names


def _metrics_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to the metrics module in this file."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "kube_batch_tpu":
                for a in node.names:
                    if a.name == "metrics":
                        aliases.add(a.asname or a.name)
            elif node.module == "kube_batch_tpu.metrics":
                continue  # direct symbol imports resolve at import time
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "kube_batch_tpu.metrics" and a.asname:
                    aliases.add(a.asname)
    return aliases


def _check_metrics(files: list[SourceFile], findings: list[Finding]) -> None:
    exported = _metrics_exports(files)
    if not exported:
        return
    for sf in files:
        if sf.path == METRICS_MODULE:
            continue
        aliases = _metrics_aliases(sf.tree)
        if not aliases:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in aliases
                and node.attr not in exported
            ):
                findings.append(
                    Finding(
                        sf.path, node.lineno, "KBT-R003",
                        f"metrics.{node.attr} is not declared in "
                        "metrics/__init__.py — AttributeError on the "
                        "(likely failure-only) path that reaches it",
                        symbol=f"metric:{node.attr}",
                    )
                )


# -- state_seq bump discipline -----------------------------------------------

SESSION_MODULE = "kube_batch_tpu/framework/session.py"
_BUMP_OWNERS = ("bump_state", "__init__")


def _check_state_seq(files: list[SourceFile], findings: list[Finding]) -> None:
    """KBT-R006: no raw ``<obj>.state_seq += 1`` / ``= n`` bump sites
    outside Session.bump_state (and the counter's __init__)."""
    for sf in files:
        owners: dict[int, str] = {}  # lineno -> enclosing function name
        if sf.path == SESSION_MODULE:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        if hasattr(sub, "lineno"):
                            owners.setdefault(sub.lineno, node.name)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.Assign):
                # `x.state_seq = y.state_seq` is a memo of the observed
                # counter (encode_cache task blocks), not a bump.
                if (
                    isinstance(node.value, ast.Attribute)
                    and node.value.attr == "state_seq"
                ):
                    continue
                targets = node.targets
            else:
                continue
            for t in targets:
                if not (isinstance(t, ast.Attribute) and t.attr == "state_seq"):
                    continue
                if owners.get(node.lineno) in _BUMP_OWNERS:
                    continue
                findings.append(
                    Finding(
                        sf.path, node.lineno, "KBT-R006",
                        "raw state_seq bump outside Session.bump_state() — "
                        "the streaming dirty tracker and state_seq-keyed "
                        "score memos cannot observe this mutation; call "
                        "bump_state() instead",
                        symbol="state_seq",
                    )
                )


# -- span names + debug endpoints (kube_batch_tpu.obs, R007-R010) ------------


def _declared_str_tuple(
    files: list[SourceFile], module: str, name: str
) -> dict[str, int]:
    """entry -> lineno of ``name = ("...", ...)`` at ``module`` top level."""
    for sf in files:
        if sf.path != module:
            continue
        mod = sf.tree
        if not isinstance(mod, ast.Module):
            continue
        for node in mod.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        v = node.value
                        if isinstance(v, (ast.Tuple, ast.List)):
                            return {
                                e.value: e.lineno
                                for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                            }
    return {}


def _check_span_names(files: list[SourceFile], findings: list[Finding]) -> None:
    declared = _declared_str_tuple(files, OBS_MODULE, "SPAN_NAMES")
    if not declared:
        return
    used: set[str] = set()
    for sf in files:
        if sf.path == OBS_MODULE:
            continue  # the registry's own span/emit plumbing
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name not in ("span", "emit"):
                continue
            if not node.args:
                continue
            a = node.args[0]
            if not (isinstance(a, ast.Constant) and isinstance(a.value, str)):
                continue  # a variable (or m.span(1)) — not checkable
            span_name = a.value
            if name == "span" and isinstance(fn, ast.Attribute) and _attr_root(
                fn
            ) not in ("obs", ""):
                continue  # e.g. some_match.span("x") on a non-obs object
            if span_name in declared:
                used.add(span_name)
            else:
                findings.append(
                    Finding(
                        sf.path, node.lineno, "KBT-R007",
                        f"span name {span_name!r} is not declared in "
                        "obs.SPAN_NAMES — an undeclared name silently "
                        "forks the trace tree past every tree check",
                        symbol=f"span:{span_name}",
                    )
                )
    for span_name, lineno in sorted(declared.items()):
        if span_name not in used:
            findings.append(
                Finding(
                    OBS_MODULE, lineno, "KBT-R008",
                    f"span name {span_name!r} is declared in SPAN_NAMES but "
                    "no obs.span()/obs.emit() call site opens it — the "
                    "declared trace shape and the real one have diverged",
                    symbol=f"span:{span_name}",
                )
            )


def _server_debug_routes(files: list[SourceFile]) -> dict[str, int]:
    """route -> lineno of every exact ``/debug/...`` literal in server.py."""
    out: dict[str, int] = {}
    for sf in files:
        if sf.path != SERVER_MODULE:
            continue
        for node in ast.walk(sf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _DEBUG_PATH_RE.match(node.value)
            ):
                out.setdefault(node.value, node.lineno)
    return out


def _documented_debug(repo: str, runbook: str) -> Optional[dict[str, int]]:
    path = os.path.join(repo, runbook)
    if not os.path.exists(path):
        return None
    out: dict[str, int] = {}
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            if not line.lstrip().startswith("|"):
                continue
            m = _DOC_DEBUG_RE.search(line)
            if m:
                out.setdefault(m.group(1), lineno)
    return out


def _check_debug_endpoints(
    files: list[SourceFile], repo: str, runbook: str, findings: list[Finding]
) -> None:
    declared = _declared_str_tuple(files, OBS_MODULE, "DEBUG_ENDPOINTS")
    if not declared:
        return
    served = _server_debug_routes(files)
    for route, lineno in sorted(served.items()):
        if route not in declared:
            findings.append(
                Finding(
                    SERVER_MODULE, lineno, "KBT-R009",
                    f"route {route!r} is served but not declared in "
                    "obs.DEBUG_ENDPOINTS — the debug surface contract and "
                    "the server have diverged",
                    symbol=f"endpoint:{route}",
                )
            )
    for route, lineno in sorted(declared.items()):
        if route not in served:
            findings.append(
                Finding(
                    OBS_MODULE, lineno, "KBT-R009",
                    f"endpoint {route!r} is declared in DEBUG_ENDPOINTS but "
                    "server.py serves no such route — it would 404",
                    symbol=f"endpoint:{route}",
                )
            )
    documented = _documented_debug(repo, runbook)
    if documented is None:
        return
    for route, lineno in sorted(declared.items()):
        if route not in documented:
            findings.append(
                Finding(
                    OBS_MODULE, lineno, "KBT-R010",
                    f"endpoint {route!r} has no row in the deployment "
                    f"runbook's endpoint table ({runbook})",
                    symbol=f"endpoint:{route}",
                )
            )
    for route, lineno in sorted(documented.items()):
        if route not in declared:
            findings.append(
                Finding(
                    runbook, lineno, "KBT-R010",
                    f"endpoint {route!r} is documented but not declared in "
                    "obs.DEBUG_ENDPOINTS — the runbook row is dead",
                    symbol=f"endpoint:{route}",
                )
            )


# -- metric help text + exposition families (R011) ---------------------------

_METRIC_CLASSES = ("Counter", "Histogram", "Gauge")


def _metric_decls(files: list[SourceFile]) -> dict[str, tuple[int, bool]]:
    """name -> (lineno, has_help) for every module-level metric object
    assignment in metrics/__init__.py."""
    out: dict[str, tuple[int, bool]] = {}
    for sf in files:
        if sf.path != METRICS_MODULE:
            continue
        mod = sf.tree
        if not isinstance(mod, ast.Module):
            continue
        for node in mod.body:
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            cls = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else ""
            )
            if cls not in _METRIC_CLASSES:
                continue
            args = node.value.args
            help_arg = args[1] if len(args) > 1 else None
            for kw in node.value.keywords:
                if kw.arg == "help_text":
                    help_arg = kw.value
            has_help = (
                isinstance(help_arg, ast.Constant)
                and isinstance(help_arg.value, str)
                and bool(help_arg.value.strip())
            ) or isinstance(help_arg, ast.JoinedStr)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = (node.lineno, has_help)
    return out


def _exposition_families(files: list[SourceFile]) -> dict[str, int]:
    """name -> lineno for every entry of the ``families = [...]`` list
    inside render_prometheus_text."""
    out: dict[str, int] = {}
    for sf in files:
        if sf.path != METRICS_MODULE:
            continue
        for node in ast.walk(sf.tree):
            if not (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "render_prometheus_text"
            ):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Name)
                            and t.id == "families"
                            and isinstance(sub.value, (ast.List, ast.Tuple))
                        ):
                            for e in sub.value.elts:
                                if isinstance(e, ast.Name):
                                    out.setdefault(e.id, e.lineno)
    return out


def _check_metric_help(files: list[SourceFile], findings: list[Finding]) -> None:
    declared = _metric_decls(files)
    if not declared:
        return
    families = _exposition_families(files)
    for name, (lineno, has_help) in sorted(declared.items()):
        if not has_help:
            findings.append(
                Finding(
                    METRICS_MODULE, lineno, "KBT-R011",
                    f"metric {name!r} is declared without help text — its "
                    "exposition would carry an empty # HELP line",
                    symbol=f"metric:{name}",
                )
            )
        if families and name not in families:
            findings.append(
                Finding(
                    METRICS_MODULE, lineno, "KBT-R011",
                    f"metric {name!r} is declared but missing from "
                    "render_prometheus_text's families list — Prometheus "
                    "never sees the series",
                    symbol=f"metric:{name}",
                )
            )
    for name, lineno in sorted(families.items()):
        if name not in declared:
            findings.append(
                Finding(
                    METRICS_MODULE, lineno, "KBT-R011",
                    f"families entry {name!r} is not a module-level metric "
                    "declaration — the exposition renders an unregistered "
                    "object",
                    symbol=f"metric:{name}",
                )
            )


# -- SLO kind registry (R012) ------------------------------------------------

_SLO_GAUGE_MAPS = ("_SLO_GAUGES", "_FLEET_SLO_GAUGES")


def _slo_kinds(files: list[SourceFile]) -> dict[str, int]:
    """kind -> lineno of the ``KINDS = (...)`` tuple inside the
    SLOAccountant class body in obs/__init__.py."""
    for sf in files:
        if sf.path != OBS_MODULE:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.ClassDef) and node.name == "SLOAccountant"):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == "KINDS":
                            v = stmt.value
                            if isinstance(v, (ast.Tuple, ast.List)):
                                return {
                                    e.value: e.lineno
                                    for e in v.elts
                                    if isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                }
    return {}


def _slo_gauge_keys(files: list[SourceFile], map_name: str) -> dict[str, int]:
    """key -> lineno for the ``map_name = {...}`` dict literal at module
    top level of metrics/__init__.py."""
    for sf in files:
        if sf.path != METRICS_MODULE:
            continue
        mod = sf.tree
        if not isinstance(mod, ast.Module):
            continue
        for node in mod.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == map_name:
                        return {
                            k.value: k.lineno
                            for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                        }
    return {}


def _check_slo_kind_registry(
    files: list[SourceFile], findings: list[Finding]
) -> None:
    kinds = _slo_kinds(files)
    if not kinds:
        return
    for map_name in _SLO_GAUGE_MAPS:
        keys = _slo_gauge_keys(files, map_name)
        if not keys:
            continue
        for kind, lineno in sorted(kinds.items()):
            if kind not in keys:
                findings.append(
                    Finding(
                        OBS_MODULE, lineno, "KBT-R012",
                        f"SLO kind {kind!r} has no gauge entry in "
                        f"metrics.{map_name} — its quantiles are tracked "
                        "but never published to the exposition",
                        symbol=f"slo_kind:{kind}",
                    )
                )
        for key, lineno in sorted(keys.items()):
            if key not in kinds:
                findings.append(
                    Finding(
                        METRICS_MODULE, lineno, "KBT-R012",
                        f"metrics.{map_name} key {key!r} is not a kind in "
                        "obs.SLOAccountant.KINDS — the gauge family is "
                        "registered but nothing ever sets it",
                        symbol=f"slo_kind:{key}",
                    )
                )


# -- env knobs ---------------------------------------------------------------


def _env_reads(files: list[SourceFile]) -> dict[str, tuple[str, int]]:
    """var -> (path, line) of one read site."""
    reads: dict[str, tuple[str, int]] = {}

    def note(var: str, sf: SourceFile, lineno: int) -> None:
        reads.setdefault(var, (sf.path, lineno))

    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                fname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else ""
                )
                env_call = False
                if isinstance(fn, ast.Attribute):
                    chain = ast.dump(fn.value) if fn.value else ""
                    env_call = "environ" in chain and fname in (
                        "get", "pop", "setdefault", "__getitem__"
                    )
                env_call = env_call or "env" in fname.lower() or fname == "getenv"
                if env_call and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Constant) and isinstance(a.value, str):
                        if _ENV_RE.match(a.value):
                            note(a.value, sf, node.lineno)
            elif isinstance(node, ast.Subscript):
                v = node.value
                if isinstance(v, ast.Attribute) and v.attr == "environ":
                    s = node.slice
                    if isinstance(s, ast.Constant) and isinstance(s.value, str):
                        if _ENV_RE.match(s.value):
                            note(s.value, sf, node.lineno)
        # ALL-CAPS module constants bound to a KBT_* string (indirection)
        mod = sf.tree
        if isinstance(mod, ast.Module):
            for node in mod.body:
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
                    val = node.value.value
                    if isinstance(val, str) and _ENV_RE.match(val):
                        for t in node.targets:
                            if isinstance(t, ast.Name) and t.id.isupper():
                                note(val, sf, node.lineno)
    return reads


def _documented_env(repo: str, runbook: str) -> Optional[dict[str, int]]:
    """var -> line in the runbook env table; None when the runbook is
    absent (partial checkouts skip the doc cross-check, loudly at the
    CLI layer)."""
    path = os.path.join(repo, runbook)
    if not os.path.exists(path):
        return None
    out: dict[str, int] = {}
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            if not line.lstrip().startswith("|"):
                continue
            m = _DOC_ENV_RE.search(line.split("|")[1] if line.count("|") > 1 else line)
            if m:
                out.setdefault(m.group(1), lineno)
    return out


def _check_env(
    files: list[SourceFile], repo: str, runbook: str, findings: list[Finding]
) -> None:
    documented = _documented_env(repo, runbook)
    if documented is None:
        return
    reads = _env_reads(files)
    for var, (path, lineno) in sorted(reads.items()):
        if var not in documented:
            findings.append(
                Finding(
                    path, lineno, "KBT-R004",
                    f"{var} is read here but has no row in the deployment "
                    f"runbook's environment table ({runbook})",
                    symbol=f"env:{var}",
                )
            )
    for var, lineno in sorted(documented.items()):
        if var not in reads:
            findings.append(
                Finding(
                    runbook, lineno, "KBT-R005",
                    f"{var} is documented in the environment table but no "
                    "package code reads it — the knob is dead",
                    symbol=f"env:{var}",
                )
            )


def analyze(
    files: list[SourceFile],
    repo: Optional[str] = None,
    runbook: Optional[str] = None,
) -> list[Finding]:
    from kube_batch_tpu.analysis import repo_root

    repo = repo or repo_root()
    runbook = runbook or RUNBOOK
    findings: list[Finding] = []
    _check_fault_points(files, findings)
    _check_metrics(files, findings)
    _check_state_seq(files, findings)
    _check_span_names(files, findings)
    _check_debug_endpoints(files, repo, runbook, findings)
    _check_metric_help(files, findings)
    _check_env(files, repo, runbook, findings)
    _check_slo_kind_registry(files, findings)
    return findings
