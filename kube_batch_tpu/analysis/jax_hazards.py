"""A2 — JAX hazard analyzer (KBT-J001..J004).

Scope, by check:

- **J001/J002/J003** run on ``ops/`` and ``parallel/`` (the solve
  kernels), inside *jit-reachable* functions only. A function is
  jit-reachable when it
    * carries a ``@jax.jit`` / ``@pjit`` / ``@partial(jax.jit, ...)``
      decorator, or
    * is passed by name into ``jax.jit`` / ``shard_map`` /
      ``pl.pallas_call`` / ``lax.while_loop|fori_loop|scan|cond|switch``
      / ``vmap`` / ``pmap``, or
    * is lexically nested inside a jit-reachable function, or
    * is a same-module function *called by name* from a jit-reachable
      function (one-module call closure — the kernels are factored as
      module-level helpers invoked from the jitted entries).
  Host work belongs in the pack/encode layers outside these functions;
  inside them, a host sync stalls the device pipeline per trace and a
  tracer truth-test is a latent ConcretizationTypeError on paths the
  parity tests never walk.

- **J004** runs on ``plugins/`` and ``api/`` (minus ``numerics.py``
  itself): raw ``np/jnp.float32|float64`` dtype literals there bypass
  the comparison-dtype policy that keeps the serial oracle bit-identical
  to the f32 device kernels. Identity/equality *comparisons* against a
  dtype literal are exempt — they consult the policy rather than bypass
  it (``if comparison_dtype() is np.float64``).

Known blind spots, deliberate: reachability does not cross modules, and
closures stashed under a ``with``/callback boundary are attributed to
their lexical position. Both trade recall for a zero-false-positive-ish
default the gate can enforce; the chaos/parity suites cover the rest.
"""

from __future__ import annotations

import ast
from typing import Optional, Union

from kube_batch_tpu.analysis import Finding, SourceFile

_FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef]

# call targets whose function-typed arguments become jit-reachable
_TRACING_CALLS = {
    "jit", "pjit", "shard_map", "pallas_call", "while_loop", "fori_loop",
    "scan", "cond", "switch", "vmap", "pmap", "checkpoint", "remat",
    "named_call", "custom_jvp", "custom_vjp", "when",
}
# attribute roots whose calls are device-side (not host syncs)
_DEVICE_ROOTS = {"jnp", "lax", "pl", "plgpu", "pltpu", "jax"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_HOST_SYNC_NP = {"asarray", "array", "ascontiguousarray"}
_SCALAR_CASTS = {"float", "int", "bool"}
_DTYPE_LITERALS = {"float32", "float64"}
_DTYPE_ROOTS = {"np", "jnp", "numpy"}


def _callable_name(fn: ast.expr) -> str:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _attr_root(node: ast.expr) -> str:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def _jit_decorated(fn: _FuncDef) -> bool:
    for dec in fn.decorator_list:
        target = dec
        if isinstance(dec, ast.Call):
            # @partial(jax.jit, ...) / @jax.jit(...)
            name = _callable_name(dec.func)
            if name == "partial" and dec.args:
                target = dec.args[0]
            else:
                target = dec.func
        if _callable_name(target) in ("jit", "pjit"):
            return True
    return False


def _static_argnames(tree: ast.AST) -> set[str]:
    """Every name listed in any static_argnames/static_argnums-adjacent
    tuple in the module — parameters by these names are compile-time
    constants, so truth tests on them are legal anywhere."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.keyword) and node.arg == "static_argnames":
            v = node.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return names


def _collect_jit_roots(tree: ast.AST) -> set[str]:
    """Names of functions passed into tracing calls or jit-decorated."""
    roots: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _jit_decorated(node):
                roots.add(node.name)
        elif isinstance(node, ast.Call):
            if _callable_name(node.func) in _TRACING_CALLS:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    target = arg
                    if isinstance(arg, ast.Call) and _callable_name(arg.func) == "partial":
                        target = arg.args[0] if arg.args else arg
                    if isinstance(target, ast.Name):
                        roots.add(target.id)
    return roots


def _index_functions(tree: ast.AST) -> dict[str, list[_FuncDef]]:
    """name -> defs (module-level and nested share the namespace; shadowing
    is resolved pessimistically by checking every def of the name)."""
    out: dict[str, list[_FuncDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _called_names(fn: _FuncDef) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            names.add(node.func.id)
    return names


def _jit_scope_functions(tree: ast.AST) -> list[_FuncDef]:
    """Transitive closure: roots + same-module functions they call, plus
    every function nested inside any of those."""
    by_name = _index_functions(tree)
    work = sorted(_collect_jit_roots(tree))
    reach: list[_FuncDef] = []
    seen: set[int] = set()
    while work:
        name = work.pop()
        for fn in by_name.get(name, []):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            reach.append(fn)
            for callee in sorted(_called_names(fn)):
                if callee in by_name and any(
                    id(d) not in seen for d in by_name[callee]
                ):
                    work.append(callee)
    # nested defs inherit jit scope
    out: list[_FuncDef] = []
    for fn in reach:
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) not in seen or node is fn:
                    out.append(node)
    return out


class _ScopeChecker(ast.NodeVisitor):
    """Hazard checks inside ONE jit-reachable function (its nested defs
    are checked by their own _ScopeChecker; skip them here)."""

    def __init__(
        self,
        sf: SourceFile,
        fn: _FuncDef,
        statics: set[str],
        findings: list[Finding],
    ) -> None:
        self.sf = sf
        self.fn = fn
        self.findings = findings
        a = fn.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        self.traced = {p for p in params if p not in statics and p != "self"}
        self._root = True

    def _flag(self, node: ast.AST, code: str, msg: str, sym: str) -> None:
        lines = self.sf.lines
        if 0 < node.lineno <= len(lines) and "noqa" in lines[node.lineno - 1]:
            return
        self.findings.append(
            Finding(self.sf.path, node.lineno, code, msg,
                    symbol=f"{self.fn.name}.{sym}")
        )

    # nested defs get their own checker
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self._root:
            self._root = False
            self.generic_visit(node)
        # else: skip body; the nested def is in the scope list itself

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def run(self) -> None:
        self.visit(self.fn)

    # -- J001 / J003 --------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = _callable_name(fn)
        if isinstance(fn, ast.Name):
            if name == "print":
                self._flag(
                    node, "KBT-J003",
                    f"bare print() inside jit-reachable `{self.fn.name}` "
                    "(runs at trace time; use jax.debug.print)",
                    "print",
                )
            elif name in _SCALAR_CASTS and node.args and not isinstance(
                node.args[0], ast.Constant
            ):
                self._flag(
                    node, "KBT-J001",
                    f"{name}() on a non-constant inside jit-reachable "
                    f"`{self.fn.name}` forces a host sync (or a tracer "
                    "concretization error)",
                    name,
                )
        elif isinstance(fn, ast.Attribute):
            root = _attr_root(fn)
            if fn.attr in _HOST_SYNC_METHODS:
                self._flag(
                    node, "KBT-J001",
                    f".{fn.attr}() inside jit-reachable `{self.fn.name}` "
                    "is a device->host sync",
                    fn.attr,
                )
            elif root in ("np", "numpy") and fn.attr in _HOST_SYNC_NP:
                self._flag(
                    node, "KBT-J001",
                    f"np.{fn.attr} inside jit-reachable `{self.fn.name}` "
                    "materializes on host (use jnp)",
                    f"np.{fn.attr}",
                )
            elif root == "jax" and fn.attr == "device_get":
                self._flag(
                    node, "KBT-J001",
                    f"jax.device_get inside jit-reachable `{self.fn.name}` "
                    "is a device->host sync",
                    "device_get",
                )
        self.generic_visit(node)

    # -- J002 ---------------------------------------------------------------

    def _test_is_traced(self, test: ast.expr) -> Optional[str]:
        """A reason string when the truth-tested expression involves
        traced data; None when it looks host-static. Static-at-trace
        subtrees are pruned: identity tests (``x is None`` selects the
        fresh/resume program shape), ``.dtype`` attribute chains and
        ``jnp.issubdtype`` (dtype metadata is compile-time)."""

        def scan(node: ast.expr) -> Optional[str]:
            if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                return None  # identity: structural/static dispatch
            if isinstance(node, ast.Attribute) and node.attr == "dtype":
                return None  # dtype metadata is static under tracing
            if isinstance(node, ast.Call):
                name = _callable_name(node.func)
                root = _attr_root(node.func)
                if name in ("issubdtype", "isinstance", "len"):
                    return None
                if root in ("jnp", "lax"):
                    return f"result of {root}.{name}"
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in self.traced:
                    return f"parameter `{node.id}`"
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    why = scan(child)
                    if why is not None:
                        return why
            return None

        return scan(test)

    def _check_test(self, node: ast.AST, test: ast.expr, kind: str) -> None:
        why = self._test_is_traced(test)
        if why is not None:
            self._flag(
                node, "KBT-J002",
                f"Python {kind} on a traced value ({why}) inside "
                f"jit-reachable `{self.fn.name}` — use lax.cond/jnp.where "
                "or make it a static argument",
                f"{kind}:{why}",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node, node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node, node.test, "while")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node, node.test, "conditional expression")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_test(node, node.test, "assert")
        self.generic_visit(node)


# -- J004 --------------------------------------------------------------------


class _DtypeChecker(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, findings: list[Finding]) -> None:
        self.sf = sf
        self.findings = findings
        self.scope: list[str] = []
        self.exempt: set[int] = set()  # ids of literals inside identity checks

    def visit_Compare(self, node: ast.Compare) -> None:
        # `x is np.float64` / `x == np.float32` consult the policy
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.Eq, ast.NotEq)) for op in node.ops):
            for operand in [node.left] + node.comparators:
                if self._is_dtype_literal(operand):
                    self.exempt.add(id(operand))
        self.generic_visit(node)

    @staticmethod
    def _is_dtype_literal(node: ast.expr) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr in _DTYPE_LITERALS
            and _attr_root(node) in _DTYPE_ROOTS
        )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self._is_dtype_literal(node) and id(node) not in self.exempt:
            lines = self.sf.lines
            if not (0 < node.lineno <= len(lines) and "noqa" in lines[node.lineno - 1]):
                root = _attr_root(node)
                where = ".".join(self.scope) or "<module>"
                self.findings.append(
                    Finding(
                        self.sf.path, node.lineno, "KBT-J004",
                        f"raw {root}.{node.attr} in `{where}` bypasses the "
                        "comparison-dtype policy (api/numerics."
                        "comparison_dtype) — derived quantities computed "
                        "here can disagree with the f32 device kernels on "
                        "sub-ulp ties",
                        symbol=f"{where}.{root}.{node.attr}",
                    )
                )
        self.generic_visit(node)


def analyze(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        in_kernels = sf.path.startswith(
            # recovery/ is jit-adjacent since the takeover path learned to
            # re-enter warm solves (PR 3); encode_cache and sharded_pallas
            # live under ops//parallel/ and are covered by the prefixes.
            ("kube_batch_tpu/ops/", "kube_batch_tpu/parallel/",
             "kube_batch_tpu/recovery/")
        )
        in_policy = sf.path.startswith(
            ("kube_batch_tpu/plugins/", "kube_batch_tpu/api/")
        ) and not sf.path.endswith("numerics.py")
        if in_kernels:
            statics = _static_argnames(sf.tree)
            for fn in _jit_scope_functions(sf.tree):
                _ScopeChecker(sf, fn, statics, findings).run()
        if in_policy:
            _DtypeChecker(sf, findings).visit(sf.tree)
    # one finding per (path, line, code, symbol): nested scopes can
    # enumerate the same def twice
    seen: set[tuple] = set()
    out = []
    for f in findings:
        key = (f.path, f.line, f.code, f.symbol)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
